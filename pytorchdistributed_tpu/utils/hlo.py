"""Compiled-artifact invariants: what a train step's executable looks like.

The regression tripwires the chip can't give us when the TPU tunnel is
down (it wedged for all of rounds 3-4): instead of a throughput number,
assert properties of the COMPILED program that predict throughput —
per-device flops and peak temp memory from XLA's own analyses, and the
collective-op census of the optimized (post-SPMD-partitioning) HLO. Any
change that bloats memory, adds a collective, or changes the op mix fails
against committed numbers in tests/test_compiled_invariants.py on the CPU
sim, no hardware needed. This generalizes the round-4 one-off of
byte-diffing lowered HLO between commits (BASELINE.md "Pallas kernel
unification") into a harness; the committed-number discipline mirrors
bench.py's COMMITTED_BASELINES. Reference analog: the benchmark-as-test
harness at 03_model_parallel.ipynb:403-423 — this is its
works-without-a-chip half.
"""

from __future__ import annotations

import re

# The full XLA collective vocabulary a step can emit. Async pairs
# (`all-reduce-start`/`-done`) count once, as the -start; `-done` and
# fused variants with extra suffixes are excluded by requiring `(` right
# after the op name.
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
    "ragged-all-to-all",
    "collective-broadcast",
)


def collective_counts(hlo_text: str) -> dict[str, int]:
    """Census of collective ops in an HLO module's text, keyed by op name.

    Run it on OPTIMIZED HLO (`compiled.as_text()`): collectives are
    inserted by the SPMD partitioner during compilation, so pre-optimized
    (`lowered.as_text()`) modules show shardings but few/no collectives.
    Zero-count ops are included so equality against a committed dict also
    catches a collective *appearing* where none was."""
    return {
        op: len(re.findall(rf"{op}(?:-start)?\(", hlo_text))
        for op in COLLECTIVE_OPS
    }


# Bytes per element of the HLO shape dtypes a collective can carry.
# Sub-byte types (s4/u4) round up to 1 — they only appear packed in
# exotic programs and a 2x overestimate beats a KeyError census hole.
_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_ARRAY_SHAPE_RE = re.compile(r"([a-z]\d*[a-z0-9]*)\[([\d,]*)\]")

# A collective's defining line: `%name = <shape> <op>(...)` where <shape>
# is an array (`f32[16,8]{1,0}`), a flat tuple (`(f32[8]{0}, f32[8]{0})`),
# or — for variadic async starts — a tuple nesting one level of tuples
# (`((f32[a], f32[b]), (f32[a], f32[b]))`). `-start` counts (the async op
# carries the transfer); `-done` does not (no `(` follows the op stem).
# Longest-first alternation so ragged all-to-alls are not double-counted
# as plain ones.
_COLL_DEF_RE = re.compile(
    r"=\s*(\((?:[^()]|\([^()]*\))*\)|\S+)\s+("
    + "|".join(sorted(COLLECTIVE_OPS, key=len, reverse=True))
    + r")(-start)?\(")

# -start ops whose staging tuple follows the (operand(s), result(s),
# context...) convention — only element [1] is the transferred data.
# all-reduce-start is NOT here: its tuple (when variadic) IS the result
# set, so every element counts.
_START_OPERAND_RESULT = ("all-gather", "collective-permute", "all-to-all",
                         "ragged-all-to-all", "collective-broadcast",
                         "reduce-scatter")


def _split_top_level(tuple_str: str) -> list[str]:
    """Top-level elements of a (possibly nested) HLO tuple string:
    "(f32[4,8]{1,0}, (b, c))" → ["f32[4,8]{1,0}", "(b, c)"] — commas
    inside nested tuples, dim brackets, and layout braces don't split."""
    parts, depth, cur = [], 0, []
    for ch in tuple_str[1:-1]:
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if cur:
        parts.append("".join(cur))
    return [p.strip() for p in parts]


def _shape_bytes(shape_str: str) -> int:
    total = 0
    for dtype, dims in _ARRAY_SHAPE_RE.findall(shape_str):
        if dtype not in _DTYPE_BYTES:
            if dtype.startswith("f8"):  # f8e4m3fn and friends
                size = 1
            else:
                continue  # token/opaque pseudo-shapes carry no data
        else:
            size = _DTYPE_BYTES[dtype]
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * size
    return total


def collective_bytes(hlo_text: str) -> dict[str, int]:
    """Per-device result bytes moved by each collective op kind, from the
    operand/result shapes in OPTIMIZED HLO text — the comm-volume half of
    the census `collective_counts` only counts.

    The number is the op's *result-shape* footprint summed over its
    occurrences: for all-reduce that equals the reduced tensor, for
    all-gather the full gathered output, for reduce-scatter the local
    shard. It is a per-step, per-device accounting quantity (what
    `StepAccounting` reports as comm-bytes/step), not a link-level
    traffic model — algorithm factors (ring all-reduce moves ~2x the
    tensor over the wire) are deliberately not applied. Async pairs
    count once at the `-start`, per-op tuple semantics: for the
    (operand(s), result(s), context...) ops (_START_OPERAND_RESULT) only
    top-level element [1] — which may itself be a variadic tuple — is
    the transferred data, so neither the in-flight operand copies nor
    TPU context tokens (trailing `u32[]` scalars on e.g.
    collective-permute-start) are billed; all-reduce-start's tuple IS
    its (variadic) result set and counts whole."""
    out = {op: 0 for op in COLLECTIVE_OPS}
    for m in _COLL_DEF_RE.finditer(hlo_text):
        shape_str, op, is_start = m.group(1), m.group(2), m.group(3)
        if is_start and shape_str.startswith("("):
            parts = _split_top_level(shape_str)
            # scalar u32/s32 trailers are async context tokens, not data
            parts = [p for p in parts
                     if not re.match(r"[su]32\[\]", p)]
            if op in _START_OPERAND_RESULT and len(parts) >= 2:
                parts = [parts[1]]
            out[op] += sum(_shape_bytes(p) for p in parts)
        else:
            out[op] += _shape_bytes(shape_str)
    return out


# one async pair: `%name = ... <op>-start(...)` later consumed by
# `<op>-done(...%name...)`. Matched by value name within the module text —
# HLO instruction names are unique per computation and the pair never
# crosses one. The type between `=` and the op is usually a TUPLE with
# internal spaces (`(f32[8]{0}, f32[8]{0}) all-gather-start(...)` — the
# staging tuple every async start returns), so the shape alternation
# mirrors _COLL_DEF_RE's rather than assuming one token.
_ASYNC_START_RE = re.compile(
    r"%?([\w.\-]+)\s*=\s*(?:\((?:[^()]|\([^()]*\))*\)|\S+)\s+("
    + "|".join(sorted(COLLECTIVE_OPS, key=len, reverse=True))
    + r")-start\(")
_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s")


def overlap_census(hlo_text: str) -> dict:
    """Census of the latency-hiding structure of an optimized HLO module
    (ISSUE 5c) — the compile-time evidence for the overlap the scheduler
    flags (trainer._TPU_OVERLAP_COMPILER_OPTIONS) and the ring matmuls
    (ops/overlap.py) claim:

      * ``async_pairs`` — per collective kind, how many ``-start`` ops
        have a matching ``-done`` (on TPU with the latency-hiding
        scheduler every collective should pair; XLA:CPU lowers most
        collectives synchronously, so sim programs legitimately show 0);
      * ``unpaired_starts`` — starts with no done: must be 0 in any
        well-formed module, a nonzero value means the census regexes
        (or the compiler) broke;
      * ``overlapped_ops`` — instructions scheduled strictly BETWEEN a
        start and its done, summed over pairs: the work the scheduler
        actually placed inside collective windows. Post-scheduling HLO
        text is in execution order, so text distance is schedule
        distance; 0 with nonzero pairs means the async pair is
        vestigial (nothing hidden);
      * ``ppermute`` — collective-permute count (async starts count
        once): the chunked collective-matmul signature. Each ring
        contributes exactly (ring_size - 1) hops per traveling operand,
        which is what tests/test_overlap.py pins against the tp size.
    """
    starts: dict[str, tuple[str, int]] = {}
    pairs = {op: 0 for op in COLLECTIVE_OPS}
    overlapped = 0
    instr_idx = 0
    for line in hlo_text.splitlines():
        is_instr = bool(_INSTR_RE.match(line))
        if is_instr:
            instr_idx += 1
        m = _ASYNC_START_RE.search(line)
        if m:
            starts[m.group(1)] = (m.group(2), instr_idx)
            continue
        done = re.search(r"[\w\-]+-done\(", line)
        if done:
            # the done's single operand is the start value; real dumps
            # print it behind its (possibly tuple) shape and with or
            # without the legacy '%' sigil (`all-gather-done((f32[8],
            # f32[16]) %ag.1)`), so rather than parse shape grammar,
            # take the first token that names a recorded start — shape
            # tokens (`f32`, dims) can never collide with instruction
            # names like `all-gather-start.1`
            for tok in re.findall(r"[\w.\-]+", line[done.end():]):
                if tok in starts:
                    op, start_idx = starts.pop(tok)
                    pairs[op] += 1
                    overlapped += max(0, instr_idx - start_idx - 1)
                    break
    return {
        "async_pairs": pairs,
        "unpaired_starts": len(starts),
        "overlapped_ops": overlapped,
        "ppermute": len(re.findall(
            r"collective-permute(?:-start)?\(", hlo_text)),
    }


def a2a_census(hlo_text: str) -> dict[str, int]:
    """The expert-parallel dispatch/combine signature (ISSUE 14): total
    ``all-to-all`` occurrences (plain + ragged, async starts counted
    once) and their per-device result bytes. The MoE a2a path
    (ops/overlap.expert_a2a_ffn) emits exactly 2 per MoE layer forward
    (dispatch + combine) and 2 more in backward — ×chunks when capacity
    pipelining splits them — so the committed ``count`` pins both that
    the explicit exchange actually lowered to all_to_all (not the
    partitioner's allgather+dynamic-slice fallback) and that no pass
    duplicated it; ``bytes`` pins the payload (int8 dispatch payloads
    shrink it ~4x minus the fp32 scale sidecar)."""
    counts = collective_counts(hlo_text)
    nbytes = collective_bytes(hlo_text)
    kinds = ("all-to-all", "ragged-all-to-all")
    return {"count": sum(counts[k] for k in kinds),
            "bytes": sum(nbytes[k] for k in kinds)}


def int8_counts(hlo_text: str) -> dict[str, int]:
    """Census of the int8 quantized-matmul op mix (ops/quant.py):
    ``s8_values`` — instructions producing an s8 tensor (the per-operand
    quantize converts; fusion bodies included, the text covers them);
    ``int_dots`` — dot instructions with s32 (int-accumulated) output.
    Both zero in an unquantized program, which is itself a tripwire: an
    int8 op appearing in a bf16 config's step is never an accident."""
    return {
        "s8_values": len(re.findall(r"= s8\[", hlo_text)),
        "int_dots": len(re.findall(r"= s32\[[^\]]*\]\S* dot\(", hlo_text)),
    }


def hlo_fingerprint(compiled) -> str:
    """sha256 of the executable's optimized-HLO text — the byte-identity
    tripwire (ISSUE 6): two compiles whose fingerprints match ran the
    same program, to the byte. Used to prove the diagnostics knob's OFF
    path adds literally nothing to a train step (the committed numeric
    invariants bound drift; this bounds it to zero)."""
    import hashlib

    return hashlib.sha256(compiled.as_text().encode()).hexdigest()


def compiled_invariants(compiled) -> dict:
    """The committed-invariant dict for one compiled train step.

    * ``flops`` — XLA cost analysis, per device (post-partitioning).
    * ``temp_bytes`` — peak scratch memory of the executable: the
      activation / workspace footprint buffer assignment settled on.
    * ``arg_bytes`` — total input size: params + optimizer state + batch.
      The cheapest state-bloat tripwire there is (round 3's regression —
      BN buffers riding the optimizer tree — was exactly an arg_bytes
      growth).
    * ``alias_bytes`` — input bytes aliased to outputs: the DONATION
      tripwire. The train step donates its TrainState; if a jit change
      silently breaks donation (a dtype/sharding mismatch between the
      donated input and the output is enough — jax only warns), the step
      holds two copies of params+opt state and a model sized near HBM
      OOMs. alias ≈ state bytes is the proof donation still holds.
    * ``collectives`` — `collective_counts` of the optimized HLO.
    * ``int8_ops`` — `int8_counts`: the quantized-matmul convert/dot mix
      (all-zero for unquantized configs).
    * ``comm_bytes`` — `collective_bytes`: per-device result bytes by
      collective kind. Together with ``flops`` these are the
      StepAccounting inputs (telemetry/accounting.py), so committing
      them makes MFU / comm-volume math a CI tripwire: a partitioning
      change that moves communication volume — or an accounting bug
      that would misreport MFU — fails against the pinned numbers.
    * ``overlap`` — `overlap_census`: async start/done pairing, ops
      scheduled inside collective windows, and the ppermute ring count
      (the chunked collective-matmul signature — ISSUE 5).
    * ``a2a`` — `a2a_census`: all-to-all count + bytes, the
      expert-parallel MoE dispatch/combine signature (ISSUE 14).
    """
    mem = compiled.memory_analysis()
    cost = compiled.cost_analysis() or {}
    if isinstance(cost, (list, tuple)):  # older jax wraps it in a list
        cost = cost[0] if cost else {}
    text = compiled.as_text()
    return {
        "flops": float(cost.get("flops", -1.0)),
        "temp_bytes": int(mem.temp_size_in_bytes),
        "arg_bytes": int(mem.argument_size_in_bytes),
        "alias_bytes": int(mem.alias_size_in_bytes),
        "collectives": collective_counts(text),
        "int8_ops": int8_counts(text),
        "comm_bytes": collective_bytes(text),
        "overlap": overlap_census(text),
        "a2a": a2a_census(text),
    }
