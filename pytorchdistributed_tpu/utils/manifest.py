"""Directory integrity manifests (ISSUE 18): the per-file
size + SHA-256 discipline CheckpointManager introduced (PR 4/10),
factored out so every durable tier in the repo — training checkpoints,
persistent KV sessions — shares ONE contract:

  * data files are written first, the manifest LAST and atomically
    (tmp + os.replace), so the manifest's presence is the publish: a
    directory without one is torn-by-definition and must be treated as
    a miss, never as truth;
  * verification checks sizes before hashes (cheap reject first) and
    returns positive-evidence verdicts — "no manifest" is unverified,
    a mismatch against an existing manifest is corruption;
  * corrupt directories are QUARANTINED (moved aside as post-mortem
    evidence, never deleted), race-tolerantly: on a shared filesystem
    every reader walks the same fallback chain, so losing the
    os.replace race to a sibling is success.

No jax, no orbax — host-side stdlib only, importable from the serving
layer without dragging the checkpoint stack in.
"""

from __future__ import annotations

import hashlib
import json
import os
import pathlib
import time

MANIFEST_NAME = "ptd_manifest.json"
QUARANTINE_DIR = "quarantine"


def hash_file(path: pathlib.Path) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_dir_manifest(dirpath: str | pathlib.Path, *,
                       exclude: frozenset | set = frozenset(),
                       extra: dict | None = None) -> pathlib.Path:
    """Per-file size + SHA-256 manifest over every file under
    ``dirpath`` (recursive), written atomically beside the data it
    covers. ``exclude`` names (basenames) are skipped — the manifest
    itself always is. ``extra`` keys are merged into the top-level
    manifest dict (e.g. a step number, a session's metadata)."""
    dirpath = pathlib.Path(dirpath)
    files = {}
    for p in sorted(dirpath.rglob("*")):
        if (not p.is_file() or p.name == MANIFEST_NAME
                or p.name in exclude or p.name.endswith(".tmp")):
            continue
        rel = str(p.relative_to(dirpath))
        files[rel] = {"size": p.stat().st_size, "sha256": hash_file(p)}
    manifest = dict(extra or {})
    manifest["time"] = round(time.time(), 3)
    manifest["files"] = files
    path = dirpath / MANIFEST_NAME
    tmp = path.with_name(path.name + ".tmp")
    tmp.write_text(json.dumps(manifest, indent=0, sort_keys=True))
    os.replace(tmp, path)
    return path


def verify_dir_manifest(dirpath: str | pathlib.Path
                        ) -> tuple[bool, bool, str]:
    """Check a directory against its manifest. Returns
    ``(ok, verified, detail)``: a directory with NO manifest passes
    unverified (``(True, False, ...)`` — legacy data, or a writer that
    died after the data landed but before publish); a manifest that
    exists and mismatches is positive evidence of corruption
    (``(False, True, ...)``)."""
    dirpath = pathlib.Path(dirpath)
    mpath = dirpath / MANIFEST_NAME
    if not mpath.exists():
        return True, False, "no manifest (unverified)"
    try:
        entries = dict(json.loads(mpath.read_text())["files"])
    except (OSError, ValueError, KeyError, TypeError) as e:
        return False, False, f"unreadable manifest ({e})"
    for rel, meta in entries.items():
        p = dirpath / rel
        if not p.is_file():
            return False, True, f"missing file {rel}"
        if p.stat().st_size != meta.get("size"):
            return False, True, f"size mismatch {rel}"
        if hash_file(p) != meta.get("sha256"):
            return False, True, f"checksum mismatch {rel}"
    return True, True, f"{len(entries)} files ok"


def read_manifest(dirpath: str | pathlib.Path) -> dict | None:
    """The parsed manifest dict, or None when absent/unreadable —
    metadata-only reads (``ls``-style listings) that must not trust an
    unpublished directory."""
    mpath = pathlib.Path(dirpath) / MANIFEST_NAME
    try:
        return json.loads(mpath.read_text())
    except (OSError, ValueError):
        return None


def quarantine_dir(dirpath: str | pathlib.Path, *,
                   root: str | pathlib.Path | None = None
                   ) -> pathlib.Path:
    """Move a corrupt directory into ``<root>/quarantine/`` (evidence,
    not garbage; ``root`` defaults to the directory's parent).
    Race-tolerant: a sibling process moving it first is success."""
    dirpath = pathlib.Path(dirpath)
    qdir = pathlib.Path(root or dirpath.parent) / QUARANTINE_DIR
    qdir.mkdir(parents=True, exist_ok=True)
    dest = qdir / dirpath.name
    if dest.exists():  # a prior incarnation quarantined this name too
        dest = qdir / f"{dirpath.name}.{int(time.time() * 1e3)}"
    try:
        os.replace(dirpath, dest)
    except FileNotFoundError:
        dest = qdir / dirpath.name  # a sibling moved it first
    return dest
