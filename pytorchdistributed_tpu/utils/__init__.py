from pytorchdistributed_tpu.utils.metrics import (  # noqa: F401
    StepTimer,
    ThroughputMeter,
    scaling_efficiency,
)
from pytorchdistributed_tpu.utils.guards import (  # noqa: F401
    NaNWatchdog,
    assert_finite,
    assert_replicas_consistent,
)
from pytorchdistributed_tpu.utils.profiling import (  # noqa: F401
    profile,
    step_annotation,
)
