"""Runtime guards (SURVEY.md §5 "Race detection / sanitizers"): the
reference leans entirely on NCCL's synchronous collective semantics; on TPU
XLA's static schedule removes data races by construction, so the remaining
failure classes are (a) divergent state across processes — which deadlocks
collectives the way mismatched NCCL calls do — and (b) numeric blowups.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def assert_finite(tree, *, name: str = "tree") -> None:
    """NaN/Inf watchdog: raises FloatingPointError naming every offending
    leaf (path included — the debugging detail torch's detect_anomaly buries)."""
    bad = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            if not bool(jnp.all(jnp.isfinite(leaf))):
                bad.append(jax.tree_util.keystr(path))
    if bad:
        raise FloatingPointError(
            f"non-finite values in {name}: {', '.join(bad)}")


class NaNWatchdog:
    """Periodic finite-check on metrics/state during training; cheap (only
    metrics every step, full state every ``state_every`` checks)."""

    def __init__(self, state_every: int = 100):
        self.state_every = state_every
        self._count = 0

    def check(self, metrics: dict, state=None) -> None:
        for k, v in metrics.items():
            if not np.isfinite(float(v)):
                raise FloatingPointError(f"metric {k!r} is {float(v)}")
        self._count += 1
        if state is not None and self._count % self.state_every == 0:
            assert_finite(state.params, name="params")


def assert_replicas_consistent(tree, *, name: str = "pytree") -> None:
    """Cross-process collective-mismatch guard (SURVEY.md §5): every process
    must hold an identical tree structure + leaf shapes/dtypes before
    compiling a collective program, else the pod deadlocks mid-compile the
    way mismatched NCCL calls do. Call before the first train step on
    multi-process runs; no-op single-process."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    leaves, treedef = jax.tree.flatten(tree)
    desc = str(treedef) + "|" + "|".join(
        f"{getattr(l, 'shape', ())}:{getattr(l, 'dtype', type(l).__name__)}"
        for l in leaves)
    digest = np.frombuffer(
        __import__("hashlib").sha256(desc.encode()).digest()[:8],
        dtype=np.int64)
    gathered = multihost_utils.process_allgather(digest)
    if not (gathered == gathered[0]).all():
        raise RuntimeError(
            f"{name} differs across processes (collective-mismatch guard): "
            f"digests {gathered.ravel().tolist()}")
