"""Device-time summaries of `jax.profiler` captures (SURVEY.md §5
"Tracing / profiling").

The Trainer's ``profile_dir`` writes a Perfetto trace; this module answers
the first question anyone asks of it — *where did the step time go?* —
without leaving the terminal:

    python -m pytorchdistributed_tpu.utils.trace /tmp/profile [--steps 3]

It aggregates the TPU "XLA Ops" track by op family (fusion kinds, Pallas
custom-calls, copies, while-loops...) and prints a per-step table plus the
top individual ops. This is the exact workflow that found the round-3 MFU
wins (latency-bound attention grids, GQA repeat copies): keep the trace
window small (the Trainer captures steps 2-7) and divide by the step count.
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re


def load_trace_events(profile_dir: str) -> list[dict]:
    """Events of the newest ``*.trace.json.gz`` under ``profile_dir``
    (searching the plugins/profile/<run>/ layout jax.profiler writes)."""
    paths = sorted(
        glob.glob(os.path.join(profile_dir, "**", "*.trace.json.gz"),
                  recursive=True),
        key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {profile_dir!r} — point at the "
            f"directory passed to Trainer(profile_dir=...) / "
            f"jax.profiler.trace")
    with gzip.open(paths[-1], "rt") as f:
        return json.load(f)["traceEvents"]


def device_op_durations(events: list[dict]) -> dict[str, tuple[float, int]]:
    """{op name: (total us, count)} over every device's "XLA Ops" thread.
    Note XLA nests some regions (a while-loop's body ops are also emitted
    as their own events), so the grand total can exceed wall time — the
    table answers "which ops are hot", not "what sums to 100%"."""
    pids = {e["pid"]: e["args"].get("name", "") for e in events
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    tids = {(e["pid"], e["tid"]): e["args"].get("name", "") for e in events
            if e.get("ph") == "M" and e.get("name") == "thread_name"}
    out: dict[str, list] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        if "TPU" not in pids.get(e["pid"], ""):
            continue
        if tids.get((e["pid"], e["tid"])) != "XLA Ops":
            continue
        r = out.setdefault(e["name"], [0.0, 0])
        r[0] += e.get("dur", 0)
        r[1] += 1
    return {k: (v[0], v[1]) for k, v in out.items()}


def family(op_name: str) -> str:
    """Strip the trailing instruction numbering: ``fusion.123`` →
    ``fusion``, ``multiply_reduce_fusion.5`` → ``multiply_reduce_fusion``."""
    return re.sub(r"[.\d]+$", "", op_name)


# the Trainer wraps each profiled dispatch in
# jax.profiler.StepTraceAnnotation(STEP_ANNOTATION, step_num=i) so the
# capture carries its own step count — the old --steps default of 1
# silently mislabeled every per-step number 6x (the Trainer captures 6)
STEP_ANNOTATION = "train"


def detect_step_count(events: list[dict]) -> int | None:
    """Step count from step annotations in the capture: complete events
    named exactly ``STEP_ANNOTATION`` (the Trainer's host-side
    StepTraceAnnotation), or events on a profiler-derived "Steps" thread.
    Max per-thread count so multi-device captures (one step line per
    device) don't multiply. None when the capture carries no markers."""
    steps_tids = {(e["pid"], e.get("tid")) for e in events
                  if e.get("ph") == "M" and e.get("name") == "thread_name"
                  and "Steps" in e.get("args", {}).get("name", "")}
    counts: collections.Counter = collections.Counter()
    for e in events:
        if e.get("ph") != "X":
            continue
        key = (e.get("pid"), e.get("tid"))
        if e.get("name") == STEP_ANNOTATION or key in steps_tids:
            counts[key] += 1
    return max(counts.values()) if counts else None


def summarize(profile_dir: str, *, steps: int | None = None,
              top: int = 15) -> str:
    """Human-readable per-family and top-ops tables. ``steps`` divides
    the totals so numbers read as ms/step; None auto-detects it from the
    capture's step annotations (falling back to 1 with a warning when
    the capture predates them)."""
    events = load_trace_events(profile_dir)
    note = ""
    if steps is None:
        detected = detect_step_count(events)
        if detected:
            steps, note = detected, " auto-detected"
        else:
            steps, note = 1, (" NO step annotations found — per-step "
                              "numbers are whole-capture totals; pass "
                              "--steps")
    ops = device_op_durations(events)
    fams: collections.Counter = collections.Counter()
    counts: collections.Counter = collections.Counter()
    for name, (dur, n) in ops.items():
        fams[family(name)] += dur
        counts[family(name)] += n
    total = sum(fams.values())
    lines = [f"device op time: {total / steps / 1e3:.1f} ms/step "
             f"(x{steps} steps{note}; nested regions double-count)"]
    lines.append(f"{'share':>6}  {'ms/step':>9}  {'calls':>6}  op family")
    for fam, dur in fams.most_common(top):
        lines.append(f"{dur / total * 100:5.1f}%  {dur / steps / 1e3:9.2f}"
                     f"  {counts[fam]:6d}  {fam}")
    lines.append("")
    lines.append(f"{'ms/step':>9}  {'calls':>6}  top individual ops")
    for name, (dur, n) in sorted(ops.items(), key=lambda kv: -kv[1][0])[:top]:
        lines.append(f"{dur / steps / 1e3:9.2f}  {n:6d}  {name}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        "pytorchdistributed_tpu.utils.trace",
        description="summarize a jax.profiler capture's device time")
    p.add_argument("profile_dir")
    p.add_argument("--steps", type=int, default=None,
                   help="steps inside the capture window; default: "
                        "auto-detected from the capture's step "
                        "annotations (the Trainer annotates each "
                        "profiled dispatch)")
    p.add_argument("--top", type=int, default=15)
    args = p.parse_args(argv)
    print(summarize(args.profile_dir, steps=args.steps, top=args.top))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
