"""Step-time / throughput / scaling-efficiency meters (SURVEY.md §5:
"per-step metrics (loss, step time, tokens/s or img/s, scaling efficiency)
since those are the BASELINE metric").

The reference's only measurement device is `timeit.repeat(number=1,
repeat=10)` → mean±std (03_model_parallel.ipynb:403-423); `StepTimer.timeit`
reproduces that exact methodology so our benchmark numbers are comparable
with its harness shape.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import time
from typing import Callable

import numpy as np


@dataclasses.dataclass
class StepTimer:
    """Wall-clock per-step timer with warmup discard (first compile)."""

    warmup: int = 1
    _times: list = dataclasses.field(default_factory=list)
    _seen: int = 0
    _t0: float | None = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self._seen += 1
        if self._seen > self.warmup:
            self._times.append(dt)

    @property
    def mean(self) -> float:
        return float(np.mean(self._times)) if self._times else float("nan")

    @property
    def std(self) -> float:
        return float(np.std(self._times)) if self._times else float("nan")

    @staticmethod
    def timeit(fn: Callable[[], None], *, repeat: int = 10) -> tuple[float, float]:
        """The reference's methodology: run ``fn`` ``repeat`` times, one
        execution each, report mean±std (03_model_parallel.ipynb:403-423)."""
        times = []
        for _ in range(repeat):
            t0 = time.perf_counter()
            fn()
            times.append(time.perf_counter() - t0)
        return float(np.mean(times)), float(np.std(times))


class ThroughputMeter:
    """samples/s (or img/s, tokens/s) over a sliding window, excluding the
    compile step."""

    def __init__(self, window: int = 50, warmup: int = 1):
        self.window = window
        self.warmup = warmup
        # bounded deque: eviction is O(1) where the old list.pop(0) was
        # O(window) per step, every step, for the life of the job
        self._stamps: collections.deque[tuple[float, int]] = \
            collections.deque(maxlen=window)
        self._seen = 0

    def update(self, n_samples: int) -> None:
        self._seen += 1
        if self._seen <= self.warmup:
            return
        self._stamps.append((time.perf_counter(), n_samples))

    @property
    def rate(self) -> float:
        """samples/s over the window; NaN until two post-warmup stamps
        exist or when the window spans zero wall time."""
        if len(self._stamps) < 2:
            return float("nan")
        dt = self._stamps[-1][0] - self._stamps[0][0]
        n = sum(s for _, s in itertools.islice(self._stamps, 1, None))
        return n / dt if dt > 0 else float("nan")


def scaling_efficiency(throughput_n: float, throughput_1: float,
                       n: int) -> float:
    """DDP scaling efficiency (BASELINE north star: ≥0.90 at 8→256 chips):
    throughput on n chips / (n × throughput on 1 chip)."""
    if n <= 0 or throughput_1 <= 0:
        return float("nan")
    return throughput_n / (n * throughput_1)
