"""Profiling hooks (SURVEY.md §5 "Tracing / profiling": the reference has
only timeit+matplotlib; here: the jax profiler, viewable in
TensorBoard/Perfetto/XProf).
"""

from __future__ import annotations

import contextlib

import jax


@contextlib.contextmanager
def profile(logdir: str, *, first_step: int = 0):
    """Capture a device trace for the enclosed steps:

        with profile("/tmp/trace"):
            for _ in range(5): trainer.train_step(batch)

    Open with TensorBoard's profile plugin or ui.perfetto.dev."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def step_annotation(name: str):
    """Label a region so it shows up named in the trace timeline."""
    return jax.profiler.TraceAnnotation(name)
