"""Continuous-batching serving engine: a slot-based KV-cache scheduler
over a single compiled decode step.

`inference.generate()` is a one-shot batch call: every request in a batch
must start together and run to the same max_new_tokens, so short requests
pay for long ones and new arrivals wait for the whole batch to drain.
This module is the Orca-style fix (iteration-level scheduling) with a
vLLM-style fixed-slot cache, realized TPU-natively:

  * the engine owns ONE persistent KV cache of ``num_slots`` rows
    (`[slots, max_seq_len, kv_heads, head_dim]` per layer — the model's
    existing ``decode=True`` cache collection at ``decode_slots > 0``,
    where every position counter is a per-row vector);
  * a jitted **decode tick** (`decode_tick`) advances ALL slots one token
    per call — per-slot lengths ride the position counters/masks inside
    the model, per-request sampling params are dynamic `[slots]` arrays
    (`inference.sample_slots`), and the cache is donated, so steady-state
    decode is one fixed-shape program with zero retraces and zero cache
    copies;
  * a jitted **prefill** (`prefill_into_slot`) runs one request's chunked
    prompt forward (batch 1, prompts right-padded to a bucket multiple so
    variable lengths hit a handful of programs) and writes the resulting
    cache rows into a free slot via `dynamic_update_slice`, rewinding
    that slot's position counters to the true prompt length;
  * a host-side scheduler (`ServingEngine`) keeps the request queue,
    admits a prefill whenever a slot frees, retires on stop-ids /
    max-token budget, streams tokens per request (callbacks or the
    `stream()` iterator), and bridges TTFT / tokens-per-s / queue depth /
    slot occupancy into telemetry/ (serving.telemetry).

Paged mode (ISSUE 7, ``block_size > 0``) swaps the dense per-slot cache
for a block-table **paged KV pool** (vLLM's PagedAttention,
TPU-natively): one donated pool of fixed-size KV blocks + per-slot
block tables gathered inside the same compiled tick, a host-side
**radix prefix cache** admitting shared prompt prefixes by refcounted
block reference instead of re-prefilling, **chunked prefill**
interleaving long admissions with decode ticks, and preempt-requeue
under pool pressure — HBM then bounds actual resident tokens, not
slots x max_seq_len. Tables/lengths are host numpy stamped into each
call as dynamic arguments, so all of it is host bookkeeping between
two fixed compiled programs (paged_decode_tick / paged_prefill_chunk).

Speculative mode (ISSUE 8, ``spec_k > 0``, paged only) replaces the
one-token tick with **draft-and-verify**: a draft model proposes
``spec_k`` tokens per slot inside one fused compiled program
(`spec_decode_tick` — draft rollout scan + ONE k+1-wide target forward
through the same paged scatter/gather + the lossless rejection kernel,
both pools donated), and each slot advances by its accepted length + 1.
Decode is memory-bound, so accepted tokens per target forward is the
decode-rate multiplier; losslessness means draft quality can only cost
acceptance rate, never correctness.

Composition: params may be dp/tp sharded (pass the mesh) and quantized
(`--quant` int8 policies) exactly as generate() accepts them — the tick
and prefill run the same decode einsums under the same logical rules.
Greedy outputs are bitwise-equal to generate()'s per request, for any
admission order — prefix hits, chunk boundaries, preemptions and
speculation included (tests/test_serving.py + tests/test_paging.py +
tests/test_spec.py pin it).
"""

from __future__ import annotations

import base64
import collections
import contextlib
import dataclasses
import functools
import hashlib
import itertools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from pytorchdistributed_tpu.inference import (
    _zero_cache,
    draft_and_verify,
    draft_and_verify_heads,
    kv_cache_bytes,
    sample_slots,
    stop_ids_tuple,
)
from pytorchdistributed_tpu.runtime.compile_cache import (
    CompileCache,
    static_repr,
)
from pytorchdistributed_tpu.serving.paging import (
    BlockAllocator,
    RadixPrefixCache,
)
from pytorchdistributed_tpu.serving.telemetry import ServingTelemetry
from pytorchdistributed_tpu.telemetry.tracing import (
    TraceContext,
    from_unix as _trace_from_unix,
    to_unix as _trace_to_unix,
)

# Traced-body invocation counter (same discipline as inference.
# TRACE_COUNTS): the zero-recompiles-after-warmup guarantee is asserted
# against these — a steady-state serving loop must never move them.
TRACE_COUNTS: collections.Counter = collections.Counter()


def slot_models(model, num_slots: int):
    """(tick_model, prefill_model) for a causal LM module.

    The tick model decodes with per-row position counters
    (``decode_slots=num_slots``; batch == slots); the prefill model is the
    plain scalar-counter decode model at batch 1 (a single request starts
    from position 0, so it needs no per-row state). Both attend over the
    full max_seq_len window (slots sit at arbitrary positions) on the
    cache-masked dense path — the training-time attention backend knob
    does not apply to decode, so it is pinned to "dense" here to keep the
    clone warning-free."""
    cfg = dataclasses.replace(
        model.cfg, decode=True, attention="dense", decode_attend_len=None,
        decode_slots=0)
    return (model.clone(cfg=dataclasses.replace(
                cfg, decode_slots=num_slots)),
            model.clone(cfg=cfg))


def _leaf_name(path) -> str:
    return getattr(path[-1], "key", str(path[-1]))


# The paged pool's cache-collection leaves, with the offset of the block
# axis from the END of each leaf's shape (scanned layer stacks prepend
# dims, so the end is the stable anchor): K/V pools are
# [..., kv_blocks, block_size, kv_heads, head_dim] (block axis ndim-4),
# the int8 scale planes drop head_dim (ndim-3). Everything that moves
# blocks — the compiled gather/scatter pair, the prefill-chunk merge,
# the export/import payloads and the fleet prefix stream — keys off this
# one table, which is how the int8 pool's scales ride every existing
# block-transport path without a second code path.
POOL_LEAF_AXIS = {
    "cached_key": 4, "cached_value": 4,
    "cached_key_scale": 3, "cached_value_scale": 3,
}


def _pool_block_axis(name: str, ndim: int) -> int:
    """Block-axis index for a pool leaf, by its (path or bare) name."""
    return ndim - POOL_LEAF_AXIS[name.rsplit("/", 1)[-1]]


#: KV wire-payload schema version (ISSUE 13): bumped when the payload's
#: pool-leaf set or meaning changes (v2 added kv_dtype + the int8 scale
#: planes). import_kv_blocks rejects any other version loudly — a bf16
#: replica must never scatter an int8 payload's codes into its pool.
KV_WIRE_VERSION = 2


@functools.partial(
    jax.jit,
    static_argnames=("model", "candidates"),
    donate_argnames=("cache",))
def decode_tick(model, weights, cache, tokens, key_data, counts,
                temperature, top_k, top_p, *, candidates: int):
    """Advance every slot one token: ONE model apply over ``[slots, 1]``
    last-tokens (each slot reads/writes its own cache row at its own
    position) + the per-slot sampler. Free/retired slots tick along as
    greedy garbage — the fixed-shape price of zero retraces; the host
    simply ignores their outputs.

    ``key_data``/``counts`` carry each request's seeded stream: token n of
    a request is sampled with fold_in(key(seed), n), so outputs are
    deterministic per request no matter which slot or admission order it
    got (the determinism test's property)."""
    TRACE_COUNTS["decode_tick"] += 1
    logits, mut = model.apply({"params": weights, "cache": cache},
                              tokens[:, None], mutable=["cache"])
    keys = jax.random.wrap_key_data(key_data)
    subs = jax.vmap(jax.random.fold_in)(keys, counts)
    nxt = sample_slots(logits[:, 0].astype(jnp.float32), subs,
                       temperature, top_k, top_p, candidates=candidates)
    return mut["cache"], nxt


@functools.partial(
    jax.jit,
    static_argnames=("model", "candidates"),
    donate_argnames=("cache",))
def prefill_into_slot(model, weights, cache, prompt, true_len, slot,
                      key_data, count, temperature, top_k, top_p, *,
                      candidates: int):
    """Admit one request: a chunked prompt forward (batch 1, prompt
    right-padded to the bucket length — ``true_len`` is dynamic) fills a
    fresh single-row cache, whose rows are written into ``slot`` of the
    engine cache via dynamic_update_slice; the slot's position counters
    are rewound to ``true_len`` (pad rows sit beyond the position mask
    until decode overwrites them — the same trick as
    inference.generate_bucketed). Returns (cache, first_token): sampling
    the first token here is what makes TTFT one prefill, not
    prefill + a decode tick. ``count`` is the sampled token's fold_in
    index — 0 on a fresh admission, the generated-so-far length when a
    request RESUMES from tokens (submit(generated=...) — the router's
    failover path), so a resumed sampled stream continues its seeded
    PRNG sequence exactly where the dead replica left it."""
    TRACE_COUNTS["prefill"] += 1
    fresh = _zero_cache(model, prompt)
    logits, mut = model.apply({"params": weights, "cache": fresh}, prompt,
                              mutable=["cache"])
    last = jax.lax.dynamic_slice_in_dim(logits, true_len - 1, 1, axis=1)
    keys = jax.random.wrap_key_data(key_data[None])
    subs = jax.vmap(jax.random.fold_in)(keys, count[None])
    first = sample_slots(last[:, 0].astype(jnp.float32), subs,
                         temperature[None], top_k[None], top_p[None],
                         candidates=candidates)[0]

    def merge(path, big, small):
        if _leaf_name(path) in ("index", "pos_index"):
            # rewind to the true prompt length (the padded prefill
            # advanced the single-row counters to the bucket length)
            return jnp.where(jnp.arange(big.shape[-1]) == slot,
                             true_len, big)
        # K/V rows: [..., slots, max_seq_len, kv_heads, head_dim] — the
        # slot axis is always 4 dims from the end, scanned-layer or not
        axis = big.ndim - 4
        start = tuple(slot if d == axis else 0 for d in range(big.ndim))
        return jax.lax.dynamic_update_slice(big, small, start)

    new_cache = jax.tree_util.tree_map_with_path(merge, cache, mut["cache"])
    return new_cache, first


def paged_slot_models(model, num_slots: int, block_size: int,
                      num_blocks: int, *, kv_dtype: str = "bf16",
                      kv_sink_tokens: int = 0, kv_window_tokens: int = 0,
                      paged_attn: str = "gather",
                      per_slot_kv_limits: bool = False):
    """(tick_model, chunk_model) for the PAGED engine: both share the one
    block pool (pool shapes carry no slot dim); the tick model decodes
    all ``num_slots`` rows, the chunk model runs one request's prefill
    chunk at batch 1 (``decode_slots=1``) against the same pool. Same
    dense-path pinning rationale as slot_models. The KV-compression
    knobs (ISSUE 13) ride here: ``kv_dtype`` picks the pool's storage
    dtype (int8 adds the scale-plane cache leaves), sink/window set the
    STATIC attention-window mask, and ``paged_attn`` picks the decode
    tick's attention implementation (the chunked-prefill path always
    gathers — chunks run s > 1, the Pallas kernel is decode-only).
    ``per_slot_kv_limits`` (ISSUE 15) swaps the static window mask for
    per-slot ``kv_sinks``/``kv_windows`` cache leaves on the TICK model
    only — the chunk model keeps the static mask (one request's prefill
    has no slot row to read), so prefill always masks under the pool
    window and the per-request override takes effect from the first
    decoded token."""
    cfg = dataclasses.replace(
        model.cfg, decode=True, attention="dense", decode_attend_len=None,
        decode_slots=num_slots, kv_block_size=block_size,
        kv_blocks=num_blocks, kv_dtype=kv_dtype,
        kv_sink_tokens=kv_sink_tokens, kv_window_tokens=kv_window_tokens,
        paged_attn=paged_attn, per_slot_kv_limits=per_slot_kv_limits)
    return (model.clone(cfg=cfg),
            model.clone(cfg=dataclasses.replace(
                cfg, decode_slots=1, per_slot_kv_limits=False)))


def _override_paging(cache, tables, lengths):
    """Stamp the host scheduler's block tables + per-slot lengths over
    the cache collection's counter/table leaves (every layer reads the
    same values — leaves just broadcast up the scan axis). The device
    copies are write-through scratch: the engine re-stamps from host
    state on every compiled call, which is what makes prefix sharing,
    block growth and preemption pure host bookkeeping."""
    def fix(path, leaf):
        name = _leaf_name(path)
        if name in ("index", "pos_index"):
            return jnp.broadcast_to(lengths, leaf.shape).astype(leaf.dtype)
        if name == "block_table":
            return jnp.broadcast_to(tables, leaf.shape).astype(leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(fix, cache)


@functools.partial(
    jax.jit,
    static_argnames=("model", "candidates"),
    donate_argnames=("cache",))
def paged_decode_tick(model, weights, cache, tables, lengths, tokens,
                      key_data, counts, temperature, top_k, top_p, *,
                      candidates: int):
    """The paged twin of decode_tick: same one-apply-over-[slots, 1]
    shape, but K/V live in the donated block POOL and each slot's rows
    are table-gathered inside the compiled program
    (models/transformer.py paged branch). ``tables``/``lengths`` arrive
    from host state every call — free slots carry all-trash tables and
    length 0, so their garbage ticks write the reserved trash block and
    can never corrupt a live request's blocks."""
    TRACE_COUNTS["paged_decode_tick"] += 1
    cache = _override_paging(cache, tables, lengths)
    logits, mut = model.apply({"params": weights, "cache": cache},
                              tokens[:, None], mutable=["cache"])
    keys = jax.random.wrap_key_data(key_data)
    subs = jax.vmap(jax.random.fold_in)(keys, counts)
    nxt = sample_slots(logits[:, 0].astype(jnp.float32), subs,
                       temperature, top_k, top_p, candidates=candidates)
    return mut["cache"], nxt


@functools.partial(
    jax.jit,
    static_argnames=("model", "candidates"),
    donate_argnames=("cache",))
def paged_prefill_chunk(model, weights, cache, chunk, start, table_row,
                        true_len, key_data, count, temperature, top_k,
                        top_p, *, candidates: int):
    """One fixed-size prefill chunk of one request, written straight
    into ITS blocks of the shared pool. ``chunk`` is [1, C] tokens
    covering absolute positions [start, start+C) (right-padded past
    true_len — pad K/V lands beyond the position mask, or in the trash
    block past max_seq_len, until decode overwrites it); ``start`` is
    dynamic, so a prefix-cache hit just starts chunking at the first
    unmatched block with the SAME compiled program. Chunking long
    prompts into C-token calls is what lets the scheduler interleave
    resident slots' decode ticks between chunks — a long admission no
    longer head-of-line-blocks their TTFT. Samples the request's next
    token at the (dynamic) last true position — only the final chunk's
    sample is used; ``count`` is its fold_in index (> 0 when a preempted
    request resumes mid-generation)."""
    TRACE_COUNTS["paged_prefill_chunk"] += 1

    def shrink(path, leaf):
        # the chunk model is the same module tree at decode_slots=1:
        # pool leaves pass through untouched (no slot dim), counter and
        # table leaves shrink to the one-request row
        name = _leaf_name(path)
        if name in ("index", "pos_index"):
            return jnp.broadcast_to(
                start, leaf.shape[:-1] + (1,)).astype(leaf.dtype)
        if name == "block_table":
            return jnp.broadcast_to(
                table_row,
                leaf.shape[:-2] + (1,) + table_row.shape).astype(leaf.dtype)
        return leaf

    small = jax.tree_util.tree_map_with_path(shrink, cache)
    logits, mut = model.apply({"params": weights, "cache": small}, chunk,
                              mutable=["cache"])

    def merge(path, big, new):
        # only the pools mutated (K/V codes AND, on an int8 pool, their
        # scale planes); the big cache's counter/table leaves are
        # scratch the engine re-stamps anyway
        return new if _leaf_name(path) in POOL_LEAF_AXIS else big

    new_cache = jax.tree_util.tree_map_with_path(merge, cache, mut["cache"])
    off = jnp.clip(true_len - 1 - start, 0, chunk.shape[1] - 1)
    last = jax.lax.dynamic_slice_in_dim(logits, off, 1, axis=1)
    keys = jax.random.wrap_key_data(key_data[None])
    subs = jax.vmap(jax.random.fold_in)(keys, count[None])
    first = sample_slots(last[:, 0].astype(jnp.float32), subs,
                         temperature[None], top_k[None], top_p[None],
                         candidates=candidates)[0]
    return new_cache, first


@functools.partial(
    jax.jit,
    static_argnames=("model", "draft_model", "spec_k", "candidates"),
    donate_argnames=("cache", "draft_cache"))
def spec_decode_tick(model, draft_model, weights, draft_weights, cache,
                     draft_cache, tables, lengths, tokens, key_data, counts,
                     temperature, top_k, top_p, k_eff=None, *, spec_k: int,
                     candidates: int):
    """The speculative twin of paged_decode_tick (ISSUE 8): ONE compiled
    program per tick that (a) rolls the draft model ``spec_k + 1``
    single-token steps from each slot's last token (k proposals, plus one
    extra step that only writes the last proposal's K/V so a
    fully-accepted slot's next round attends a complete draft cache),
    (b) scores all k+1 positions with ONE target forward — the verify
    chunk [last_tok, d_1..d_k] rides the same paged scatter/gather path,
    so draft K/V lands in table-mapped blocks and anything past
    max_seq_len drops into trash block 0 — and (c) runs the lossless
    rejection kernel (inference.speculative_accept) per slot.

    Both caches share the SAME host-stamped block tables: the draft pool
    is a second (shallower) set of block arrays addressed by identical
    block ids, so growth/preemption/trash bookkeeping is one table. No
    rollback pass exists anywhere: the host advances each slot's length
    by its accepted count + 1, and the NEXT round's k+1 writes at
    [len, len+k] always cover this round's rejected-suffix K/V before
    anything can attend it (the position mask bounds reads at len).

    Returns ``(cache, draft_cache, tokens [slots, k+1], n_accept
    [slots])`` — the host delivers exactly n_accept+1 tokens per slot.
    Randomness: a round at generated-count c derives every stream from
    fold_in(request_key, c) (draft step j → fold_in twice with tag 1 and
    j; accept uniforms tag 2; residual tag 3), so sampled outputs are a
    function of (prompt, sampling params, seed, scheduling) alone — the
    same request in any admission order reproduces its tokens. One
    honest caveat vs the plain tick: a preempt-RESUME re-derives the
    resumed token from the prefill sampler rather than the interrupted
    round's streams, so a SAMPLED stream's post-resume suffix is a
    different (equally target-distributed) sample than the
    uninterrupted run's; greedy streams are bitwise-stable across
    preemption either way (tests/test_spec.py pins that).

    ``k_eff`` (optional [slots] int32, ISSUE 16) is the per-slot
    EFFECTIVE proposal depth — a DYNAMIC operand of this fixed
    spec_k-wide program, so the host can move it every tick (adaptive k)
    with zero recompiles; see inference.speculative_accept for why the
    masked width stays lossless."""
    TRACE_COUNTS["spec_decode_tick"] += 1
    cache = _override_paging(cache, tables, lengths)
    draft_cache = _override_paging(draft_cache, tables, lengths)
    keys = jax.random.wrap_key_data(key_data)
    base = jax.vmap(jax.random.fold_in)(keys, counts)
    step1 = jax.vmap(jax.random.fold_in, in_axes=(0, None))(base, 1)
    draft_keys = jax.vmap(
        lambda j: jax.vmap(jax.random.fold_in, in_axes=(0, None))(step1, j)
    )(jnp.arange(spec_k + 1))
    acc_keys = jax.vmap(jax.random.fold_in, in_axes=(0, None))(base, 2)
    unif = jax.vmap(lambda k_: jax.random.uniform(k_, (spec_k,)))(acc_keys)
    res_keys = jax.vmap(jax.random.fold_in, in_axes=(0, None))(base, 3)
    return draft_and_verify(
        model, draft_model, weights, draft_weights, cache, draft_cache,
        tokens, draft_keys, unif, res_keys, temperature, top_k, top_p,
        spec_k=spec_k, candidates=candidates, k_eff=k_eff)


@functools.partial(
    jax.jit,
    static_argnames=("model", "draft_model", "spec_k", "candidates"),
    donate_argnames=("cache", "draft_cache"))
def spec_decode_tick_heads(model, draft_model, weights, draft_weights,
                           cache, draft_cache, tables, lengths,
                           draft_lengths, prev_tokens, prev_idx, tokens,
                           key_data, counts, temperature, top_k, top_p,
                           k_eff=None, *, spec_k: int, candidates: int):
    """spec_decode_tick for a draft carrying multi-token proposal heads
    (ISSUE 16): the draft's spec_k+1-step sequential rollout collapses to
    ONE forward over each slot's PREVIOUS round's emitted buffer
    (``prev_tokens`` [slots, spec_k+1], live up to ``prev_idx``), whose
    writes land at ``draft_lengths`` — the previous round's start, one
    round behind the target's ``lengths`` — through the SAME host-stamped
    block tables. The verify forward, rejection kernel, PRNG stream
    derivation, and host advance-by-n+1 contract are byte-for-byte
    spec_decode_tick's, so losslessness and stream reproducibility never
    fork; only the number of draft forwards per round changes (k+1 → 1).
    Same returns; extra host duty: after the round, ``prev_tokens`` :=
    this round's emitted buffer, ``prev_idx`` := n_accept,
    ``draft_lengths`` := the pre-advance length + 1."""
    TRACE_COUNTS["spec_decode_tick_heads"] += 1
    cache = _override_paging(cache, tables, lengths)
    draft_cache = _override_paging(draft_cache, tables, draft_lengths)
    keys = jax.random.wrap_key_data(key_data)
    base = jax.vmap(jax.random.fold_in)(keys, counts)
    step1 = jax.vmap(jax.random.fold_in, in_axes=(0, None))(base, 1)
    draft_keys = jax.vmap(
        lambda j: jax.vmap(jax.random.fold_in, in_axes=(0, None))(step1, j)
    )(jnp.arange(spec_k + 1))
    acc_keys = jax.vmap(jax.random.fold_in, in_axes=(0, None))(base, 2)
    unif = jax.vmap(lambda k_: jax.random.uniform(k_, (spec_k,)))(acc_keys)
    res_keys = jax.vmap(jax.random.fold_in, in_axes=(0, None))(base, 3)
    return draft_and_verify_heads(
        model, draft_model, weights, draft_weights, cache, draft_cache,
        tokens, prev_tokens, prev_idx, draft_keys, unif, res_keys,
        temperature, top_k, top_p, spec_k=spec_k, candidates=candidates,
        k_eff=k_eff)


def nan_params(weights):
    """Every inexact leaf replaced with NaN — the serving chaos twin of
    the training ``nan@step`` fault, shared by the in-process replica
    and the subprocess worker so both chaos modes poison IDENTICALLY
    (params_finite is the tripwire that must catch either)."""
    return jax.tree_util.tree_map(
        lambda x: (jnp.full_like(x, jnp.nan)
                   if jnp.issubdtype(x.dtype, jnp.inexact) else x),
        weights)


@jax.jit
def params_finite(weights):
    """ONE device scalar answering "are these params all finite?" — the
    engine-health tripwire the replica router polls (a NaN'd replica
    must be declared sick from its *params*, not inferred from garbage
    token ids, which stay perfectly finite ints). One reduction per
    leaf + a stacked all(): cheap enough to run every few ticks, and a
    separate compiled program, so the committed tick/prefill HLO pins
    never move."""
    TRACE_COUNTS["params_finite"] += 1
    leaves = [jnp.all(jnp.isfinite(x))
              for x in jax.tree_util.tree_leaves(weights)
              if jnp.issubdtype(x.dtype, jnp.inexact)]
    if not leaves:
        return jnp.bool_(True)
    return jnp.all(jnp.stack(leaves))


@jax.jit
def kv_block_gather(cache, block_ids):
    """Pool gather for the KV block stream (ISSUE 12): pull
    ``block_ids`` rows out of every pool leaf in one compiled call.
    ``block_ids`` is always padded to kv_pages with the trash block, so
    EVERY export — any request length, any prefix offset — is this one
    fixed-shape program; the host slices the trash rows off after the
    sync. Returns the pool leaves (cached_key/cached_value per layer
    stack) in tree-flatten order."""
    TRACE_COUNTS["kv_block_gather"] += 1
    return [jnp.take(leaf, block_ids,
                     axis=_pool_block_axis(_leaf_name(path), leaf.ndim))
            for path, leaf in jax.tree_util.tree_leaves_with_path(cache)
            if _leaf_name(path) in POOL_LEAF_AXIS]


@functools.partial(jax.jit, donate_argnames=("cache",))
def kv_block_scatter(cache, block_ids, payload):
    """The import half: scatter ``payload`` (one array per pool leaf,
    block axis padded to kv_pages like ``block_ids``) into the donated
    pool at ``block_ids``. The pad rows carry zeros addressed at the
    trash block — duplicate index-0 writes land harmlessly where
    garbage already goes — so this too is ONE program for every
    import."""
    TRACE_COUNTS["kv_block_scatter"] += 1
    it = iter(payload)

    def put(path, leaf):
        if _leaf_name(path) not in POOL_LEAF_AXIS:
            return leaf
        new = next(it)
        axis = _pool_block_axis(_leaf_name(path), leaf.ndim)
        moved = jnp.moveaxis(leaf, axis, 0)
        out = moved.at[block_ids].set(
            jnp.moveaxis(new.astype(leaf.dtype), axis, 0))
        return jnp.moveaxis(out, 0, axis)

    return jax.tree_util.tree_map_with_path(put, cache)


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs (dynamic per slot — any mix of requests
    shares the one compiled tick). temperature 0 = greedy; top_k <= 0 and
    top_p >= 1 disable their filters; seed starts the request's private
    PRNG stream."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0


@dataclasses.dataclass
class KVBlockPayload:
    """One parked request's complete handoff state on the KV block
    stream (ISSUE 12): everything a decode-role replica needs to
    activate the stream mid-flight, bitwise-equal to a colocated
    engine — the prompt, the tokens generated so far (the prefill-role
    engine's sampled first token rides here, already delivered), the
    sampling contract, and the exact K/V of positions [0, true_len)
    gathered off the exporter's pool. ``leaves`` pairs each pool leaf's
    tree-path name with its ``[num_blocks, ...]`` host array — the
    importer checks the names against its own pool so a geometry or
    model mismatch fails loudly instead of decoding garbage."""

    prompt: np.ndarray
    generated: list[int]
    true_len: int
    block_size: int
    max_new_tokens: int
    sampling: SamplingParams
    stop_ids: tuple
    leaves: list
    # pool storage dtype the leaves were gathered from ("bf16"|"int8" —
    # int8 payloads also carry the scale-plane leaves) and the payload
    # schema version; both are checked at import so a mismatched fleet
    # fails with a sentence, not garbage tokens
    kv_dtype: str = "bf16"
    wire_version: int = KV_WIRE_VERSION
    # the ORIGIN router submit as unix-epoch seconds (ISSUE 17
    # satellite): the importer maps it onto its own clock so a
    # handed-off stream's end-to-end TTFT measures from the FIRST
    # router submit, not decode-replica-local; None from pre-ISSUE-17
    # exporters
    origin_t: float | None = None
    # the request's TraceContext wire dict — the handoff keeps the
    # stream on ONE connected trace across replicas
    trace: dict | None = None
    # per-request sliding-window override (ISSUE 18 satellite): the
    # EFFECTIVE kv_sink/kv_window the exporting slot ran under, so a
    # reattached/handed-off stream keeps its tightened mask — without
    # these, retired-block positions (gathered as trash) would be
    # ATTENDED on the importer. None = the importer's pool defaults
    kv_sink: int | None = None
    kv_window: int | None = None

    @property
    def num_blocks(self) -> int:
        return -(-self.true_len // self.block_size)

    @property
    def nbytes(self) -> int:
        return int(self.prompt.nbytes
                   + sum(a.nbytes for _, a in self.leaves))


@dataclasses.dataclass
class PrefixBlockPayload:
    """A radix-cached prefix shipped over the same KV stream (the
    fleet prefix cache's remote-hit path): whole cached blocks of
    ``tokens`` (a block-multiple), gathered from the owning replica's
    pool, for the receiver to adopt into its pool + radix as REMOTE
    entries — prefilled once per fleet, served everywhere."""

    tokens: np.ndarray
    block_size: int
    leaves: list
    kv_dtype: str = "bf16"
    wire_version: int = KV_WIRE_VERSION

    @property
    def num_blocks(self) -> int:
        return len(self.tokens) // self.block_size

    @property
    def nbytes(self) -> int:
        return int(self.tokens.nbytes
                   + sum(a.nbytes for _, a in self.leaves))


def _np_dtype(name: str):
    """np.dtype by name, reaching into ml_dtypes for the low-precision
    names (bfloat16 et al.) numpy itself cannot resolve."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def _leaves_to_wire(leaves) -> list:
    return [dict(name=n, dtype=str(a.dtype), shape=list(a.shape),
                 data=base64.b64encode(
                     np.ascontiguousarray(a).tobytes()).decode("ascii"))
            for n, a in leaves]


def _leaves_from_wire(rows) -> list:
    return [(r["name"],
             np.frombuffer(base64.b64decode(r["data"]),
                           dtype=_np_dtype(r["dtype"]))
             .reshape(r["shape"]))
            for r in rows]


def kv_payload_to_wire(p: KVBlockPayload) -> dict:
    """Serialize a KVBlockPayload for the subprocess worker's line-JSON
    protocol (base64 block arrays — the same wire the submit/step ops
    ride, so disagg needs no second transport)."""
    return dict(prompt=[int(t) for t in p.prompt],
                generated=list(p.generated), true_len=p.true_len,
                block_size=p.block_size,
                max_new_tokens=p.max_new_tokens,
                sampling=dataclasses.asdict(p.sampling),
                stop_ids=list(p.stop_ids),
                leaves=_leaves_to_wire(p.leaves),
                kv_dtype=p.kv_dtype, wire_version=p.wire_version,
                origin_t=p.origin_t, trace=p.trace,
                kv_sink=p.kv_sink, kv_window=p.kv_window)


def kv_payload_from_wire(d: dict) -> KVBlockPayload:
    return KVBlockPayload(
        prompt=np.asarray(d["prompt"], np.int32),
        generated=[int(t) for t in d["generated"]],
        true_len=int(d["true_len"]), block_size=int(d["block_size"]),
        max_new_tokens=int(d["max_new_tokens"]),
        sampling=SamplingParams(**d["sampling"]),
        stop_ids=tuple(d["stop_ids"]),
        leaves=_leaves_from_wire(d["leaves"]),
        # pre-v2 senders carried neither field: report them as v1 so the
        # importer's version check names the mismatch instead of KeyError
        kv_dtype=str(d.get("kv_dtype", "bf16")),
        wire_version=int(d.get("wire_version", 1)),
        origin_t=d.get("origin_t"), trace=d.get("trace"),
        # absent on pre-ISSUE-18 senders: None = pool defaults, the
        # exact pre-18 behavior
        kv_sink=(None if d.get("kv_sink") is None
                 else int(d["kv_sink"])),
        kv_window=(None if d.get("kv_window") is None
                   else int(d["kv_window"])))


def prefix_payload_to_wire(p: PrefixBlockPayload) -> dict:
    return dict(tokens=[int(t) for t in p.tokens],
                block_size=p.block_size,
                leaves=_leaves_to_wire(p.leaves),
                kv_dtype=p.kv_dtype, wire_version=p.wire_version)


def prefix_payload_from_wire(d: dict) -> PrefixBlockPayload:
    return PrefixBlockPayload(
        tokens=np.asarray(d["tokens"], np.int32),
        block_size=int(d["block_size"]),
        leaves=_leaves_from_wire(d["leaves"]),
        kv_dtype=str(d.get("kv_dtype", "bf16")),
        wire_version=int(d.get("wire_version", 1)))


class Request:
    """One submitted generation: prompt + budget + sampling + stop ids,
    and the engine-filled lifecycle (tokens as they stream, timestamps,
    finish reason). Host-side only — nothing here touches the device."""

    _ids = itertools.count()

    def __init__(self, prompt, max_new_tokens: int,
                 sampling: SamplingParams, stop_ids: tuple[int, ...],
                 on_token=None, deadline_s: float | None = None,
                 generated=None):
        self.id = next(Request._ids)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = max_new_tokens
        self.sampling = sampling
        self.stop_ids = stop_ids
        self.on_token = on_token
        self.deadline_s = deadline_s
        # resume-from-tokens (the router's failover redispatch): the
        # stream's already-generated suffix is pre-seeded, so admission
        # re-prefills prompt+generated and the engine only ever DELIVERS
        # tokens past ``resumed_from`` — on_token never re-fires for
        # tokens the client already has
        self.new_tokens: list[int] = ([int(t) for t in generated]
                                      if generated is not None else [])
        self.resumed_from = len(self.new_tokens)
        self.slot: int | None = None
        self.done = False
        self.finish_reason: str | None = None
        self.submit_time: float | None = None
        self.first_token_time: float | None = None
        self.finish_time: float | None = None
        # paged-engine lifecycle (zero on the dense engine): prompt
        # tokens admitted from the prefix cache instead of prefill
        # compute, chunked-prefill calls paid, and preempt-requeue
        # round-trips survived (a preempted request resumes by
        # re-prefilling prompt + already-generated tokens — its output
        # stream is unchanged)
        self.prefix_hit_tokens = 0
        self.prefill_chunks = 0
        self.preemptions = 0
        # disaggregation lifecycle (ISSUE 12): prompt tokens admitted
        # from REMOTE (fleet-shipped) prefix blocks, and the
        # prefill-role handoff flags — a prefill_only request parks
        # after its first token for export_kv_blocks instead of
        # decoding in place
        self.remote_hit_tokens = 0
        self.prefill_only = False
        self.parked = False
        # speculative-decoding lifecycle (zero when spec is off): draft
        # proposals made for this request and how many the target kept —
        # accepted/draft is the request's acceptance rate
        self.draft_tokens = 0
        self.accepted_tokens = 0
        # per-request KV window/sink override (ISSUE 15): the EFFECTIVE
        # values after submit() clamps to the pool config; None = the
        # engine-static defaults
        self.kv_window: int | None = None
        self.kv_sink: int | None = None
        # persistent sessions (ISSUE 18): a tagged stream's KV parks in
        # the engine's HBM-resident session tier at retirement instead
        # of freeing; ``tenant`` rides along for the store's per-tenant
        # session budgets
        self.session_id: str | None = None
        self.tenant: str = "default"
        # distributed tracing (ISSUE 17): the router-minted
        # TraceContext this request's engine-side spans attach to, and
        # the ORIGIN router submit mapped onto THIS process's
        # perf_counter clock (equal to submit_time for a locally-born
        # request; earlier for one that arrived via handoff/redispatch)
        self.trace = None
        self.origin_submit_time: float | None = None

    @property
    def ttft_e2e_s(self) -> float | None:
        """Time to first token measured from the ORIGIN router submit
        (ISSUE 17 satellite) — on a handed-off stream this spans queue
        + prefill + handoff end-to-end, where ``ttft_s`` restarts at
        the import. Falls back to ``ttft_s`` when no origin rode in."""
        if self.first_token_time is None:
            return None
        if self.origin_submit_time is None:
            return self.ttft_s
        return self.first_token_time - self.origin_submit_time

    @property
    def output_ids(self) -> np.ndarray:
        """prompt + generated continuation (int32 [len])."""
        return np.concatenate(
            [self.prompt, np.asarray(self.new_tokens, np.int32)])

    @property
    def ttft_s(self) -> float | None:
        """Time to first token, queue wait included."""
        if self.first_token_time is None or self.submit_time is None:
            return None
        return self.first_token_time - self.submit_time

    @property
    def decode_tokens_per_s(self) -> float | None:
        """Post-prefill decode rate of this request (None until done or
        when the request finished at its first token)."""
        if self.finish_time is None or self.first_token_time is None:
            return None
        dt = self.finish_time - self.first_token_time
        # resumed tokens were generated elsewhere — only tokens THIS
        # engine decoded belong in its rate
        n = len(self.new_tokens) - self.resumed_from - 1
        if n <= 0 or dt <= 0:
            return None
        return round(n / dt, 3)


class ServingEngine:
    """The host scheduler over the compiled tick/prefill pair.

    Args:
      model: a causal LM module (GPT2 / Llama ...) — decode or train
        config; the engine derives its slot-decode twin either way.
      params: the trained variables, possibly sharded (pass ``mesh``).
      num_slots: concurrent requests resident in the KV cache — the
        engine's batch dim, fixed at compile time.
      prefill_bucket: prompts are right-padded up to this multiple so
        variable lengths reuse a handful of prefill programs (clamped to
        max_seq_len).
      candidates: static top-k candidate width of the per-slot sampler
        (per-request top_k caps here; see inference.sample_slots).
      mesh: optional jax mesh the params live on (tp/dp) — tick/prefill
        trace under it, exactly like generate().
      telemetry / telemetry_dir: a ServingTelemetry (or a run dir to
        build one) for spans + serve-metric JSONL; None = off.
      block_size: > 0 switches to the PAGED KV cache (ISSUE 7): one pool
        of ``num_blocks`` blocks of this many tokens replaces the dense
        per-slot rows — HBM is then bounded by tokens actually resident,
        not slots x max_seq_len. Must divide max_seq_len. 0 = the dense
        engine (unchanged). A model whose config already sets
        kv_block_size/kv_blocks turns paging on implicitly.
      num_blocks: pool size in blocks (block 0 is the reserved trash
        block). Default = dense-equivalent HBM (num_slots full contexts
        + 1); SHRINK it to oversubscribe slots — exhaustion first evicts
        prefix-cache LRU entries, then preempts the youngest resident
        request (requeued; it resumes by re-prefilling prompt +
        generated, its output stream unchanged).
      prefill_chunk: paged prompts prefill in fixed chunks of this many
        tokens (default prefill_bucket, rounded to a block multiple)
        interleaved with decode ticks, so a long admission cannot
        head-of-line-block resident streams' tokens.
      prefix_cache: host-side radix cache over full prompt blocks —
        prompts sharing a cached prefix admit by block REFERENCE
        (refcounted, copy-on-write by construction: shared blocks are
        never written) instead of re-running prefill. On by default in
        paged mode.
      prefill_chunks_per_step: chunk calls per step() once slots are
        decoding (1 = maximally latency-protective interleaving).
      spec_k: > 0 turns on SPECULATIVE decoding (ISSUE 8): every tick a
        draft model proposes spec_k tokens per slot and the target
        verifies all of them in ONE forward (spec_decode_tick) with
        lossless rejection sampling — greedy outputs stay bitwise-equal
        to generate()'s, sampled outputs distribution-equal, whatever
        the draft quality; only the acceptance rate (and the speedup)
        depends on it. Requires the paged engine (block_size > 0):
        rejected-suffix and past-context K/V drop into the trash block
        instead of needing a rollback. 0 = the plain tick (default, no
        behavior change).
      draft_config: the draft's TransformerConfig (same vocab; usually a
        reduced-depth clone of the target — inference.truncated_draft
        builds config+params from the target in one call). None
        self-drafts with the target model itself: acceptance ~1, the
        correctness/bring-up configuration.
      draft_params: the draft's variables (required with draft_config).
        A draft whose config sets ``spec_heads > 0`` (ISSUE 16 —
        inference.make_draft builds one) switches the tick to the
        head-parallel program (spec_decode_tick_heads): one draft
        forward proposes all spec_k tokens instead of a spec_k+1-step
        rollout; needs spec_heads >= spec_k - 1.
      adaptive_k: with spec_k > 0, drive each slot's EFFECTIVE proposal
        depth from its measured acceptance EMA (ISSUE 16): a slot whose
        draft keeps missing proposes fewer tokens next round, one whose
        draft keeps landing proposes the full spec_k. The depth is a
        masked width inside the fixed spec_k-wide compiled program — a
        dynamic operand, ZERO recompiles as it moves — and the rejection
        kernel stays lossless at any depth (the forced-stop bonus token
        draws from the FULL target distribution; greedy streams are
        bitwise-invariant to the mask). Default off: the accounting
        (draft_tokens counts the effective depth) and the extra operand
        change nothing unless asked for.
      compile_cache: the persistent AOT executable cache (ISSUE 10,
        runtime/compile_cache.py): a CompileCache, a directory path, or
        the default "auto" (the PTD_COMPILE_CACHE env contract; off
        when unset). With a cache attached, every compiled program —
        tick/prefill/spec/probe — dispatches through an AOT executable
        that is DESERIALIZED from disk on a hit and
        lower().compile()'d + published on a miss, so a restarted or
        respawned engine reaches its first token with zero XLA
        compiles; warmup() collapses to one probe round per bucket.
        The contract is never-fails: any cache defect quarantines the
        entry and the engine falls back to the plain jit path.
      kv_dtype: paged pool storage dtype (ISSUE 13): "bf16" (default —
        the model dtype; the bitwise-vs-generate() contract holds) or
        "int8" — blocks store int8 codes plus per-(token, head) fp32
        scale planes (extra cache leaves), quantized at block-write
        time and dequantized inside the attention read
        (ops/quant.kv_quantize / kv_dequantize). ~1.9x more resident
        tokens per HBM byte at equal pool bytes; outputs are
        tolerance-accurate, not bitwise. None inherits the model cfg.
      kv_sink_tokens / kv_window_tokens: sink + sliding-window
        attention over the paged cache (StreamingLLM-style): a query at
        position p attends cache position j iff ``j < kv_sink_tokens or
        j > p - kv_window_tokens``. Both are STATIC block-multiples
        (no retrace as streams grow). Middle blocks that fall fully
        dead are RETIRED mid-stream — decref'd back to the allocator
        while the stream lives — so a long stream holds sink + window
        blocks, not its whole history, and the freed capacity
        immediately backs new admissions. 0/0 = full attention
        (default). None inherits the model cfg.
      paged_attn: the decode tick's attention implementation:
        "gather" (XLA gather + masked dense — the bitwise reference),
        "pallas" (the fused paged flash kernel,
        ops/pallas_attention.paged_flash_attention — no [slots,
        attend_len] gather materialization), or None (default) →
        the PTD_PAGED_ATTN env var, else "auto" = pallas on TPU
        backends, gather elsewhere. Prefill chunks and the spec tick's
        draft rollout always use the gather read.
    """

    #: adaptive-k acceptance-EMA smoothing (ISSUE 16): high enough to
    #: track a request moving between easy and hard spans within its own
    #: lifetime, low enough that one unlucky round doesn't crater the
    #: depth
    SPEC_EMA_ALPHA = 0.2

    def __init__(self, model, params, *, num_slots: int = 4,
                 prefill_bucket: int = 128, candidates: int = 64,
                 mesh=None, telemetry: ServingTelemetry | None = None,
                 telemetry_dir=None, block_size: int = 0,
                 num_blocks: int | None = None,
                 prefill_chunk: int | None = None,
                 prefix_cache: bool = True,
                 prefill_chunks_per_step: int = 1,
                 spec_k: int = 0, draft_config=None, draft_params=None,
                 adaptive_k: bool = False,
                 compile_cache="auto", kv_dtype: str | None = None,
                 kv_sink_tokens: int | None = None,
                 kv_window_tokens: int | None = None,
                 paged_attn: str | None = None, trace=None,
                 session_store=None, session_hbm_max: int = 4):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = num_slots
        self.candidates = candidates
        self.mesh = mesh
        if block_size == 0 and model.cfg.kv_block_size:
            # a model already configured paged carries the knobs
            block_size = model.cfg.kv_block_size
            num_blocks = num_blocks or model.cfg.kv_blocks
        self.paged = block_size > 0
        # KV-compression knobs (ISSUE 13): None inherits the model cfg,
        # so a model already configured int8/windowed just works
        kv_dtype = model.cfg.kv_dtype if kv_dtype is None else kv_dtype
        kv_sink_tokens = (model.cfg.kv_sink_tokens
                          if kv_sink_tokens is None else kv_sink_tokens)
        kv_window_tokens = (model.cfg.kv_window_tokens
                            if kv_window_tokens is None
                            else kv_window_tokens)
        if paged_attn is None:
            paged_attn = (model.cfg.paged_attn
                          if model.cfg.paged_attn != "gather"
                          else os.environ.get("PTD_PAGED_ATTN", "auto"))
        if paged_attn not in ("auto", "gather", "pallas"):
            raise ValueError(
                f"paged_attn must be 'auto', 'gather' or 'pallas', got "
                f"{paged_attn!r}")
        if paged_attn == "auto":
            # backend-aware default: the fused kernel is the hot path on
            # real accelerators; CPU (tests, dev) keeps the gather read,
            # whose decode tick is bitwise generate()'s
            paged_attn = ("pallas" if jax.default_backend() == "tpu"
                          else "gather")
        if not self.paged and (kv_dtype != "bf16" or kv_sink_tokens
                               or kv_window_tokens):
            raise ValueError(
                "kv_dtype / kv_sink_tokens / kv_window_tokens are "
                "paged-engine knobs (ISSUE 13) — pass block_size > 0")
        self.kv_dtype = kv_dtype
        self.kv_sink_tokens = int(kv_sink_tokens)
        self.kv_window_tokens = int(kv_window_tokens)
        self.paged_attn = paged_attn if self.paged else "gather"
        if self.paged:
            max_len = model.cfg.max_seq_len
            if max_len % block_size:
                raise ValueError(
                    f"block_size {block_size} must divide max_seq_len "
                    f"{max_len}")
            pages = max_len // block_size
            if num_blocks is None:
                # dense-equivalent HBM by default: one full context per
                # slot, plus the trash block — shrink it to oversubscribe
                num_blocks = num_slots * pages + 1
            if num_blocks < pages + 1:
                raise ValueError(
                    f"num_blocks {num_blocks} cannot back even one "
                    f"full-context request (need >= {pages + 1}: "
                    f"max_seq_len/block_size + the trash block)")
            self.block_size = block_size
            self.num_blocks = num_blocks
            # per-request window/sink overrides (ISSUE 15) need the
            # per-slot mask leaves; the Pallas kernel takes sink/window
            # STATICALLY, so overrides stay gather-only and a pallas
            # pool keeps the exact PR 12 program
            self.per_slot_limits = bool(self.kv_window_tokens
                                        and self.paged_attn != "pallas")
            self._tick_model, self._chunk_model = paged_slot_models(
                model, num_slots, block_size, num_blocks,
                kv_dtype=kv_dtype, kv_sink_tokens=self.kv_sink_tokens,
                kv_window_tokens=self.kv_window_tokens,
                paged_attn=self.paged_attn,
                per_slot_kv_limits=self.per_slot_limits)
            self._prefill_model = None
        else:
            self.block_size = 0
            self.num_blocks = 0
            self.per_slot_limits = False
            self._tick_model, self._prefill_model = slot_models(
                model, num_slots)
        self.cfg = self._tick_model.cfg
        self.bucket = max(1, min(prefill_bucket, self.cfg.max_seq_len))
        if self.paged:
            chunk = prefill_chunk if prefill_chunk else self.bucket
            # chunks must tile the block grid (a chunk's writes stay in
            # whole blocks) and fit the context
            self.chunk = min(self._round_up(chunk, block_size),
                             self.cfg.max_seq_len)
            self._chunks_per_step = max(1, prefill_chunks_per_step)
            self._alloc = BlockAllocator(num_blocks, block_size)
            self._radix = (RadixPrefixCache(self._alloc) if prefix_cache
                           else None)
            self._tables = np.zeros((num_slots, self.cfg.kv_pages),
                                    np.int32)
            self._lengths = np.zeros(num_slots, np.int32)
            self._slot_blocks: list[list[int]] = [
                [] for _ in range(num_slots)]
            self._admit_order = np.zeros(num_slots, np.int64)
            self._admit_seq = itertools.count(1)
            self._prefilling: dict | None = None
            # prefill_only requests parked after their first token,
            # keyed by request id: {req, slot, length} — the slot holds
            # the blocks but leaves the tick's view (all-trash table,
            # length 0) until export_kv_blocks takes custody
            self._prefilled: dict[int, dict] = {}
            # per-slot EFFECTIVE sink/window (ISSUE 15): engine defaults
            # until a request with an override activates in the slot.
            # Host truth for both the compiled mask (stamped into the
            # kv_sinks/kv_windows cache leaves when dirty) and the
            # block-retirement sweep — the two MUST agree, or retirement
            # would point still-attended positions at the trash block
            self._slot_sinks = np.full(num_slots, self.kv_sink_tokens,
                                       np.int32)
            self._slot_windows = np.full(num_slots, self.kv_window_tokens,
                                         np.int32)
            # dirty from birth: _zero_cache zeroes the kv_sinks/
            # kv_windows leaves too (Flax init defaults never run), so
            # the engine defaults must be stamped before the first tick
            self._limits_dirty = self.per_slot_limits
            # persistent sessions (ISSUE 18): the HBM-RESIDENT tier —
            # finished session streams keyed by session_id, each
            # holding its slot's block list (refcounts transferred off
            # the slot at retirement). dict order == LRU; past
            # ``session_hbm_max`` the eldest demotes into a
            # KVBlockPayload (gather — the existing AOT program) bound
            # for ``session_store`` (host-DRAM/disk tiers) or the
            # spill queue a router drains over the wire
            self._sessions: dict[str, dict] = {}
            self._session_spill: list[tuple[str, str, KVBlockPayload]] = []
        self.session_store = session_store
        self.session_hbm_max = max(0, int(session_hbm_max))
        if spec_k < 0:
            raise ValueError(f"spec_k must be >= 0, got {spec_k}")
        self.spec_k = spec_k
        if adaptive_k and not spec_k:
            raise ValueError(
                "adaptive_k without spec_k > 0 — per-slot proposal depth "
                "is a speculative-decode knob")
        self.adaptive_k = bool(adaptive_k)
        self._spec_heads = 0
        self.draft_swaps = 0
        if spec_k:
            if not self.paged:
                raise ValueError(
                    "spec_k > 0 requires the paged engine (block_size > "
                    "0): the verify forward's rejected-suffix K/V writes "
                    "must drop into the trash block, not clamp onto live "
                    "dense rows")
            if draft_config is not None and draft_params is None:
                raise ValueError(
                    "draft_config without draft_params — pass both "
                    "(inference.truncated_draft builds the pair), or "
                    "neither to self-draft with the target")
            if draft_config is None:
                draft_config, draft_params = model.cfg, params
            if draft_config.vocab_size != model.cfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_config.vocab_size} != target "
                    f"vocab {model.cfg.vocab_size}")
            # multi-token proposal heads (ISSUE 16): the base head
            # proposes token 1, head j token j+2 — spec_k proposals need
            # spec_k - 1 heads
            self._spec_heads = int(draft_config.spec_heads)
            if 0 < self._spec_heads < spec_k - 1:
                raise ValueError(
                    f"draft has {self._spec_heads} proposal heads but "
                    f"spec_k={spec_k} needs {spec_k - 1} (build the "
                    f"draft with inference.make_draft("
                    f"spec_heads=spec_k-1))")
            # the draft shares the target's block TABLES (same block ids
            # into its own shallower pool), so its geometry must match
            draft_base = model.clone(cfg=dataclasses.replace(
                draft_config, max_seq_len=model.cfg.max_seq_len))
            # the draft pool rides the same compression + window (it
            # shares block IDS with the target, so a retired block must
            # be dead for both) but keeps the gather read: its rollout
            # runs inside a scanned spec tick, not the plain decode tick
            self._draft_tick_model, self._draft_chunk_model = \
                paged_slot_models(draft_base, num_slots, self.block_size,
                                  self.num_blocks, kv_dtype=kv_dtype,
                                  kv_sink_tokens=self.kv_sink_tokens,
                                  kv_window_tokens=self.kv_window_tokens,
                                  per_slot_kv_limits=self.per_slot_limits)
            # unbox (nn.meta) at boot: callers hand model.init output
            # with LogicallyPartitioned boxes as often as plain trees,
            # and the hot-swap path compares TREEDEFS — a boxed boot
            # tree would refuse every trainer-produced (unboxed) swap
            import flax.linen as nn

            self._draft_weights = nn.meta.unbox(
                draft_params["params"] if "params" in draft_params
                else draft_params)
        self._weights = params["params"] if "params" in params else params
        with self._mesh_ctx():
            self._cache = _zero_cache(
                self._tick_model, jnp.zeros((num_slots, 1), jnp.int32))
            if spec_k:
                self._draft_cache = _zero_cache(
                    self._draft_tick_model,
                    jnp.zeros((num_slots, 1), jnp.int32))
        # the KV cache HBM footprint (pool or dense rows) — the bench's
        # capacity-per-byte denominator; the draft pool is accounted
        # separately (it shares block IDs, not bytes)
        self.kv_hbm_bytes = kv_cache_bytes(self._cache)
        self.draft_kv_hbm_bytes = (
            kv_cache_bytes(self._draft_cache) if spec_k else 0)
        kd = np.asarray(jax.random.key_data(jax.random.key(0)))
        self._key_data = np.zeros((num_slots,) + kd.shape, kd.dtype)
        self._tokens = np.zeros(num_slots, np.int32)
        self._counts = np.zeros(num_slots, np.int32)
        self._temps = np.zeros(num_slots, np.float32)
        self._top_ks = np.zeros(num_slots, np.int32)
        self._top_ps = np.ones(num_slots, np.float32)
        if spec_k:
            # per-slot speculative round state (ISSUE 16). Adaptive k:
            # acceptance EMA drives each slot's effective proposal depth
            # (a DYNAMIC operand of the fixed spec_k-wide tick — zero
            # recompiles as it moves). Heads mode: the previous round's
            # emitted buffer / live index / draft write position — the
            # head-parallel draft forward's input (one round behind the
            # target, see spec_decode_tick_heads).
            self._accept_ema = np.ones(num_slots, np.float64)
            self._k_eff = np.full(num_slots, spec_k, np.int32)
            self._spec_prev_tokens = np.zeros((num_slots, spec_k + 1),
                                              np.int32)
            self._spec_prev_idx = np.zeros(num_slots, np.int32)
            self._spec_prev_start = np.zeros(num_slots, np.int32)
        self._free = list(reversed(range(num_slots)))  # pop() -> slot 0
        self._queue: collections.deque[Request] = collections.deque()
        self._active: dict[int, Request] = {}
        self._draining = False
        # health-snapshot state (ISSUE 9): ``_progress`` is a MONOTONIC
        # device-work watermark (never reset by reset_stats) — it moves
        # exactly when a compiled call completed and synced, so a router
        # watching it can tell a hung replica from an idle one; the TTFT
        # EMA is the router's load-balancing latency signal; ``_sick``
        # holds the last params-finite probe verdict
        self._progress = 0
        self._ttft_ema: float | None = None
        self._sick = False
        if telemetry is None and telemetry_dir is not None:
            telemetry = ServingTelemetry(telemetry_dir)
        self.telemetry = telemetry
        # request tracing (ISSUE 17): a telemetry.tracing.RequestTracer
        # — the router shares its own with in-process engines, a
        # subprocess worker builds one from PTD_TRACE + the telemetry
        # dir. None (the default) means OFF: every emit site guards on
        # it, so off costs nothing per tick. The engine never closes it
        # (the owner does); rows are line-buffered, so a crashed worker
        # loses nothing.
        self.trace = trace
        # AOT executable table (ISSUE 10): with a compile cache
        # attached, every compiled-program call goes through _aot_call —
        # a per-program jax.stages.Compiled either deserialized from the
        # cache or lower().compile()'d once and published. Without one
        # (the default when PTD_COMPILE_CACHE is unset) the engine calls
        # the module-level jit wrappers exactly as before.
        self._compile_cache = CompileCache.resolve(compile_cache)
        self._exec: dict[str, object] = {}
        self._aot_failed: set[str] = set()
        #: name -> "hit" | "miss" per AOT-resolved program (tests and
        #: the coldstart bench read this after warmup)
        self.aot_outcomes: dict[str, str] = {}
        self.reset_stats()

    # ------------------------------------------------------------------
    # submission

    def submit(self, prompt, *, max_new_tokens: int,
               sampling: SamplingParams | None = None, stop_ids=None,
               on_token=None, deadline_s: float | None = None,
               generated=None, prefill_only: bool = False,
               kv_window: int | None = None,
               kv_sink: int | None = None,
               trace=None, origin_t: float | None = None,
               session_id: str | None = None,
               tenant: str = "default") -> Request:
        """Queue one request; returns its handle (tokens stream into
        ``handle.new_tokens`` / the on_token callback as the engine
        steps). ``stop_ids`` accepts a single id or a sequence.
        ``deadline_s`` is a wall-clock budget from submission: a request
        past it — queued or mid-decode — is retired with finish_reason
        "deadline" (whatever tokens it produced stay delivered) and its
        slot is freed for the next arrival; the other slots are never
        disturbed. The robustness knob a serving tier needs under
        overload — a stuck client budget must shed, not wedge, the
        engine.

        ``generated`` resumes a stream FROM TOKENS (the replica
        router's mid-stream failover, ISSUE 9): admission re-prefills
        prompt+generated — the exact mechanism the paged engine's
        preempt-requeue already uses, factored up to the public API —
        and decoding continues with the per-token fold_in count at
        ``len(generated)``, so the continuation is bitwise what the
        uninterrupted run would have produced (greedy AND seeded
        sampling). ``max_new_tokens`` still bounds the TOTAL new-token
        stream, generated prefix included; only tokens past it are
        delivered/streamed.

        ``prefill_only`` (ISSUE 12, paged only) is the PREFILL-ROLE
        half of disaggregation: the request runs chunked prefill,
        delivers its first token, then PARKS instead of decoding — its
        K/V blocks wait for ``export_kv_blocks`` to hand them to a
        decode-role replica. A request already done at its first token
        (stop id / max_new_tokens == 1) finishes normally and never
        parks.

        ``kv_window`` / ``kv_sink`` (ISSUE 15) TIGHTEN this request's
        sliding-window attention below the pool's static config: values
        are clamped to the pool's (you can never widen past what every
        slot's HBM budget was sized for) and rounded up to whole
        blocks (retirement granularity). They take effect from the
        first DECODED token — prefill masks under the pool window —
        and the retirement sweep frees the request's dead blocks at
        its own tighter horizon. Requires a windowed gather-path pool:
        a dense engine, a windowless pool (there are no mask leaves to
        stamp — the compiled programs are exactly PR 12's) and the
        Pallas kernel (sink/window are STATIC kernel parameters there)
        all reject loudly. The KV handoff wire CARRIES the effective
        override (ISSUE 18), so a ``prefill_only`` stream keeps its
        tightened mask on the decode replica.

        ``session_id`` (ISSUE 18) tags the stream as a persistent
        SESSION: at retirement its KV blocks park in the engine's
        HBM-resident session tier instead of freeing, and a later
        submit with the same id rides them as a radix prefix hit (or
        pulls them back up from the attached ``session_store``'s
        host-DRAM/disk tiers). ``tenant`` rides along for the store's
        per-tenant session budgets."""
        if kv_window is not None or kv_sink is not None:
            if not self.paged:
                raise ValueError(
                    "per-request kv_window/kv_sink need the paged engine "
                    "(block_size > 0)")
            if not self.kv_window_tokens:
                raise ValueError(
                    "per-request kv_window/kv_sink need a windowed pool "
                    "(engine kv_window_tokens > 0): a windowless pool "
                    "compiles no per-slot mask leaves")
            if not self.per_slot_limits:
                raise ValueError(
                    "per-request kv_window/kv_sink need paged_attn="
                    "'gather' — the Pallas kernel takes sink/window as "
                    "STATIC parameters")
            if kv_window is not None and kv_window < 1:
                raise ValueError(
                    f"kv_window must be >= 1, got {kv_window}")
            if kv_sink is not None and kv_sink < 0:
                raise ValueError(f"kv_sink must be >= 0, got {kv_sink}")
        if prefill_only:
            if not self.paged:
                raise ValueError(
                    "prefill_only requires the paged engine "
                    "(block_size > 0): KV blocks are the handoff unit")
            if self.spec_k:
                raise ValueError(
                    "prefill_only does not compose with spec_k > 0 "
                    "(the draft pool is not on the KV stream)")
        if session_id is not None:
            if not self.paged:
                raise ValueError(
                    "session_id requires the paged engine "
                    "(block_size > 0): sessions park KV blocks")
            if self.spec_k:
                raise ValueError(
                    "session_id does not compose with spec_k > 0 "
                    "(the draft pool is not on the session tier)")
            from pytorchdistributed_tpu.serving.sessions import \
                session_id_ok
            if not session_id_ok(session_id):
                raise ValueError(
                    f"malformed session_id {session_id!r} (want "
                    f"[A-Za-z0-9][A-Za-z0-9._:-]*, <= 128 chars)")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if deadline_s is not None and deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0, got {deadline_s}")
        if generated is not None and len(generated) >= max_new_tokens:
            raise ValueError(
                f"generated carries {len(generated)} tokens but "
                f"max_new_tokens is {max_new_tokens} — nothing left to "
                f"resume")
        if prompt.size + max_new_tokens > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt_len {prompt.size} + max_new_tokens "
                f"{max_new_tokens} exceeds max_seq_len "
                f"{self.cfg.max_seq_len}")
        req = Request(prompt, max_new_tokens, sampling or SamplingParams(),
                      stop_ids_tuple(stop_ids), on_token,
                      deadline_s=deadline_s, generated=generated)
        req.prefill_only = prefill_only
        if kv_window is not None or kv_sink is not None:
            req.kv_sink, req.kv_window = self._clamp_limits(
                kv_sink, kv_window)
        req.session_id = session_id
        req.tenant = str(tenant)
        if session_id is not None:
            # reattach (ISSUE 18): a parked resident session's blocks
            # publish into the radix (turn-2 prefill rides them as a
            # prefix hit, bitwise-equal to a full prefill); a session
            # in the store's host-DRAM/disk tiers seeds its full
            # blocks back into the pool the same way. A miss or a
            # declined tier just means a plain re-prefill — lossless.
            self._reattach_session(session_id)
        req.submit_time = time.perf_counter()
        # distributed tracing + origin timestamp (ISSUE 17): ``trace``
        # is the router-minted TraceContext (a wire dict from the
        # subprocess protocol is accepted as-is); ``origin_t`` the
        # FIRST router submit as unix-epoch seconds, mapped onto this
        # process's clock so TTFT-e2e survives redispatch across
        # processes
        if trace is not None:
            req.trace = (trace if isinstance(trace, TraceContext)
                         else TraceContext.from_wire(trace))
        req.origin_submit_time = (
            req.submit_time if origin_t is None
            else _trace_from_unix(float(origin_t)))
        self._queue.append(req)
        return req

    # ------------------------------------------------------------------
    # the scheduler loop

    def step(self) -> dict:
        """One scheduler iteration: shed deadline-expired requests, admit
        prefills while slots are free (paged: at most
        ``prefill_chunks_per_step`` chunks once slots are decoding, so a
        long admission interleaves with — instead of blocking — resident
        streams), then ONE decode tick over all slots; deliver + retire
        from the synced tokens. Returns a small stats dict."""
        if self._draining:
            self.drain()
            return {"admitted": 0, "decoded": 0, "expired": 0,
                    "active": 0, "queued": 0}
        expired = self._expire_deadlines()
        admitted = 0
        if self.paged:
            admitted = self._paged_admissions()
        else:
            while self._free and self._queue:
                self._admit(self._queue.popleft())
                admitted += 1
        decoded = 0
        if self.paged and self._active:
            self._grow_slots()  # back this tick's write positions
        if self.per_slot_limits and self._limits_dirty:
            self._stamp_slot_limits()
        if self._active and self.spec_k:
            decoded = self._spec_step()
        elif self._active:
            t0 = time.perf_counter()
            with self._span("serve/decode_tick"), self._mesh_ctx():
                # one shared per-slot argument tail; the paged tick just
                # prepends the host-stamped block tables and lengths
                name, tick, head = (("paged_decode_tick",
                                     paged_decode_tick,
                                     (jnp.asarray(self._tables),
                                      jnp.asarray(self._lengths)))
                                    if self.paged
                                    else ("decode_tick", decode_tick, ()))
                self._cache, nxt = self._aot_call(
                    name, tick, (self._tick_model,),
                    (self._weights, self._cache, *head,
                     jnp.asarray(self._tokens),
                     jnp.asarray(self._key_data),
                     jnp.asarray(self._counts),
                     jnp.asarray(self._temps),
                     jnp.asarray(self._top_ks),
                     jnp.asarray(self._top_ps)),
                    dict(candidates=self.candidates))
                toks = np.asarray(nxt)  # host sync: streaming delivery
            dt = time.perf_counter() - t0
            self._counts += 1
            self._progress += 1
            st = self._stats
            st["ticks"] += 1
            st["tick_s"] += dt
            st["occupancy_sum"] += len(self._active) / self.num_slots
            row = {}
            if self.paged:
                used = self._alloc.usable - self._alloc.free_count
                st["block_used_sum"] += used / self._alloc.usable
                st["peak_blocks_used"] = max(st["peak_blocks_used"], used)
                row = dict(blocks_used=used,
                           blocks_free=self._alloc.free_count)
                for slot in self._active:
                    self._lengths[slot] += 1  # this tick's write landed
            for slot, req in list(self._active.items()):
                self._deliver(req, int(toks[slot]))
                decoded += 1
            st["decode_tokens"] += decoded
            if self.telemetry is not None:
                self.telemetry.tick(
                    tick=st["ticks"], tick_ms=round(dt * 1e3, 3),
                    active=len(self._active), queued=len(self._queue),
                    slot_occupancy=round(decoded / self.num_slots, 4),
                    **row)
        return {"admitted": admitted, "decoded": decoded,
                "expired": expired, "active": len(self._active),
                "queued": len(self._queue)}

    def _spec_step(self) -> int:
        """One speculative decode tick over all slots (spec_decode_tick)
        and its host bookkeeping: each active slot advances by its own
        accepted length + 1, delivery stops early at a stop id or the
        token budget (the undelivered remainder of a round is simply
        discarded — it was never part of the request's stream), and the
        per-slot length/count vectors move by exactly the delivered-or-
        accepted span so the next tick's verify writes cover this round's
        rejected suffix. Returns the number of delivered tokens."""
        st = self._stats
        heads = self._spec_heads > 0
        adaptive = self.adaptive_k
        t0 = time.perf_counter()
        with self._span("serve/spec_tick"), self._mesh_ctx():
            # adaptive off keeps the k_eff=None operand list — the exact
            # pre-ISSUE-16 program, so committed AOT caches and the
            # serve_spec_tick invariant pin stay valid
            tail = ((jnp.asarray(self._k_eff),) if adaptive else ())
            if heads:
                (self._cache, self._draft_cache, out,
                 nacc) = self._aot_call(
                    "spec_decode_tick_heads", spec_decode_tick_heads,
                    (self._tick_model, self._draft_tick_model),
                    (self._weights, self._draft_weights, self._cache,
                     self._draft_cache,
                     jnp.asarray(self._tables),
                     jnp.asarray(self._lengths),
                     jnp.asarray(self._spec_prev_start),
                     jnp.asarray(self._spec_prev_tokens),
                     jnp.asarray(self._spec_prev_idx),
                     jnp.asarray(self._tokens),
                     jnp.asarray(self._key_data),
                     jnp.asarray(self._counts),
                     jnp.asarray(self._temps), jnp.asarray(self._top_ks),
                     jnp.asarray(self._top_ps)) + tail,
                    dict(spec_k=self.spec_k, candidates=self.candidates),
                    donation="cache,draft_cache")
            else:
                (self._cache, self._draft_cache, out,
                 nacc) = self._aot_call(
                    "spec_decode_tick", spec_decode_tick,
                    (self._tick_model, self._draft_tick_model),
                    (self._weights, self._draft_weights, self._cache,
                     self._draft_cache,
                     jnp.asarray(self._tables),
                     jnp.asarray(self._lengths),
                     jnp.asarray(self._tokens),
                     jnp.asarray(self._key_data),
                     jnp.asarray(self._counts),
                     jnp.asarray(self._temps), jnp.asarray(self._top_ks),
                     jnp.asarray(self._top_ps)) + tail,
                    dict(spec_k=self.spec_k, candidates=self.candidates),
                    donation="cache,draft_cache")
            toks = np.asarray(out)   # host sync: streaming delivery
            ns = np.asarray(nacc)
        dt = time.perf_counter() - t0
        n_active = len(self._active)
        self._progress += 1
        st["ticks"] += 1
        st["tick_s"] += dt
        st["occupancy_sum"] += n_active / self.num_slots
        used = self._alloc.usable - self._alloc.free_count
        st["block_used_sum"] += used / self._alloc.usable
        st["peak_blocks_used"] = max(st["peak_blocks_used"], used)
        decoded = accepted = 0
        for slot, req in list(self._active.items()):
            n = int(ns[slot])
            k_used = int(self._k_eff[slot]) if adaptive else self.spec_k
            # the round's writes + randomness are consumed whether or not
            # every token gets delivered; a retiring request's slot state
            # is reset by _release_slot anyway
            old_len = int(self._lengths[slot])
            self._lengths[slot] += n + 1
            self._counts[slot] += n + 1
            st["draft_tokens"] += k_used
            st["accepted_tokens"] += n
            st["target_forwards"] += 1
            req.draft_tokens += k_used
            req.accepted_tokens += n
            if heads:
                # next round's draft chunk: this round's emitted buffer,
                # live up to n, written one past the pre-advance length
                self._spec_prev_tokens[slot] = toks[slot]
                self._spec_prev_idx[slot] = n
                self._spec_prev_start[slot] = old_len + 1
            if adaptive:
                # acceptance EMA -> next round's depth: propose about as
                # many tokens as this slot has been accepting (never 0 —
                # one proposal costs nothing extra, never > spec_k — the
                # compiled width)
                ema = ((1.0 - self.SPEC_EMA_ALPHA) * self._accept_ema[slot]
                       + self.SPEC_EMA_ALPHA * (n / max(k_used, 1)))
                self._accept_ema[slot] = ema
                self._k_eff[slot] = min(
                    self.spec_k, max(1, int(round(ema * self.spec_k))))
            accepted += n
            for j in range(n + 1):
                self._deliver(req, int(toks[slot, j]))
                decoded += 1
                if req.done:
                    break
        st["decode_tokens"] += decoded
        if self.telemetry is not None:
            self.telemetry.tick(
                tick=st["ticks"], tick_ms=round(dt * 1e3, 3),
                active=len(self._active), queued=len(self._queue),
                slot_occupancy=round(n_active / self.num_slots, 4),
                blocks_used=used, blocks_free=self._alloc.free_count,
                spec_k=self.spec_k, accepted_tokens=accepted,
                decoded_tokens=decoded,
                accept_ema=round(float(self._accept_ema.mean()), 4),
                k_eff=round(float(self._k_eff.mean()), 3))
        return decoded

    # ------------------------------------------------------------------
    # paged admission: chunked prefill + prefix reuse + block accounting

    @staticmethod
    def _round_up(n: int, q: int) -> int:
        return -(-n // q) * q

    def _clamp_limits(self, kv_sink: int | None,
                      kv_window: int | None) -> tuple[int, int]:
        """Clamp a per-request sink/window override to the pool config
        (tighten-only — you can never widen past what every slot's HBM
        budget was sized for) and round UP to whole blocks: retirement
        frees whole blocks, and a window shorter than one block would
        retire the block the next write needs. submit() and
        import_kv_blocks() funnel here so a wire-carried override lands
        on the importer exactly as the exporter clamped it."""
        bs = self.block_size
        win = self.kv_window_tokens if kv_window is None else kv_window
        win = min(self.kv_window_tokens, self._round_up(win, bs))
        sink = self.kv_sink_tokens if kv_sink is None else kv_sink
        sink = min(self.kv_sink_tokens, self._round_up(sink, bs))
        return int(sink), int(win)

    def _paged_admissions(self) -> int:
        """Advance the admission pipeline: while nothing is decoding,
        push the current prefill to completion and keep admitting (an
        idle engine has no TTFT to protect); once slots are live, spend
        at most ``prefill_chunks_per_step`` chunk calls so resident
        streams keep ticking between chunks."""
        admitted = chunks = 0
        while True:
            if self._prefilling is None:
                if not (self._queue and self._free):
                    break
                if not self._start_prefill():
                    break  # pool pressure: wait for retirements
            admitted += self._prefill_chunk_step()
            chunks += 1
            if self._active and chunks >= self._chunks_per_step:
                break
        return admitted

    def _alloc_blocks(self, n: int):
        """Allocate n blocks, evicting prefix-cache LRU entries if the
        free list is short — but only when eviction can actually cover
        the shortfall (a doomed allocation must not destroy reusable
        cached prefixes on its way to failing anyway). None when it
        cannot be covered."""
        fresh = self._alloc.alloc(n)
        if fresh is None and self._radix is not None:
            # parked sessions must never deadlock a live admission:
            # byte pressure outranks the session_hbm_max count, so
            # demote LRU residents down the hierarchy (store / spill —
            # lossless either way) until eviction covers the shortfall
            while (self._sessions
                   and self._radix.evictable_count()
                   < n - self._alloc.free_count):
                self._demote_session(next(iter(self._sessions)))
            short = n - self._alloc.free_count
            if short <= 0:
                fresh = self._alloc.alloc(n)
            elif self._radix.evictable_count() >= short:
                self._radix.reclaim(short)
                fresh = self._alloc.alloc(n)
        return fresh

    def _start_prefill(self) -> bool:
        """Begin admitting the queue head: match its prompt against the
        radix cache (matched FULL blocks are admitted by reference — no
        prefill compute), allocate private blocks for the rest, claim a
        slot. The last prompt token is never taken from the cache: its
        forward pass produces the logits the first sampled token needs.
        Returns False when the pool cannot back it yet."""
        req = self._queue[0]
        # a preempted request resumes by re-prefilling prompt + what it
        # already generated — continuation tokens, sampling stream and
        # the delivered output are unchanged
        tokens = np.concatenate(
            [req.prompt, np.asarray(req.new_tokens, np.int32)])
        true_len = int(tokens.size)
        bs = self.block_size
        lookup_len = ((true_len - 1) // bs) * bs
        matched_nodes: list = []
        if self._radix is not None:
            matched_nodes = self._radix.match_nodes(tokens[:lookup_len])
        matched = [n.block for n in matched_nodes]
        for b in matched:  # hold them before eviction can reap them
            self._alloc.incref(b)
        m = len(matched) * bs
        span = min(self._round_up(true_len - m, self.chunk),
                   self.cfg.max_seq_len - m)
        fresh = self._alloc_blocks(self._round_up(span, bs) // bs)
        if fresh is None and not self._active and m:
            # nothing will retire and the shared prefix is squatting the
            # pool: fall back to a full private prefill so the lone
            # request can make progress
            for b in matched:
                self._alloc.decref(b)
            matched, matched_nodes, m = [], [], 0
            if self._radix is not None:
                self._radix.clear()
            span = min(self._round_up(true_len, self.chunk),
                       self.cfg.max_seq_len)
            fresh = self._alloc_blocks(self._round_up(span, bs) // bs)
        if fresh is None:
            for b in matched:
                self._alloc.decref(b)
            return False
        self._queue.popleft()
        # fleet-shipped (remote) prefix nodes count separately: their
        # tokens were prefilled on ANOTHER replica, so the local
        # prefix_hit_rate must stay comparable to single-engine runs
        remote_m = sum(1 for n in matched_nodes if n.remote)
        if self._radix is not None:  # ONE stat row per landed admission
            self._radix.record_admission(len(matched), lookup_len,
                                         remote_blocks=remote_m)
        slot = self._free.pop()
        blocks = matched + fresh
        self._slot_blocks[slot] = blocks
        # the TICK's view of this slot (self._tables/_lengths) stays
        # all-trash until activation: decode ticks keep running between
        # prefill chunks, and the mid-prefill slot's garbage tick must
        # write the trash block, not position 0 of the request's first
        # real block. The chunk program reads the real row from pf state.
        table_row = np.zeros(self.cfg.kv_pages, np.int32)
        table_row[:len(blocks)] = blocks
        req.prefix_hit_tokens += m
        req.remote_hit_tokens += remote_m * bs
        st = self._stats
        st["admissions"] += 1
        st["admitted_tokens"] += true_len
        st["prefix_hit_tokens"] += m
        st["remote_hit_tokens"] += remote_m * bs
        self._prefilling = dict(
            req=req, slot=slot, tokens=tokens, true_len=true_len, pos=m,
            resume=len(req.new_tokens), table_row=table_row,
            # spec: the draft prefill also starts at the prefix-hit
            # offset — radix-held blocks keep their draft K/V resident
            # (same block ids into the draft pool, written by the
            # admission that cached them), and every position below a
            # slot's length is rewritten with ACCEPTED tokens before the
            # length passes it (the covering-writes property), so cached
            # draft K/V is always conditioned on the true prefix
            dpos=m, first=None,
            kd=np.asarray(jax.random.key_data(
                jax.random.key(req.sampling.seed))))
        return True

    def _chunk_call(self, name, model, weights, cache, pf, pos):
        """One paged_prefill_chunk call for the admission in flight, at
        absolute position ``pos`` of its token stream — shared by the
        target and (spec mode) draft cache fills, which are distinct
        AOT programs (``name`` keys the executable table: same shapes,
        different static model)."""
        req = pf["req"]
        chunk = np.zeros((1, self.chunk), np.int32)
        n = min(self.chunk, pf["true_len"] - pos)
        chunk[0, :n] = pf["tokens"][pos:pos + n]
        return self._aot_call(
            name, paged_prefill_chunk, (model,),
            (weights, cache,
             jnp.asarray(chunk), jnp.int32(pos),
             jnp.asarray(pf["table_row"]),
             jnp.int32(pf["true_len"]),
             jnp.asarray(pf["kd"]),
             jnp.int32(pf["resume"]),
             jnp.float32(req.sampling.temperature),
             jnp.int32(req.sampling.top_k),
             jnp.float32(req.sampling.top_p)),
            dict(candidates=self.candidates))

    def _prefill_chunk_step(self) -> int:
        """Run ONE chunk step of the in-flight admission — a target
        chunk while the target cache is short of the prompt, plus (spec
        mode) a draft chunk filling the draft pool over the SAME blocks
        (both start at the prefix-hit offset: matched blocks carry valid
        draft K/V from the admission that cached them) — and, once both
        caches cover the prompt, activate the slot with the target's
        sampled next token. Returns 1 on completed admission, else 0."""
        pf = self._prefilling
        req, slot = pf["req"], pf["slot"]
        t0 = time.perf_counter()
        with self._span("serve/prefill"), self._mesh_ctx():
            if pf["pos"] < pf["true_len"]:
                pos = pf["pos"]
                final_t = pos + self.chunk >= pf["true_len"]
                self._cache, first = self._chunk_call(
                    "paged_prefill_chunk", self._chunk_model,
                    self._weights, self._cache, pf, pos)
                if final_t:
                    # sync: the TTFT timestamp is honest
                    pf["first"] = int(first)
                pf["pos"] = pos + self.chunk
            if self.spec_k and pf["dpos"] < pf["true_len"]:
                self._draft_cache, _ = self._chunk_call(
                    "paged_prefill_chunk_draft", self._draft_chunk_model,
                    self._draft_weights, self._draft_cache, pf, pf["dpos"])
                pf["dpos"] += self.chunk
        now = time.perf_counter()
        self._progress += 1
        st = self._stats
        st["prefill_s"] += now - t0
        st["prefill_chunks"] += 1
        req.prefill_chunks += 1
        if pf["pos"] < pf["true_len"] or (
                self.spec_k and pf["dpos"] < pf["true_len"]):
            return 0
        first = pf["first"]
        # admission complete: cache the prompt's full blocks for future
        # arrivals, publish the real table to the tick's view, rewind to
        # the true length, activate the slot
        self._tables[slot, :] = pf["table_row"]
        self._lengths[slot] = pf["true_len"]
        if self._radix is not None:
            nb = pf["true_len"] // self.block_size
            self._radix.insert(pf["tokens"][:nb * self.block_size],
                               self._slot_blocks[slot][:nb])
        self._prefilling = None
        st["prefills"] += 1
        req.slot = slot
        if req.first_token_time is None:
            req.first_token_time = now
            if req.submit_time is not None:
                self._note_ttft(now - req.submit_time)
        self._trace_span(req, "prefill", req.submit_time, now,
                         chunks=req.prefill_chunks,
                         parked=bool(req.prefill_only and not req.done),
                         resumed_from=req.resumed_from)
        self._active[slot] = req
        self._admit_order[slot] = next(self._admit_seq)
        if self.per_slot_limits:
            self._set_slot_limits(slot, req.kv_sink, req.kv_window)
        self._key_data[slot] = pf["kd"]
        self._counts[slot] = pf["resume"] + 1
        self._temps[slot] = req.sampling.temperature
        self._top_ks[slot] = req.sampling.top_k
        self._top_ps[slot] = req.sampling.top_p
        if self.spec_k:
            self._reset_spec_slot(slot, first, pf["true_len"])
        self._deliver(req, first)
        if req.prefill_only and not req.done:
            # PARK for handoff (ISSUE 12): the first token is
            # delivered, the blocks hold exact K/V for positions
            # [0, true_len) — custody now belongs to export_kv_blocks.
            # The slot leaves the tick's view (all-trash table, length
            # 0: garbage ticks must not write the parked K/V) and
            # leaves _active so growth/preemption/delivery skip it.
            del self._active[slot]
            self._prefilled[req.id] = dict(req=req, slot=slot,
                                           length=pf["true_len"])
            self._tables[slot, :] = 0
            self._lengths[slot] = 0
            req.parked = True
        return 1

    def _grow_slots(self) -> None:
        """Back every active slot's next write position with a physical
        block, oldest admissions first — a speculative tick writes
        [len, len+spec_k], so spec serving backs the whole span (clamped
        to the context: past-max_seq_len writes go to the trash block and
        need no backing). When the pool is exhausted even after
        prefix-cache eviction, preempt the YOUNGEST resident request
        (free its blocks, requeue it at the front — it resumes later by
        re-prefilling prompt + generated, output unchanged) until the
        older stream can proceed.

        With a sliding window configured (kv_window_tokens > 0) this is
        also where blocks RETIRE: before growing a slot, any middle
        block whose every position has fallen out of the sink+window
        visible set — for this tick's MINIMUM query position, so spec
        rounds are covered too — is decref'd back to the allocator, its
        table entry pointed at the trash block, and its list entry
        zeroed as a sentinel. Dead is forever (positions only grow), so
        each block retires exactly once, and the freed capacity backs
        the very growth loop below — a long stream's footprint is
        sink + window + a block, not its whole history."""
        bs = self.block_size
        for slot in sorted(self._active,
                           key=lambda s: self._admit_order[s]):
            if slot not in self._active:
                continue  # preempted by an older slot's growth
            # retirement horizon = this slot's EFFECTIVE sink/window
            # (per-request overrides, ISSUE 15) — must agree with the
            # compiled mask's per-slot leaves or retired garbage would
            # be attended
            if self.per_slot_limits:
                win = int(self._slot_windows[slot])
                sink = int(self._slot_sinks[slot])
            else:
                win, sink = self.kv_window_tokens, self.kv_sink_tokens
            blocks = self._slot_blocks[slot]
            if win:
                qlo = int(self._lengths[slot])  # this tick's first query
                for bi in range(sink // bs, len(blocks)):
                    if (bi + 1) * bs > qlo - win + 1:
                        break  # first live block; younger ones follow
                    if blocks[bi]:
                        self._alloc.decref(blocks[bi])
                        blocks[bi] = 0
                        self._tables[slot, bi] = 0
                        self._stats["retired_blocks"] += 1
            bi = min(int(self._lengths[slot]) + self.spec_k,
                     self.cfg.max_seq_len - 1) // self.block_size
            while bi >= len(blocks):
                fresh = self._alloc_blocks(1)
                if fresh is not None:
                    self._tables[slot, len(blocks)] = fresh[0]
                    blocks.append(fresh[0])
                    continue
                victim = max(self._active,
                             key=lambda s: self._admit_order[s])
                self._preempt(victim)
                if victim == slot:
                    break  # this very request went back to the queue

    def _set_slot_limits(self, slot: int, sink: int | None,
                         window: int | None) -> None:
        """Record one slot's effective sink/window (None = engine
        defaults) and mark the compiled mask leaves stale — they are
        re-stamped lazily before the next tick."""
        s = self.kv_sink_tokens if sink is None else sink
        w = self.kv_window_tokens if window is None else window
        if (self._slot_sinks[slot] != s
                or self._slot_windows[slot] != w):
            self._slot_sinks[slot] = s
            self._slot_windows[slot] = w
            self._limits_dirty = True

    def _stamp_slot_limits(self) -> None:
        """Push the host per-slot sink/window vectors into the cache's
        ``kv_sinks``/``kv_windows`` leaves (every layer reads the same
        row — broadcast up the scan axis, exactly like
        _override_paging's table stamp, just host-initiated because
        the values change on admission/release, not every tick)."""
        sinks = jnp.asarray(self._slot_sinks)
        windows = jnp.asarray(self._slot_windows)

        def fix(path, leaf):
            name = _leaf_name(path)
            if name == "kv_sinks":
                return jnp.broadcast_to(sinks, leaf.shape).astype(leaf.dtype)
            if name == "kv_windows":
                return jnp.broadcast_to(windows,
                                        leaf.shape).astype(leaf.dtype)
            return leaf

        with self._mesh_ctx():
            self._cache = jax.tree_util.tree_map_with_path(fix, self._cache)
            if self.spec_k:
                self._draft_cache = jax.tree_util.tree_map_with_path(
                    fix, self._draft_cache)
        self._limits_dirty = False

    def preempt_request(self, req: Request) -> bool:
        """Release ``req``'s resources NOW and retire it with
        finish_reason "preempted", keeping every delivered token — the
        ROUTER-level preemption hook (ISSUE 15): the router requeues
        the stream and a later submit(generated=req.new_tokens) resumes
        it losslessly, exactly like failover redispatch. Queued
        requests just leave the queue; an active slot's blocks return
        to the pool. Returns False (no-op) for requests this engine
        cannot cleanly release mid-flight: already done, mid-chunked-
        prefill, or parked for KV handoff."""
        if req.done:
            return False
        if req in self._queue:
            self._queue.remove(req)
        elif (self.paged and self._prefilling is not None
                and self._prefilling["req"] is req):
            return False
        elif self.paged and req.id in self._prefilled:
            return False
        elif req.slot is not None and self._active.get(req.slot) is req:
            slot = req.slot
            del self._active[slot]
            if self.paged:
                self._release_slot(slot)
            else:
                self._free.append(slot)
                self._temps[slot] = 0.0
            req.slot = None
            req.preemptions += 1
        else:
            return False
        req.done = True
        req.finish_reason = "preempted"
        req.finish_time = time.perf_counter()
        self._stats["preempted_requests"] += 1
        return True

    def _preempt(self, slot: int) -> None:
        req = self._active.pop(slot)
        self._release_slot(slot)
        req.slot = None
        req.preemptions += 1
        self._stats["preemptions"] += 1
        self._queue.appendleft(req)

    def _release_slot(self, slot: int) -> None:
        """Return a slot's blocks to the pool (radix-cached blocks
        survive via the cache's own reference) and point its table at
        the trash block so its garbage ticks stay harmless. Zero
        entries are window-retirement sentinels — those refs were
        already returned mid-stream."""
        for b in self._slot_blocks[slot]:
            if b:
                self._alloc.decref(b)
        self._slot_blocks[slot] = []
        self._tables[slot, :] = 0
        self._lengths[slot] = 0
        if self.per_slot_limits:
            self._set_slot_limits(slot, None, None)
        if self.spec_k:
            self._reset_spec_slot(slot, 0, 0)
        self._free.append(slot)
        self._temps[slot] = 0.0

    def _reset_spec_slot(self, slot: int, first: int,
                         true_len: int) -> None:
        """Fresh per-slot speculative round state (ISSUE 16) — every
        activation path (chunked-prefill completion, KV import) and
        _release_slot funnel here: full proposal depth, EMA at 1.0, and
        the heads-mode round-1 draft chunk = [first, pad...] written at
        ``true_len`` (the first committed token's position — exactly the
        offline path's prev_pos = plen init)."""
        self._accept_ema[slot] = 1.0
        self._k_eff[slot] = self.spec_k
        self._spec_prev_tokens[slot] = 0
        self._spec_prev_tokens[slot, 0] = first
        self._spec_prev_idx[slot] = 0
        self._spec_prev_start[slot] = true_len

    # ------------------------------------------------------------------
    # KV block streaming (ISSUE 12): the disaggregation transfer unit

    @property
    def parked_requests(self) -> list[Request]:
        """Prefill-only requests parked awaiting export (in park
        order) — what a router's handoff sweep polls."""
        if not self.paged:
            return []
        return [rec["req"] for rec in self._prefilled.values()]

    def _pool_leaf_names(self) -> list[str]:
        """Tree-path names of the pool's leaves (K/V codes plus, on an
        int8 pool, the scale planes), in the flatten order
        kv_block_gather emits — the payload's integrity tags."""
        return ["/".join(str(getattr(p, "key", p)) for p in path)
                for path, leaf in
                jax.tree_util.tree_leaves_with_path(self._cache)
                if _leaf_name(path) in POOL_LEAF_AXIS]

    def _gather_blocks(self, blocks) -> list:
        """Run the ONE fixed-shape gather program over ``blocks`` (ids
        padded to kv_pages with trash) and return named host arrays
        with the pad rows sliced off."""
        nb = len(blocks)
        ids = np.zeros(self.cfg.kv_pages, np.int32)
        ids[:nb] = blocks
        with self._mesh_ctx():
            gathered = self._aot_call(
                "kv_block_gather", kv_block_gather, (),
                (self._cache, jnp.asarray(ids)), {}, donation="")
        out = []
        for name, leaf in zip(self._pool_leaf_names(), gathered):
            a = np.asarray(leaf)  # host sync
            out.append((name, np.take(a, np.arange(nb),
                                      axis=_pool_block_axis(name, a.ndim))))
        self._progress += 1
        return out

    def _scatter_blocks(self, blocks, arrays) -> None:
        """Run the ONE fixed-shape scatter program: pad ids and the
        payload's block axis to kv_pages (pad zeros land in the trash
        block) and write into the donated pool."""
        nb = len(blocks)
        ids = np.zeros(self.cfg.kv_pages, np.int32)
        ids[:nb] = blocks
        padded = []
        for name, a in zip(self._pool_leaf_names(), arrays):
            axis = _pool_block_axis(name, a.ndim)
            pad = [(0, 0)] * a.ndim
            pad[axis] = (0, self.cfg.kv_pages - a.shape[axis])
            padded.append(jnp.asarray(np.pad(a, pad)))
        with self._mesh_ctx():
            self._cache = self._aot_call(
                "kv_block_scatter", kv_block_scatter, (),
                (self._cache, jnp.asarray(ids), padded), {},
                donation="cache")

    def export_kv_blocks(self, req: Request) -> KVBlockPayload:
        """Gather a PARKED request's KV blocks off the pool into a
        host payload and release its slot — the prefill-role half of a
        disaggregated handoff. The payload carries the prompt, the
        delivered first token (in ``generated``), the sampling
        contract and the exact K/V of [0, true_len), so the importing
        engine continues the stream bitwise as if it had prefilled
        locally. After export this engine holds NOTHING for the
        request (radix-cached prefix blocks live on through the
        cache's own reference)."""
        if not self.paged:
            raise ValueError("export_kv_blocks requires the paged engine")
        rec = self._prefilled.pop(req.id, None)
        if rec is None:
            raise ValueError(
                f"request {req.id} is not parked for export")
        slot, true_len = rec["slot"], rec["length"]
        nb = -(-true_len // self.block_size)
        payload = KVBlockPayload(
            prompt=req.prompt.copy(), generated=list(req.new_tokens),
            true_len=true_len, block_size=self.block_size,
            max_new_tokens=req.max_new_tokens, sampling=req.sampling,
            stop_ids=tuple(req.stop_ids),
            leaves=self._gather_blocks(self._slot_blocks[slot][:nb]),
            kv_dtype=self.kv_dtype,
            # the effective per-request window rides the wire (ISSUE 18
            # bug fix): without it the importer would ATTEND positions
            # the exporter's tightened mask had retired
            kv_sink=req.kv_sink, kv_window=req.kv_window,
            # the ORIGIN submit + trace identity ride the handoff
            # (ISSUE 17): unix-epoch so two processes agree on it
            origin_t=(None if req.origin_submit_time is None
                      else _trace_to_unix(req.origin_submit_time)),
            trace=(None if req.trace is None else req.trace.to_wire()))
        self._release_slot(slot)
        req.slot = None
        req.parked = False
        st = self._stats
        st["kv_exports"] += 1
        st["kv_exported_blocks"] += nb
        st["kv_stream_bytes"] += payload.nbytes
        return payload

    def import_kv_blocks(self, payload: KVBlockPayload, *,
                         on_token=None,
                         deadline_s: float | None = None
                         ) -> Request | None:
        """Scatter a KVBlockPayload into free pool blocks and ACTIVATE
        the stream mid-flight — the decode-role half. Returns the live
        Request handle (its ``new_tokens`` is pre-seeded with the
        exporter's delivered tokens; ``resumed_from`` guards
        re-delivery exactly like submit(generated=...)), or None on a
        resource shortfall (no free slot / pool blocks) — the caller
        falls back to resume-from-tokens redispatch, which is lossless
        by construction. Geometry/model mismatches raise ValueError:
        importing foreign K/V silently would serve garbage."""
        if not self.paged:
            raise ValueError("import_kv_blocks requires the paged engine")
        if self.spec_k:
            raise ValueError(
                "import_kv_blocks does not compose with spec_k > 0 "
                "(the draft pool is not on the KV stream)")
        if payload.wire_version != KV_WIRE_VERSION:
            raise ValueError(
                f"KV payload wire_version {payload.wire_version} != "
                f"engine wire_version {KV_WIRE_VERSION} — the sender "
                f"speaks a different KV stream schema; upgrade both "
                f"ends before disaggregating")
        if payload.kv_dtype != self.kv_dtype:
            raise ValueError(
                f"payload kv_dtype {payload.kv_dtype!r} != engine "
                f"kv_dtype {self.kv_dtype!r} — an int8 payload cannot "
                f"be scattered into a bf16 pool (or vice versa); run "
                f"prefill- and decode-role replicas with the same "
                f"kv_dtype")
        if payload.block_size != self.block_size:
            raise ValueError(
                f"payload block_size {payload.block_size} != engine "
                f"block_size {self.block_size}")
        if not payload.generated:
            raise ValueError(
                "payload carries no generated tokens — the exporter "
                "always delivers the first token before parking")
        if payload.true_len != payload.prompt.size + len(
                payload.generated) - 1:
            raise ValueError(
                f"payload true_len {payload.true_len} != prompt "
                f"{payload.prompt.size} + generated "
                f"{len(payload.generated)} - 1")
        if payload.prompt.size + payload.max_new_tokens > \
                self.cfg.max_seq_len:
            raise ValueError(
                f"prompt_len {payload.prompt.size} + max_new_tokens "
                f"{payload.max_new_tokens} exceeds max_seq_len "
                f"{self.cfg.max_seq_len}")
        names = self._pool_leaf_names()
        if [n for n, _ in payload.leaves] != names:
            raise ValueError(
                "payload pool leaves do not match this engine's pool "
                "(different model or layer stacking)")
        if payload.kv_window is not None or payload.kv_sink is not None:
            if not (self.kv_window_tokens and self.per_slot_limits):
                raise ValueError(
                    "payload carries a per-request kv_window/kv_sink "
                    "override but this engine has no per-slot mask "
                    "leaves (kv_window_tokens == 0 or paged_attn="
                    "'pallas') — importing it would ATTEND positions "
                    "the exporter's tightened mask retired")
        if not self._free:
            return None
        nb = payload.num_blocks
        blocks = self._alloc_blocks(nb)
        if blocks is None:
            return None
        self._scatter_blocks(blocks, [a for _, a in payload.leaves])
        req = Request(payload.prompt, payload.max_new_tokens,
                      payload.sampling, tuple(payload.stop_ids),
                      on_token, deadline_s=deadline_s,
                      generated=payload.generated)
        req.submit_time = time.perf_counter()
        # the exporter timed the real TTFT; this engine's EMA must not
        # absorb a handoff as a near-zero first token
        req.first_token_time = req.submit_time
        # end-to-end identity (ISSUE 17): the ORIGIN router submit and
        # the TraceContext arrive in the payload — ttft_e2e_s and the
        # decode-side spans stay on the request's one fleet-wide trace
        req.origin_submit_time = (
            req.submit_time if payload.origin_t is None
            else _trace_from_unix(float(payload.origin_t)))
        if payload.trace is not None:
            req.trace = TraceContext.from_wire(payload.trace)
        slot = self._free.pop()
        req.slot = slot
        self._slot_blocks[slot] = list(blocks)
        self._tables[slot, :] = 0
        self._tables[slot, :nb] = blocks
        self._lengths[slot] = payload.true_len
        self._active[slot] = req
        self._admit_order[slot] = next(self._admit_seq)
        self._key_data[slot] = np.asarray(jax.random.key_data(
            jax.random.key(payload.sampling.seed)))
        # the activation invariants, verbatim: token n samples with
        # fold_in(key, n), the next tick's input is the last delivered
        # token, and the next write position is true_len (backed by
        # _grow_slots exactly like a local activation — when true_len
        # is a block multiple the write lands in a FRESH block, never
        # in an imported/radix-shared one)
        self._counts[slot] = len(payload.generated)
        self._tokens[slot] = payload.generated[-1]
        self._temps[slot] = payload.sampling.temperature
        self._top_ks[slot] = payload.sampling.top_k
        self._top_ps[slot] = payload.sampling.top_p
        if payload.kv_window is not None or payload.kv_sink is not None:
            # re-apply the exporter's tightened mask (ISSUE 18 bug
            # fix): re-clamp against THIS pool's config — tighten-only
            # both ways — and stamp the slot's mask leaves so the
            # resumed stream masks exactly what the exporter's would
            req.kv_sink, req.kv_window = self._clamp_limits(
                payload.kv_sink, payload.kv_window)
            self._set_slot_limits(slot, req.kv_sink, req.kv_window)
        if self.spec_k:
            # the imported blocks carry no DRAFT K/V, so heads-mode
            # proposals start cold here — acceptance suffers, tokens
            # never do (the rejection kernel is lossless at any draft
            # quality)
            self._reset_spec_slot(slot, payload.generated[-1],
                                  payload.true_len)
        if self._radix is not None:
            full = np.concatenate(
                [payload.prompt,
                 np.asarray(payload.generated[:-1], np.int32)])
            nbf = payload.true_len // self.block_size
            if nbf:
                self._radix.insert(full[:nbf * self.block_size],
                                   blocks[:nbf])
        st = self._stats
        st["kv_imports"] += 1
        st["kv_imported_blocks"] += nb
        st["kv_stream_bytes"] += payload.nbytes
        return req

    def export_prefix_blocks(self, tokens) -> PrefixBlockPayload | None:
        """Gather the radix-cached prefix of ``tokens`` for fleet
        shipping (the remote-hit path: this replica owns the longest
        match, another replica is about to prefill it from scratch).
        None when nothing is cached."""
        if not self.paged or self._radix is None:
            return None
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        nodes = self._radix.match_nodes(tokens)
        if not nodes:
            return None
        blocks = [n.block for n in nodes]
        payload = PrefixBlockPayload(
            tokens=tokens[:len(blocks) * self.block_size].copy(),
            block_size=self.block_size,
            leaves=self._gather_blocks(blocks),
            kv_dtype=self.kv_dtype)
        self._stats["kv_stream_bytes"] += payload.nbytes
        return payload

    def import_prefix_blocks(self, payload: PrefixBlockPayload) -> int:
        """Adopt a fleet-shipped prefix into the local pool + radix as
        REMOTE entries (steered hits on them count separately from
        local ones). Best-effort by design — returns the number of
        blocks adopted, 0 on any mismatch or pool pressure: a failed
        ship just means this replica prefills the prefix itself."""
        if (not self.paged or self._radix is None or self.spec_k
                or payload.block_size != self.block_size
                or payload.kv_dtype != self.kv_dtype
                or payload.wire_version != KV_WIRE_VERSION
                or [n for n, _ in payload.leaves]
                != self._pool_leaf_names()):
            return 0
        tokens = np.asarray(payload.tokens, np.int32).reshape(-1)
        nb = len(tokens) // self.block_size
        matched = self._radix.match(tokens)
        m = len(matched)
        if m >= nb:
            return 0  # already holds the whole prefix
        fresh = self._alloc_blocks(nb - m)
        if fresh is None:
            return 0
        suffix = [np.take(a, np.arange(m, nb),
                          axis=_pool_block_axis(n, a.ndim))
                  for n, a in payload.leaves]
        self._scatter_blocks(fresh, suffix)
        self._radix.insert(tokens[:nb * self.block_size],
                           matched + fresh, remote=True)
        for b in fresh:  # the radix reference is now the sole owner
            self._alloc.decref(b)
        st = self._stats
        st["kv_imported_blocks"] += nb - m
        st["kv_stream_bytes"] += payload.nbytes
        return nb - m

    # ------------------------------------------------------------------
    # persistent sessions (ISSUE 18): the HBM-resident tier + the
    # detach/attach/seed surface the tiered store and router ride

    def detach_request(self, handle: Request) -> KVBlockPayload:
        """Export a LIVE mid-stream request's KV + continuation
        contract as a KVBlockPayload and retire it locally with
        finish_reason "detached" — the suspend half of a fleet-wide
        session reattach. ``import_kv_blocks`` on ANY replica (this
        one included) continues the stream bitwise as if it had never
        been interrupted: the payload is exactly the disagg handoff
        wire format, including the partial tail block PAST the radix
        full-block boundary, the per-request kv_sink/kv_window
        override and the trace identity. Parked prefill_only requests
        delegate to export_kv_blocks."""
        if not self.paged:
            raise ValueError("detach_request requires the paged engine")
        if self.spec_k:
            raise ValueError(
                "detach_request does not compose with spec_k > 0 "
                "(the draft pool is not on the KV stream)")
        if handle.id in self._prefilled:
            return self.export_kv_blocks(handle)
        slot = handle.slot
        if slot is None or self._active.get(slot) is not handle:
            raise ValueError(
                f"request {handle.id} is not resident (queued, "
                f"mid-prefill or already finished) — nothing to "
                f"detach")
        true_len = int(self._lengths[slot])
        nb = -(-true_len // self.block_size)
        payload = KVBlockPayload(
            prompt=handle.prompt.copy(),
            generated=list(handle.new_tokens),
            true_len=true_len, block_size=self.block_size,
            max_new_tokens=handle.max_new_tokens,
            sampling=handle.sampling,
            stop_ids=tuple(handle.stop_ids),
            leaves=self._gather_blocks(self._slot_blocks[slot][:nb]),
            kv_dtype=self.kv_dtype,
            kv_sink=handle.kv_sink, kv_window=handle.kv_window,
            origin_t=(None if handle.origin_submit_time is None
                      else _trace_to_unix(handle.origin_submit_time)),
            trace=(None if handle.trace is None
                   else handle.trace.to_wire()))
        del self._active[slot]
        self._release_slot(slot)
        handle.slot = None
        handle.done = True
        handle.finish_reason = "detached"
        handle.finish_time = time.perf_counter()
        st = self._stats
        st["kv_exports"] += 1
        st["kv_exported_blocks"] += nb
        st["kv_stream_bytes"] += payload.nbytes
        st["session_detaches"] += 1
        if self.telemetry is not None:
            self.telemetry.request(handle)
        return payload

    def seed_session_blocks(self, payload: KVBlockPayload, *,
                            remote: bool = False) -> int:
        """Adopt a stored session's FULL KV blocks into the pool +
        radix so the reattaching turn's prefill rides them as a prefix
        hit — bitwise-equal to re-prefilling them, minus the compute.
        The partial tail block (true_len past the full-block boundary)
        is NOT published — radix granularity is full blocks — so the
        reattaching turn re-prefills at most block_size - 1 positions.
        Best-effort by design: returns the number of prefix TOKENS now
        backed, 0 on ANY mismatch (wire version, dtype, geometry,
        window-retired payloads whose gathered trash rows must never
        enter the prefix cache) or pool pressure — a declined seed
        just means a plain re-prefill, lossless by construction."""
        if (not self.paged or self._radix is None or self.spec_k
                or payload.block_size != self.block_size
                or payload.kv_dtype != self.kv_dtype
                or payload.wire_version != KV_WIRE_VERSION
                or payload.kv_window is not None
                or payload.kv_sink is not None
                or [n for n, _ in payload.leaves]
                != self._pool_leaf_names()):
            return 0
        if not payload.generated or payload.true_len != (
                payload.prompt.size + len(payload.generated) - 1):
            return 0
        bs = self.block_size
        nbf = payload.true_len // bs
        if not nbf:
            return 0
        tokens = np.concatenate(
            [payload.prompt,
             np.asarray(payload.generated[:-1], np.int32)])
        st = self._stats
        matched = self._radix.match(tokens[:nbf * bs])
        m = len(matched)
        if m < nbf:
            fresh = self._alloc_blocks(nbf - m)
            if fresh is None:
                return 0
            suffix = [np.take(a, np.arange(m, nbf),
                              axis=_pool_block_axis(n, a.ndim))
                      for n, a in payload.leaves]
            self._scatter_blocks(fresh, suffix)
            self._radix.insert(tokens[:nbf * bs], matched + fresh,
                               remote=remote)
            for b in fresh:  # the radix reference is the sole owner
                self._alloc.decref(b)
            st["kv_imported_blocks"] += nbf - m
            st["kv_stream_bytes"] += payload.nbytes
        st["session_attaches"] += 1
        st["session_seed_tokens"] += nbf * bs
        return nbf * bs

    def take_demoted_sessions(self
                              ) -> list[tuple[str, str, KVBlockPayload]]:
        """Drain the spill queue: ``(session_id, tenant, payload)``
        triples the HBM-budget sweep demoted while NO session_store is
        attached — what a router/worker absorbs into the fleet store
        (the subprocess wire's pull side)."""
        if not self.paged:
            return []
        out, self._session_spill = self._session_spill, []
        return out

    def _reattach_session(self, sid: str) -> None:
        """Pull a session's KV as close to HBM as it can get BEFORE
        the request queues, so its prefill rides the radix prefix hit:
        a resident session publishes its full blocks into the radix; a
        store-tier session seeds its payload back into the pool. A
        miss at every tier is SILENT — the prefill behind it is the
        lossless fallback, the router's fallback counter the loud
        part."""
        if sid in self._sessions:
            self._adopt_resident_session(sid)
            self._stats["session_attaches"] += 1
        elif self.session_store is not None:
            got = self.session_store.get(sid)
            if got is not None:
                self.seed_session_blocks(got[0])

    def _adopt_resident_session(self, sid: str) -> None:
        """Move a parked session from the resident tier into the radix
        prefix cache: its contiguous non-retired full blocks publish
        under the conversation tokens (the reattaching prefill matches
        them like any shared prefix), then the session's own references
        drop — the radix is the sole owner, and the partial tail block
        frees (its positions re-prefill with the new turn)."""
        rec = self._sessions.pop(sid)
        req = rec["req"]
        bs = self.block_size
        nbf = rec["true_len"] // bs
        blocks = rec["blocks"]
        # a windowed session's retired blocks are zero sentinels — the
        # radix may only ever see the contiguous LIVE prefix (a trash
        # block published as cached KV would serve garbage)
        k = 0
        while k < nbf and blocks[k]:
            k += 1
        if k and self._radix is not None:
            tokens = np.concatenate(
                [req.prompt, np.asarray(req.new_tokens, np.int32)])
            self._radix.insert(tokens[:k * bs], blocks[:k])
        for b in blocks:
            if b:
                self._alloc.decref(b)

    def _park_session(self, req: Request) -> None:
        """Park a finishing session stream's KV in the HBM-resident
        tier: ownership of the slot's blocks transfers to the session
        record (the list empties, so the _release_slot that follows
        frees everything EXCEPT them), and the LRU budget sweep demotes
        the eldest resident down the hierarchy."""
        slot = req.slot
        true_len = int(self._lengths[slot])
        if true_len < 1:
            return
        nb = -(-true_len // self.block_size)
        blocks = list(self._slot_blocks[slot][:nb])
        # blocks past true_len (grown for the write the retirement
        # preempted) stay with the slot and free in _release_slot
        self._slot_blocks[slot] = self._slot_blocks[slot][nb:]
        old = self._sessions.pop(req.session_id, None)
        if old is not None:  # superseded turn: the newer KV wins
            for b in old["blocks"]:
                if b:
                    self._alloc.decref(b)
        self._sessions[req.session_id] = dict(
            req=req, blocks=blocks, true_len=true_len,
            tenant=req.tenant)
        self._stats["session_detaches"] += 1
        self._enforce_session_budget()

    def _enforce_session_budget(self) -> None:
        while len(self._sessions) > self.session_hbm_max:
            self._demote_session(next(iter(self._sessions)))

    def _demote_session(self, sid: str) -> None:
        """Demote one resident session down the hierarchy: gather its
        blocks into a KVBlockPayload (the PR 11 wire format — the same
        bytes a disagg handoff ships) bound for the attached
        session_store's host-DRAM/disk tiers, or the bounded spill
        queue a router drains over the subprocess wire. The HBM blocks
        free either way."""
        rec = self._sessions.pop(sid)
        payload = self._session_to_payload(rec)
        st = self._stats
        st["session_demotes"] += 1
        if self.session_store is not None:
            self.session_store.put(sid, payload, tenant=rec["tenant"])
        elif len(self._session_spill) >= 64:
            # bounded: an unattended engine must not hoard host copies
            self._session_spill.pop(0)
            self._session_spill.append((sid, rec["tenant"], payload))
            st["session_dropped"] += 1
        else:
            self._session_spill.append((sid, rec["tenant"], payload))

    def _demote_all_sessions(self) -> None:
        for sid in list(self._sessions):
            self._demote_session(sid)

    def _session_to_payload(self, rec: dict) -> KVBlockPayload:
        """Gather a resident session record into the wire payload and
        free its HBM blocks — the record must already be popped."""
        req = rec["req"]
        nb = -(-rec["true_len"] // self.block_size)
        payload = KVBlockPayload(
            prompt=req.prompt.copy(), generated=list(req.new_tokens),
            true_len=rec["true_len"], block_size=self.block_size,
            max_new_tokens=req.max_new_tokens, sampling=req.sampling,
            stop_ids=tuple(req.stop_ids),
            leaves=self._gather_blocks(rec["blocks"][:nb]),
            kv_dtype=self.kv_dtype,
            kv_sink=req.kv_sink, kv_window=req.kv_window)
        for b in rec["blocks"]:
            if b:
                self._alloc.decref(b)
        self._stats["kv_stream_bytes"] += payload.nbytes
        return payload

    def export_session(self, session_id: str) -> KVBlockPayload | None:
        """Pop a RESIDENT parked session and hand it over as a
        KVBlockPayload (blocks gathered, then freed) — the fleet
        reattach's cross-replica pull: when a reattaching turn lands
        on a different replica than the session's HBM home, the router
        pulls the payload here and seeds it there. None when this
        engine holds nothing for the id (the caller falls through to
        the store tiers, then to re-prefill)."""
        if not self.paged:
            return None
        rec = self._sessions.pop(session_id, None)
        if rec is None:
            return None
        payload = self._session_to_payload(rec)
        st = self._stats
        st["kv_exports"] += 1
        st["kv_exported_blocks"] += payload.num_blocks
        return payload

    def warmup_kv_stream(self) -> None:
        """Compile the KV stream's two programs with one empty-blocks
        roundtrip mirroring the real export→host→import data path, so
        the first real handoff performs zero compiles (the disagg A/B's
        tripwire). Call AFTER warmup(): the gather must see the
        steady-state (committed) pool. No-op on the dense engine."""
        if not self.paged:
            return
        leaves = self._gather_blocks([])
        self._scatter_blocks([], [a for _, a in leaves])

    def _expire_deadlines(self) -> int:
        """Retire every request past its ``deadline_s`` — still queued
        (shed before wasting a prefill on it) or resident in a slot (the
        slot frees for this very step's admissions). The engine keeps
        serving everything else; each expiry is a telemetry span plus the
        usual per-request row with the distinct finish reason."""
        now = time.perf_counter()

        def overdue(req: Request) -> bool:
            return (req.deadline_s is not None and req.submit_time is not None
                    and now - req.submit_time >= req.deadline_s)

        expired = ([r for r in self._queue if overdue(r)]
                   + [r for r in self._active.values() if overdue(r)])
        pf = getattr(self, "_prefilling", None) if self.paged else None
        if pf is not None and overdue(pf["req"]):
            # mid-chunked-prefill expiry: abandon the admission, free
            # its blocks and slot before it ever decodes
            self._release_slot(pf["slot"])
            self._prefilling = None
            expired.append(pf["req"])
        if not expired:
            return 0
        with self._span("serve/deadline_retire"):
            for req in expired:
                if req.slot is None and req in self._queue:
                    self._queue.remove(req)
                self._retire(req, "deadline")
        return len(expired)

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        """Step until queue, in-flight prefill and slots drain (tests /
        batch-mode use)."""
        while (self._queue or self._active
               or (self.paged and self._prefilling is not None)):
            if max_steps <= 0:
                raise RuntimeError("serving loop did not drain")
            self.step()
            max_steps -= 1

    def stream(self, req: Request):
        """Iterator over one request's tokens, stepping the engine (and
        every other resident request) as needed — the single-consumer
        streaming shape; concurrent consumers share the same step()s.
        Starts past any resume-from-tokens prefix: the client already
        holds those tokens (submit's delivery contract)."""
        sent = req.resumed_from
        while True:
            while sent < len(req.new_tokens):
                yield req.new_tokens[sent]
                sent += 1
            if req.done:
                return
            self.step()

    def warmup(self, prompt_lens=None, max_new_tokens: int = 2) -> None:
        """Compile the steady state up front: run dummy requests through
        each prefill bucket plus the decode tick, then reset stats —
        after this, serving performs ZERO recompiles (TRACE_COUNTS and the
        jitted programs' _cache_size are the tests' tripwires) and the
        first real TTFT pays no compile.

        TWO serial rounds per bucket on purpose (plain jit path): the
        engine's fresh cache is an uncommitted array, so round one
        compiles each program against it, and jit then recompiles —
        without retracing — when the cache next arrives committed from
        another executable's output. Round two runs every program with
        exactly the steady-state (committed) input shardings.

        With a compile cache attached (ISSUE 10), ONE round suffices:
        every program dispatches through an AOT executable whose input
        convention was fixed at lower time, so the fresh-vs-committed
        recompile the second round exists to absorb cannot happen — a
        cache hit makes the round a pure deserialized-executable probe
        (zero traces, zero XLA compiles), a miss compiles each program
        exactly once and publishes it. Either way the TTFT EMA is still
        reset below: warmup TTFTs (deserialize or compile) must never
        skew the router's balancer."""
        lens = tuple(prompt_lens) if prompt_lens else (self.bucket,)
        rounds = 1 if self._compile_cache is not None else 2
        for n in lens * rounds:
            n = max(1, min(n, self.cfg.max_seq_len - max_new_tokens))
            self.submit(np.zeros(n, np.int32), max_new_tokens=max_new_tokens)
            self.run_until_idle()
        if rounds == 1 and self._aot_failed:
            # a program fell back to jit during the single cached round
            # (cache defect / unserializable backend): give the jit
            # path its second round too, or the first real request
            # would pay the fresh-vs-committed recompile on the hot
            # path — the never-fails contract covers warmup's
            # no-first-TTFT-compile promise as well
            for n in lens:
                n = max(1, min(n, self.cfg.max_seq_len - max_new_tokens))
                self.submit(np.zeros(n, np.int32),
                            max_new_tokens=max_new_tokens)
                self.run_until_idle()
        # warm the health probe too: a router polling
        # check_params_finite() must find it compiled, or the first
        # steady-state health check pays a trace
        self.check_params_finite()
        # warmup TTFTs include COMPILES — a router balancing on the
        # TTFT EMA would permanently shun whichever replica compiled
        # first (the others warm from the shared jit cache in ms)
        self._ttft_ema = None
        if self.paged and self._radix is not None:
            self._radix.clear()  # don't serve real traffic warmup zeros
            self._radix.reset_stats()
        self.reset_stats()

    def drain(self) -> list[Request]:
        """Retire EVERY request — queued, mid-prefill, resident — with
        finish_reason "drained" and free their slots/blocks: the SIGTERM
        / shutdown exit path (pair with request_drain() from a signal
        handler; close() also drains). Returns the drained requests."""
        self._draining = False
        out: list[Request] = []
        if self.paged and self._sessions:
            # resident sessions demote down the hierarchy on shutdown
            # (store or spill queue) — restart-survival for the warm
            # tier, and close()'s leak assertion sees a clean pool
            self._demote_all_sessions()
        if self.paged and self._prefilling is not None:
            pf, self._prefilling = self._prefilling, None
            self._release_slot(pf["slot"])
            out.append(pf["req"])
        if self.paged and self._prefilled:
            # parked handoffs: release blocks before retiring (a parked
            # req's slot is NOT in _active — clear req.slot first so
            # _retire doesn't try to release it a second way)
            for rec in [self._prefilled.pop(k)
                        for k in list(self._prefilled)]:
                self._release_slot(rec["slot"])
                rec["req"].slot = None
                rec["req"].parked = False
                out.append(rec["req"])
        while self._queue:
            out.append(self._queue.popleft())
        out.extend(self._active.values())
        with self._span("serve/drain"):
            for req in out:
                self._retire(req, "drained")
        return out

    def request_drain(self) -> None:
        """Signal-handler-safe drain request: sets a flag the next
        step() honors (draining involves device/telemetry work that must
        not run inside a signal frame — the same finish-the-step
        discipline as the Trainer's SIGTERM checkpoint)."""
        self._draining = True

    def install_sigterm_drain(self) -> None:
        """Route SIGTERM to request_drain() — a preempted serving tier
        sheds its requests (streams get finish_reason "drained") instead
        of dying mid-tick with the pool in limbo."""
        import signal

        signal.signal(signal.SIGTERM, lambda *_: self.request_drain())

    def close(self) -> None:
        """Drain outstanding work, assert the paged pool's leak
        invariant (free + resident == pool: every retirement path must
        have returned its blocks), and flush telemetry."""
        self.drain()
        if self.paged:
            if self.telemetry is not None:
                st = self._stats
                spec = (dict(spec_k=self.spec_k,
                             draft_tokens=st["draft_tokens"],
                             accepted_tokens=st["accepted_tokens"],
                             acceptance_rate=(
                                 round(st["accepted_tokens"]
                                       / st["draft_tokens"], 4)
                                 if st["draft_tokens"] else None),
                             # learned-drafting identity (ISSUE 16):
                             # which draft served this engine, and how
                             # many hot-swaps it absorbed mid-serve
                             spec_heads=self._spec_heads,
                             draft_swaps=self.draft_swaps,
                             draft_params_hash=self.draft_params_hash(),
                             **(dict(accept_ema=round(
                                         float(self._accept_ema.mean()),
                                         4),
                                     effective_k=round(
                                         float(self._k_eff.mean()), 3))
                                if self.adaptive_k else {}))
                        if self.spec_k else {})
                per_block = self.kv_hbm_bytes // self.num_blocks
                self.telemetry.pool(
                    kv_hbm_bytes=self.kv_hbm_bytes,
                    block_size=self.block_size,
                    num_blocks=self.num_blocks,
                    kv_dtype=self.kv_dtype,
                    kv_bytes_resident=st["peak_blocks_used"] * per_block,
                    kv_tokens_capacity=(self._alloc.usable
                                        * self.block_size),
                    retired_blocks=st["retired_blocks"],
                    prefill_chunks=st["prefill_chunks"],
                    preemptions=st["preemptions"],
                    prefix_hit_tokens=st["prefix_hit_tokens"],
                    admitted_tokens=st["admitted_tokens"],
                    **spec,
                    **(self._radix.stats() if self._radix is not None
                       else {}))
            cached = (self._radix.block_count
                      if self._radix is not None else 0)
            self._alloc.check_leaks(expected_resident=cached)
            if self._radix is not None:
                self._radix.clear()
            self._alloc.check_leaks(0)
        if self.telemetry is not None:
            self.telemetry.close()

    # ------------------------------------------------------------------
    # internals

    def _aot_call(self, name, jit_fn, statics, args, kw_statics, *,
                  donation="cache"):
        """Dispatch one compiled-program call. With a compile cache:
        resolve ``name`` to an AOT ``jax.stages.Compiled`` (deserialize
        on a cache hit — no trace, no XLA compile; ``lower().compile()``
        + publish on a miss) and call it with the DYNAMIC args only
        (statics are baked into the executable). The AOT convention is
        fixed at lower time, so the fresh-vs-committed-cache recompile
        jit performs (the reason warmup ran two rounds) cannot happen
        here. Any failure — cache defect, a backend that cannot
        serialize, an executable rejecting a call — permanently falls
        this program back to the plain jit path: the cache may only
        ever make startup faster, never serving wrong or dead. Callers
        invoke this inside their ``_mesh_ctx()``, so lowering sees the
        same ambient mesh the jit path traces under."""
        ex = self._exec.get(name)
        if (ex is None and self._compile_cache is not None
                and name not in self._aot_failed):
            ex = self._aot_load_or_compile(name, jit_fn, statics, args,
                                           kw_statics, donation)
        if ex is not None:
            try:
                return ex(*args)
            except Exception as e:  # noqa: BLE001 — never-fails contract
                self._exec.pop(name, None)
                self._aot_failed.add(name)
                if self._compile_cache is not None:
                    self._compile_cache.note_exec_failure(name, e)
                # signature/sharding rejections raise BEFORE execution,
                # leaving the donated buffers intact for the jit retry;
                # a mid-execution failure (runtime error, OOM) has
                # already consumed them — re-raise the REAL error
                # rather than letting the retry mask it with a bogus
                # "Array has been deleted"
                if any(getattr(a, "is_deleted", lambda: False)()
                       for a in jax.tree_util.tree_leaves(args)):
                    raise
        return jit_fn(*statics, *args, **kw_statics)

    def _aot_load_or_compile(self, name, jit_fn, statics, args,
                             kw_statics, donation):
        srepr = ";".join(
            [static_repr(s) for s in statics]
            + [f"{k}={v!r}" for k, v in sorted(kw_statics.items())])
        cfg_hash = (f"slots={self.num_slots};bucket={self.bucket};"
                    f"block={self.block_size};blocks={self.num_blocks};"
                    f"spec_k={self.spec_k};kvd={self.kv_dtype};"
                    f"sink={self.kv_sink_tokens};"
                    f"win={self.kv_window_tokens};"
                    f"pattn={self.paged_attn};"
                    # per-slot KV limits change the cache tree (kv_sinks/
                    # kv_windows leaves) — a stale windowed executable from
                    # before ISSUE 15 would deserialize against the wrong
                    # donation layout, so the flag is part of the key
                    f"pslot={int(self.per_slot_limits)};"
                    # ISSUE 16: proposal heads change the draft tree and
                    # the tick program; adaptive k adds the k_eff operand
                    f"sheads={self._spec_heads};"
                    f"adk={int(self.adaptive_k)}")

        def compile_fn():
            return jit_fn.lower(*statics, *args, **kw_statics).compile()

        try:
            compiled, outcome = self._compile_cache.load_or_compile(
                name, compile_fn, args, statics=srepr,
                config_hash=cfg_hash, donation=donation)
        except Exception as e:  # noqa: BLE001 — never-fails contract
            self._aot_failed.add(name)
            self._compile_cache.note_exec_failure(name, e)
            return None
        self._exec[name] = compiled
        self.aot_outcomes[name] = outcome
        return compiled

    def _mesh_ctx(self):
        return (jax.set_mesh(self.mesh) if self.mesh is not None
                else contextlib.nullcontext())

    def _span(self, name: str):
        return (self.telemetry.span(name) if self.telemetry is not None
                else contextlib.nullcontext())

    def _admit(self, req: Request) -> None:
        slot = self._free.pop()
        # a resume-from-tokens submit (router failover) prefills
        # prompt + already-generated — the dense twin of the paged
        # engine's preempt-requeue re-prefill; the continuation token is
        # sampled with fold_in count == resume so seeded streams pick up
        # exactly where they stopped
        tokens = np.concatenate(
            [req.prompt, np.asarray(req.new_tokens, np.int32)])
        n = int(tokens.size)
        resume = len(req.new_tokens)
        padded_len = min(-(-n // self.bucket) * self.bucket,
                         self.cfg.max_seq_len)
        padded = np.zeros((1, padded_len), np.int32)
        padded[0, :n] = tokens
        kd = np.asarray(jax.random.key_data(
            jax.random.key(req.sampling.seed)))
        t0 = time.perf_counter()
        with self._span("serve/prefill"), self._mesh_ctx():
            # one AOT program per prefill bucket length, same as the
            # one-jit-signature-per-bucket the plain path compiles
            self._cache, first = self._aot_call(
                f"prefill_b{padded_len}", prefill_into_slot,
                (self._prefill_model,),
                (self._weights, self._cache,
                 jnp.asarray(padded), jnp.int32(n), jnp.int32(slot),
                 jnp.asarray(kd), jnp.int32(resume),
                 jnp.float32(req.sampling.temperature),
                 jnp.int32(req.sampling.top_k),
                 jnp.float32(req.sampling.top_p)),
                dict(candidates=self.candidates))
            first = int(first)  # sync: the TTFT timestamp is honest
        now = time.perf_counter()
        self._progress += 1
        st = self._stats
        st["prefills"] += 1
        st["prefill_s"] += now - t0
        req.slot = slot
        if req.first_token_time is None:
            req.first_token_time = now
            if req.submit_time is not None:
                self._note_ttft(now - req.submit_time)
        self._trace_span(req, "prefill", req.submit_time, now,
                         resumed_from=req.resumed_from)
        self._active[slot] = req
        self._key_data[slot] = kd
        self._counts[slot] = resume + 1  # token n samples fold_in(key, n)
        self._temps[slot] = req.sampling.temperature
        self._top_ks[slot] = req.sampling.top_k
        self._top_ps[slot] = req.sampling.top_p
        self._deliver(req, first)

    def _deliver(self, req: Request, tok: int) -> None:
        req.new_tokens.append(tok)
        self._tokens[req.slot] = tok  # next tick's input for this slot
        if req.on_token is not None:
            req.on_token(req, tok)
        if tok in req.stop_ids:
            self._retire(req, "stop")
        elif len(req.new_tokens) >= req.max_new_tokens:
            self._retire(req, "length")

    def _retire(self, req: Request, reason: str) -> None:
        req.done = True
        req.finish_reason = reason
        req.finish_time = time.perf_counter()
        if req.slot is not None:  # deadline-expired in queue: no slot yet
            del self._active[req.slot]
            if self.paged:
                if (req.session_id is not None
                        and reason in ("stop", "length")):
                    # a CLEANLY finishing session turn parks its KV in
                    # the resident tier (ownership transfers off the
                    # slot before the release below); sheds —
                    # deadline, drain — free normally, the store's
                    # older copy (if any) stays the session's truth
                    self._park_session(req)
                # EVERY retirement path funnels here: the slot's blocks
                # go back to the pool (or live on only through the radix
                # cache's own reference) — close() asserts none leak
                self._release_slot(req.slot)
            else:
                self._free.append(req.slot)
                self._temps[req.slot] = 0.0  # idle slots tick greedy
        self._stats["completed"] += 1
        if reason == "deadline":
            self._stats["deadline_expired"] += 1
        self._trace_span(
            req, "decode",
            (req.first_token_time if req.first_token_time is not None
             else req.submit_time),
            req.finish_time, new_tokens=len(req.new_tokens),
            finish_reason=reason, preemptions=req.preemptions)
        if self.telemetry is not None:
            self.telemetry.request(req)

    def _trace_span(self, req: Request, stage: str, t0, t1,
                    **attrs) -> None:
        """Emit one request-trace span (ISSUE 17) — a no-op unless BOTH
        a tracer is wired and the request carries a TraceContext, so
        tracing off costs one attribute read per lifecycle edge."""
        if self.trace is None or req.trace is None or t0 is None:
            return
        if self.telemetry is not None:
            attrs.setdefault("replica", self.telemetry.rank)
        self.trace.span(req.trace, stage, t0, t1, **attrs)

    def _note_ttft(self, dt: float) -> None:
        self._stats["ttft_s"].append(dt)
        self._ttft_ema = (dt if self._ttft_ema is None
                          else 0.8 * self._ttft_ema + 0.2 * dt)

    # ------------------------------------------------------------------
    # health (ISSUE 9): the snapshot the replica router polls

    def health(self) -> dict:
        """One host-side health/load snapshot — NO device work (the
        params-finite probe is ``check_params_finite``, priced
        separately so the router chooses its cadence):

          * ``progress`` — monotonic count of completed compiled calls
            (ticks + prefills + chunks). A replica with work whose
            watermark stops moving is hung (the serving analog of
            runtime/heartbeat.py's device-sync rule: every increment
            sits after a host sync of device results, so it can't be
            the async-dispatch illusion);
          * ``occupancy`` / ``queued`` / ``free_slots`` /
            ``prefilling`` — the load-balancing signals;
          * ``pool_free_frac`` — paged pool headroom (1.0 dense);
          * ``ttft_ema_s`` — smoothed recent time-to-first-token;
          * ``sick`` — the last params-finite probe verdict (True
            after a NaN poisoning until the probe passes again)."""
        free_frac = 1.0
        if self.paged:
            free_frac = self._alloc.free_count / max(1, self._alloc.usable)
        out = {
            "alive": True,
            "progress": self._progress,
            "active": len(self._active),
            "queued": len(self._queue),
            "free_slots": len(self._free),
            "prefilling": self.prefilling_count,
            "num_slots": self.num_slots,
            "occupancy": len(self._active) / self.num_slots,
            "pool_free_frac": round(free_frac, 4),
            "ttft_ema_s": self._ttft_ema,
            "sick": self._sick,
            # process-wide compiled-program census: a soak's invariant
            # checker watches this NOT grow on survivors (fresh XLA
            # traces mid-serving mean the warmup contract broke)
            "trace_count": int(sum(TRACE_COUNTS.values())),
        }
        if self.paged:
            # the disagg signals (ISSUE 12): parked handoffs awaiting
            # export, the pool geometry a router needs to hash prompts
            # for fleet prefix steering, this replica's published
            # block-hash frontier, and the cross-replica hit counters
            out["parked"] = len(self._prefilled)
            out["block_size"] = self.block_size
            out["kv_dtype"] = self.kv_dtype
            out["remote_hit_tokens"] = self._stats["remote_hit_tokens"]
            out["admitted_tokens"] = self._stats["admitted_tokens"]
            if self._radix is not None:
                out["prefix_frontier"] = self._radix.frontier()
            # the session signals (ISSUE 18): how many sessions park
            # in this replica's HBM tier, and WHICH — the router's
            # FleetSessionIndex steers reattaching requests by this
            # frontier exactly like prefix steering
            out["sessions_resident"] = len(self._sessions)
            out["session_frontier"] = list(self._sessions)[-64:]
        return out

    def check_params_finite(self) -> bool:
        """Run the compiled params-finite probe (one scalar sync) and
        record the verdict in ``health()['sick']``. False = this
        replica's weights carry NaN/Inf — every token it emits is
        garbage and a router must quarantine it."""
        with self._mesh_ctx():
            ok = bool(self._aot_call("params_finite", params_finite, (),
                                     (self._weights,), {}, donation=""))
        self._sick = not ok
        return ok

    def set_params(self, params) -> None:
        """Swap the serving weights in place (same treedef — the
        compiled programs retrace on a structure change, never on new
        values). The quarantine/rejoin path: an operator repairs a
        NaN'd replica by reloading a verified checkpoint here, then the
        router's warmup re-admission probes it healthy again."""
        self._weights = params["params"] if "params" in params else params

    def set_draft_params(self, params) -> None:
        """Hot-swap the DRAFT weights mid-serving (ISSUE 16) — the
        distill→swap→measure loop's serve-side handle. The new tree must
        match the current draft's structure and leaf shapes exactly (the
        draft ARCHITECTURE is baked into the compiled tick; only values
        may move), which also guarantees no retrace: resident streams
        keep ticking and their tokens never change — draft quality moves
        ACCEPTANCE only, the rejection kernel is lossless either way
        (greedy streams are bitwise-identical across the swap; tests pin
        that mid-stream)."""
        if not self.spec_k:
            raise ValueError(
                "set_draft_params on a non-speculative engine (spec_k "
                "== 0): there is no draft to swap")
        import flax.linen as nn

        new = nn.meta.unbox(params["params"] if "params" in params
                            else params)
        old_leaves = jax.tree_util.tree_flatten_with_path(
            self._draft_weights)
        new_leaves = jax.tree_util.tree_flatten_with_path(new)
        if old_leaves[1] != new_leaves[1]:
            raise ValueError(
                "draft param tree structure mismatch — a hot-swap may "
                "only replace VALUES for the architecture the engine "
                "compiled (same num_layers / spec_heads; rebuild the "
                "engine to change the draft's shape)")
        for (path, a), (_, b) in zip(old_leaves[0], new_leaves[0]):
            if getattr(a, "shape", None) != getattr(b, "shape", None):
                raise ValueError(
                    f"draft param shape mismatch at "
                    f"{jax.tree_util.keystr(path)}: engine has "
                    f"{getattr(a, 'shape', None)}, swap brings "
                    f"{getattr(b, 'shape', None)}")
            if jnp.asarray(b).dtype != getattr(a, "dtype", None):
                raise ValueError(
                    f"draft param dtype mismatch at "
                    f"{jax.tree_util.keystr(path)}: engine compiled "
                    f"{getattr(a, 'dtype', None)}, swap brings "
                    f"{jnp.asarray(b).dtype} — precision is baked into "
                    f"the tick; a cast here would not be value-lossless")
        # re-place each leaf to be cache-key-identical to the RESIDENT
        # leaf: the pjit cache keys on sharding AND committedness, so a
        # checkpoint restored under a trainer mesh (committed
        # NamedSharding leaves vs the boot tree's uncommitted
        # default-device ones) would silently retrace the tick — and
        # the first post-swap step would stall a subprocess replica
        # straight into the router's hang watchdog
        def _like(b, a):
            if not hasattr(a, "sharding"):
                return jnp.asarray(b)
            if getattr(a, "_committed", True):
                return jax.device_put(b, a.sharding)
            # uncommitted resident leaf: round-trip through host so the
            # result is an uncommitted default-device array too
            return jnp.asarray(np.asarray(b))

        self._draft_weights = jax.tree.map(_like, new,
                                           self._draft_weights)
        self.draft_swaps += 1
        self._draft_hash = None  # recomputed lazily on next read

    def draft_params_hash(self) -> str | None:
        """8-hex fingerprint of the CURRENT draft weights (None when
        spec is off) — per-leaf fp32 sums hashed with the tree paths, so
        a replica row can show WHICH draft it serves and a fleet
        broadcast can be audited replica-by-replica without shipping
        trees around. Computed lazily, cached until the next swap."""
        if not self.spec_k:
            return None
        if getattr(self, "_draft_hash", None) is None:
            h = hashlib.sha1()
            for path, leaf in jax.tree_util.tree_leaves_with_path(
                    self._draft_weights):
                h.update(jax.tree_util.keystr(path).encode())
                h.update(np.float64(
                    jnp.sum(jnp.asarray(leaf, jnp.float32))).tobytes())
            self._draft_hash = h.hexdigest()[:8]
        return self._draft_hash

    def invalidate_prefix_cache(self) -> None:
        """Drop every radix-cached prefix block (refcounts released; a
        block still referenced by a resident slot survives until that
        slot retires). A rejoining quarantined replica must do this:
        blocks cached while its params were NaN hold poisoned K/V that
        a future prefix hit would serve as truth."""
        if self.paged and self._radix is not None:
            self._radix.clear()

    # ------------------------------------------------------------------
    # stats

    def reset_stats(self) -> None:
        self._stats = dict(ticks=0, tick_s=0.0, prefills=0, prefill_s=0.0,
                           decode_tokens=0, occupancy_sum=0.0, completed=0,
                           deadline_expired=0, ttft_s=[],
                           # paged-mode counters (stay 0 on dense)
                           admissions=0, admitted_tokens=0,
                           prefix_hit_tokens=0, prefill_chunks=0,
                           preemptions=0, preempted_requests=0,
                           block_used_sum=0.0,
                           # KV-compression counters (ISSUE 13):
                           # high-water pool occupancy in blocks (the
                           # kv_bytes_resident numerator) and blocks
                           # retired mid-stream by the sliding window
                           peak_blocks_used=0, retired_blocks=0,
                           # disaggregation counters (ISSUE 12; stay 0
                           # colocated)
                           remote_hit_tokens=0, kv_exports=0,
                           kv_imports=0, kv_exported_blocks=0,
                           kv_imported_blocks=0, kv_stream_bytes=0,
                           # speculative counters (stay 0 when spec off)
                           draft_tokens=0, accepted_tokens=0,
                           target_forwards=0,
                           # persistent-session counters (ISSUE 18):
                           # detaches = turns parked/exported, attaches
                           # = reattach KV hits (any tier), seed_tokens
                           # = prefix tokens seeded from stored
                           # payloads, demotes = HBM -> store/spill
                           # evictions, dropped = spill-queue overflow
                           session_detaches=0, session_attaches=0,
                           session_seed_tokens=0, session_demotes=0,
                           session_dropped=0)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def active_count(self) -> int:
        return len(self._active)

    @property
    def prefilling_count(self) -> int:
        """Admissions mid-chunked-prefill (0 or 1; always 0 dense) —
        include it in any is-there-work-left check alongside queue_depth
        and active_count."""
        return int(self.paged and self._prefilling is not None)

    def summary(self) -> dict:
        """Aggregate serving metrics since the last reset_stats():
        steady-state decode tokens/s (decoded tokens over tick wall
        time, prefills excluded), TTFT percentiles, mean slot
        occupancy — the fields bench.py --mode serve stamps."""
        st = self._stats
        ttfts = np.asarray(st["ttft_s"], np.float64)
        out = {
            "requests_completed": st["completed"],
            "deadline_expired": st["deadline_expired"],
            "ticks": st["ticks"],
            "prefills": st["prefills"],
            "decode_tokens_per_s": (
                round(st["decode_tokens"] / st["tick_s"], 1)
                if st["tick_s"] > 0 else None),
            "slot_occupancy": (
                round(st["occupancy_sum"] / st["ticks"], 4)
                if st["ticks"] else None),
            "prefill_ms_mean": (
                round(st["prefill_s"] / st["prefills"] * 1e3, 3)
                if st["prefills"] else None),
        }
        if ttfts.size:
            out["ttft_ms_p50"] = round(
                float(np.percentile(ttfts, 50)) * 1e3, 3)
            out["ttft_ms_p99"] = round(
                float(np.percentile(ttfts, 99)) * 1e3, 3)
        out["kv_hbm_bytes"] = self.kv_hbm_bytes
        if self.paged:
            out["block_size"] = self.block_size
            out["num_blocks"] = self.num_blocks
            # KV-compression telemetry (ISSUE 13): the pool's storage
            # dtype, its token capacity after the reserved trash block,
            # the high-water HBM actually resident in KV blocks
            # (peak blocks x bytes/block, scale planes included), and
            # how many blocks the sliding window retired mid-stream
            out["kv_dtype"] = self.kv_dtype
            out["kv_tokens_capacity"] = (self._alloc.usable
                                         * self.block_size)
            out["kv_bytes_resident"] = (
                st["peak_blocks_used"]
                * (self.kv_hbm_bytes // self.num_blocks))
            out["peak_blocks_used"] = st["peak_blocks_used"]
            out["retired_blocks"] = st["retired_blocks"]
            if self.kv_window_tokens:
                out["kv_window_tokens"] = self.kv_window_tokens
                out["kv_sink_tokens"] = self.kv_sink_tokens
            out["paged_attn"] = self.paged_attn
            out["prefill_chunks"] = st["prefill_chunks"]
            out["preemptions"] = st["preemptions"]
            out["preempted_requests"] = st["preempted_requests"]
            out["block_utilization"] = (
                round(st["block_used_sum"] / st["ticks"], 4)
                if st["ticks"] else None)
            # prefix_hit_rate stays LOCAL-only (comparable to
            # single-engine runs); fleet-shipped prefix hits report as
            # cross_replica_hit_rate — the steering win, priced apart
            out["prefix_hit_rate"] = (
                round((st["prefix_hit_tokens"]
                       - st["remote_hit_tokens"])
                      / st["admitted_tokens"], 4)
                if st["admitted_tokens"] else None)
            out["prefix_hit_tokens"] = st["prefix_hit_tokens"]
            out["remote_hit_tokens"] = st["remote_hit_tokens"]
            out["admitted_tokens"] = st["admitted_tokens"]
            out["cross_replica_hit_rate"] = (
                round(st["remote_hit_tokens"] / st["admitted_tokens"], 4)
                if st["admitted_tokens"] else None)
            out["kv_exports"] = st["kv_exports"]
            out["kv_imports"] = st["kv_imports"]
            out["kv_exported_blocks"] = st["kv_exported_blocks"]
            out["kv_imported_blocks"] = st["kv_imported_blocks"]
            out["kv_stream_bytes"] = st["kv_stream_bytes"]
            # persistent-session telemetry (ISSUE 18): the HBM tier's
            # current residency and the lifecycle counters — the
            # host-DRAM/disk tiers report from SessionStore.stats()
            per_block = self.kv_hbm_bytes // self.num_blocks
            out["sessions"] = dict(
                resident=len(self._sessions),
                resident_blocks=sum(
                    len(r["blocks"])
                    for r in self._sessions.values()),
                resident_bytes=per_block * sum(
                    len(r["blocks"])
                    for r in self._sessions.values()),
                detaches=st["session_detaches"],
                attaches=st["session_attaches"],
                seed_tokens=st["session_seed_tokens"],
                demotes=st["session_demotes"],
                dropped=st["session_dropped"])
            if self._radix is not None:
                out["prefix_cache"] = self._radix.stats()
        if self.spec_k:
            out["spec_k"] = self.spec_k
            out["draft_tokens"] = st["draft_tokens"]
            out["accepted_tokens"] = st["accepted_tokens"]
            out["acceptance_rate"] = (
                round(st["accepted_tokens"] / st["draft_tokens"], 4)
                if st["draft_tokens"] else None)
            # emitted tokens per target-model forward — the speculative
            # multiplier on the memory-bound decode path (1.0 when spec
            # is off; up to spec_k + 1 at full acceptance)
            out["tokens_per_target_forward"] = (
                round(st["decode_tokens"] / st["target_forwards"], 3)
                if st["target_forwards"] else None)
            out["draft_kv_hbm_bytes"] = self.draft_kv_hbm_bytes
            # learned-drafting telemetry (ISSUE 16): which draft this
            # engine serves (fingerprint + how many hot-swaps it has
            # absorbed), the head-parallel flag, and — adaptive mode —
            # the fleet-mean acceptance EMA and effective depth
            out["spec_heads"] = self._spec_heads
            out["adaptive_k"] = self.adaptive_k
            out["draft_swaps"] = self.draft_swaps
            out["draft_params_hash"] = self.draft_params_hash()
            if self.adaptive_k:
                out["accept_ema"] = round(
                    float(self._accept_ema.mean()), 4)
                out["effective_k"] = round(float(self._k_eff.mean()), 3)
        return out
