"""Continuous-batching serving engine: a slot-based KV-cache scheduler
over a single compiled decode step.

`inference.generate()` is a one-shot batch call: every request in a batch
must start together and run to the same max_new_tokens, so short requests
pay for long ones and new arrivals wait for the whole batch to drain.
This module is the Orca-style fix (iteration-level scheduling) with a
vLLM-style fixed-slot cache, realized TPU-natively:

  * the engine owns ONE persistent KV cache of ``num_slots`` rows
    (`[slots, max_seq_len, kv_heads, head_dim]` per layer — the model's
    existing ``decode=True`` cache collection at ``decode_slots > 0``,
    where every position counter is a per-row vector);
  * a jitted **decode tick** (`decode_tick`) advances ALL slots one token
    per call — per-slot lengths ride the position counters/masks inside
    the model, per-request sampling params are dynamic `[slots]` arrays
    (`inference.sample_slots`), and the cache is donated, so steady-state
    decode is one fixed-shape program with zero retraces and zero cache
    copies;
  * a jitted **prefill** (`prefill_into_slot`) runs one request's chunked
    prompt forward (batch 1, prompts right-padded to a bucket multiple so
    variable lengths hit a handful of programs) and writes the resulting
    cache rows into a free slot via `dynamic_update_slice`, rewinding
    that slot's position counters to the true prompt length;
  * a host-side scheduler (`ServingEngine`) keeps the request queue,
    admits a prefill whenever a slot frees, retires on stop-ids /
    max-token budget, streams tokens per request (callbacks or the
    `stream()` iterator), and bridges TTFT / tokens-per-s / queue depth /
    slot occupancy into telemetry/ (serving.telemetry).

Composition: params may be dp/tp sharded (pass the mesh) and quantized
(`--quant` int8 policies) exactly as generate() accepts them — the tick
and prefill run the same decode einsums under the same logical rules.
Greedy outputs are bitwise-equal to generate()'s per request, for any
admission order (tests/test_serving.py pins it).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import functools
import itertools
import time

import jax
import jax.numpy as jnp
import numpy as np

from pytorchdistributed_tpu.inference import (
    _zero_cache,
    sample_slots,
    stop_ids_tuple,
)
from pytorchdistributed_tpu.serving.telemetry import ServingTelemetry

# Traced-body invocation counter (same discipline as inference.
# TRACE_COUNTS): the zero-recompiles-after-warmup guarantee is asserted
# against these — a steady-state serving loop must never move them.
TRACE_COUNTS: collections.Counter = collections.Counter()


def slot_models(model, num_slots: int):
    """(tick_model, prefill_model) for a causal LM module.

    The tick model decodes with per-row position counters
    (``decode_slots=num_slots``; batch == slots); the prefill model is the
    plain scalar-counter decode model at batch 1 (a single request starts
    from position 0, so it needs no per-row state). Both attend over the
    full max_seq_len window (slots sit at arbitrary positions) on the
    cache-masked dense path — the training-time attention backend knob
    does not apply to decode, so it is pinned to "dense" here to keep the
    clone warning-free."""
    cfg = dataclasses.replace(
        model.cfg, decode=True, attention="dense", decode_attend_len=None,
        decode_slots=0)
    return (model.clone(cfg=dataclasses.replace(
                cfg, decode_slots=num_slots)),
            model.clone(cfg=cfg))


def _leaf_name(path) -> str:
    return getattr(path[-1], "key", str(path[-1]))


@functools.partial(
    jax.jit,
    static_argnames=("model", "candidates"),
    donate_argnames=("cache",))
def decode_tick(model, weights, cache, tokens, key_data, counts,
                temperature, top_k, top_p, *, candidates: int):
    """Advance every slot one token: ONE model apply over ``[slots, 1]``
    last-tokens (each slot reads/writes its own cache row at its own
    position) + the per-slot sampler. Free/retired slots tick along as
    greedy garbage — the fixed-shape price of zero retraces; the host
    simply ignores their outputs.

    ``key_data``/``counts`` carry each request's seeded stream: token n of
    a request is sampled with fold_in(key(seed), n), so outputs are
    deterministic per request no matter which slot or admission order it
    got (the determinism test's property)."""
    TRACE_COUNTS["decode_tick"] += 1
    logits, mut = model.apply({"params": weights, "cache": cache},
                              tokens[:, None], mutable=["cache"])
    keys = jax.random.wrap_key_data(key_data)
    subs = jax.vmap(jax.random.fold_in)(keys, counts)
    nxt = sample_slots(logits[:, 0].astype(jnp.float32), subs,
                       temperature, top_k, top_p, candidates=candidates)
    return mut["cache"], nxt


@functools.partial(
    jax.jit,
    static_argnames=("model", "candidates"),
    donate_argnames=("cache",))
def prefill_into_slot(model, weights, cache, prompt, true_len, slot,
                      key_data, temperature, top_k, top_p, *,
                      candidates: int):
    """Admit one request: a chunked prompt forward (batch 1, prompt
    right-padded to the bucket length — ``true_len`` is dynamic) fills a
    fresh single-row cache, whose rows are written into ``slot`` of the
    engine cache via dynamic_update_slice; the slot's position counters
    are rewound to ``true_len`` (pad rows sit beyond the position mask
    until decode overwrites them — the same trick as
    inference.generate_bucketed). Returns (cache, first_token): sampling
    the first token here is what makes TTFT one prefill, not
    prefill + a decode tick."""
    TRACE_COUNTS["prefill"] += 1
    fresh = _zero_cache(model, prompt)
    logits, mut = model.apply({"params": weights, "cache": fresh}, prompt,
                              mutable=["cache"])
    last = jax.lax.dynamic_slice_in_dim(logits, true_len - 1, 1, axis=1)
    keys = jax.random.wrap_key_data(key_data[None])
    subs = jax.vmap(jax.random.fold_in)(keys, jnp.zeros((1,), jnp.int32))
    first = sample_slots(last[:, 0].astype(jnp.float32), subs,
                         temperature[None], top_k[None], top_p[None],
                         candidates=candidates)[0]

    def merge(path, big, small):
        if _leaf_name(path) in ("index", "pos_index"):
            # rewind to the true prompt length (the padded prefill
            # advanced the single-row counters to the bucket length)
            return jnp.where(jnp.arange(big.shape[-1]) == slot,
                             true_len, big)
        # K/V rows: [..., slots, max_seq_len, kv_heads, head_dim] — the
        # slot axis is always 4 dims from the end, scanned-layer or not
        axis = big.ndim - 4
        start = tuple(slot if d == axis else 0 for d in range(big.ndim))
        return jax.lax.dynamic_update_slice(big, small, start)

    new_cache = jax.tree_util.tree_map_with_path(merge, cache, mut["cache"])
    return new_cache, first


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs (dynamic per slot — any mix of requests
    shares the one compiled tick). temperature 0 = greedy; top_k <= 0 and
    top_p >= 1 disable their filters; seed starts the request's private
    PRNG stream."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0


class Request:
    """One submitted generation: prompt + budget + sampling + stop ids,
    and the engine-filled lifecycle (tokens as they stream, timestamps,
    finish reason). Host-side only — nothing here touches the device."""

    _ids = itertools.count()

    def __init__(self, prompt, max_new_tokens: int,
                 sampling: SamplingParams, stop_ids: tuple[int, ...],
                 on_token=None, deadline_s: float | None = None):
        self.id = next(Request._ids)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = max_new_tokens
        self.sampling = sampling
        self.stop_ids = stop_ids
        self.on_token = on_token
        self.deadline_s = deadline_s
        self.new_tokens: list[int] = []
        self.slot: int | None = None
        self.done = False
        self.finish_reason: str | None = None
        self.submit_time: float | None = None
        self.first_token_time: float | None = None
        self.finish_time: float | None = None

    @property
    def output_ids(self) -> np.ndarray:
        """prompt + generated continuation (int32 [len])."""
        return np.concatenate(
            [self.prompt, np.asarray(self.new_tokens, np.int32)])

    @property
    def ttft_s(self) -> float | None:
        """Time to first token, queue wait included."""
        if self.first_token_time is None or self.submit_time is None:
            return None
        return self.first_token_time - self.submit_time

    @property
    def decode_tokens_per_s(self) -> float | None:
        """Post-prefill decode rate of this request (None until done or
        when the request finished at its first token)."""
        if self.finish_time is None or self.first_token_time is None:
            return None
        dt = self.finish_time - self.first_token_time
        n = len(self.new_tokens) - 1
        if n <= 0 or dt <= 0:
            return None
        return round(n / dt, 3)


class ServingEngine:
    """The host scheduler over the compiled tick/prefill pair.

    Args:
      model: a causal LM module (GPT2 / Llama ...) — decode or train
        config; the engine derives its slot-decode twin either way.
      params: the trained variables, possibly sharded (pass ``mesh``).
      num_slots: concurrent requests resident in the KV cache — the
        engine's batch dim, fixed at compile time.
      prefill_bucket: prompts are right-padded up to this multiple so
        variable lengths reuse a handful of prefill programs (clamped to
        max_seq_len).
      candidates: static top-k candidate width of the per-slot sampler
        (per-request top_k caps here; see inference.sample_slots).
      mesh: optional jax mesh the params live on (tp/dp) — tick/prefill
        trace under it, exactly like generate().
      telemetry / telemetry_dir: a ServingTelemetry (or a run dir to
        build one) for spans + serve-metric JSONL; None = off.
    """

    def __init__(self, model, params, *, num_slots: int = 4,
                 prefill_bucket: int = 128, candidates: int = 64,
                 mesh=None, telemetry: ServingTelemetry | None = None,
                 telemetry_dir=None):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = num_slots
        self.candidates = candidates
        self.mesh = mesh
        self._tick_model, self._prefill_model = slot_models(model, num_slots)
        self.cfg = self._tick_model.cfg
        self.bucket = max(1, min(prefill_bucket, self.cfg.max_seq_len))
        self._weights = params["params"] if "params" in params else params
        with self._mesh_ctx():
            self._cache = _zero_cache(
                self._tick_model, jnp.zeros((num_slots, 1), jnp.int32))
        kd = np.asarray(jax.random.key_data(jax.random.key(0)))
        self._key_data = np.zeros((num_slots,) + kd.shape, kd.dtype)
        self._tokens = np.zeros(num_slots, np.int32)
        self._counts = np.zeros(num_slots, np.int32)
        self._temps = np.zeros(num_slots, np.float32)
        self._top_ks = np.zeros(num_slots, np.int32)
        self._top_ps = np.ones(num_slots, np.float32)
        self._free = list(reversed(range(num_slots)))  # pop() -> slot 0
        self._queue: collections.deque[Request] = collections.deque()
        self._active: dict[int, Request] = {}
        if telemetry is None and telemetry_dir is not None:
            telemetry = ServingTelemetry(telemetry_dir)
        self.telemetry = telemetry
        self.reset_stats()

    # ------------------------------------------------------------------
    # submission

    def submit(self, prompt, *, max_new_tokens: int,
               sampling: SamplingParams | None = None, stop_ids=None,
               on_token=None, deadline_s: float | None = None) -> Request:
        """Queue one request; returns its handle (tokens stream into
        ``handle.new_tokens`` / the on_token callback as the engine
        steps). ``stop_ids`` accepts a single id or a sequence.
        ``deadline_s`` is a wall-clock budget from submission: a request
        past it — queued or mid-decode — is retired with finish_reason
        "deadline" (whatever tokens it produced stay delivered) and its
        slot is freed for the next arrival; the other slots are never
        disturbed. The robustness knob a serving tier needs under
        overload — a stuck client budget must shed, not wedge, the
        engine."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if deadline_s is not None and deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0, got {deadline_s}")
        if prompt.size + max_new_tokens > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt_len {prompt.size} + max_new_tokens "
                f"{max_new_tokens} exceeds max_seq_len "
                f"{self.cfg.max_seq_len}")
        req = Request(prompt, max_new_tokens, sampling or SamplingParams(),
                      stop_ids_tuple(stop_ids), on_token,
                      deadline_s=deadline_s)
        req.submit_time = time.perf_counter()
        self._queue.append(req)
        return req

    # ------------------------------------------------------------------
    # the scheduler loop

    def step(self) -> dict:
        """One scheduler iteration: shed deadline-expired requests, admit
        prefills while slots are free, then ONE decode tick over all
        slots; deliver + retire from the synced tokens. Returns a small
        stats dict."""
        expired = self._expire_deadlines()
        admitted = 0
        while self._free and self._queue:
            self._admit(self._queue.popleft())
            admitted += 1
        decoded = 0
        if self._active:
            t0 = time.perf_counter()
            with self._span("serve/decode_tick"), self._mesh_ctx():
                self._cache, nxt = decode_tick(
                    self._tick_model, self._weights, self._cache,
                    jnp.asarray(self._tokens),
                    jnp.asarray(self._key_data),
                    jnp.asarray(self._counts),
                    jnp.asarray(self._temps),
                    jnp.asarray(self._top_ks),
                    jnp.asarray(self._top_ps),
                    candidates=self.candidates)
                toks = np.asarray(nxt)  # host sync: streaming delivery
            dt = time.perf_counter() - t0
            self._counts += 1
            st = self._stats
            st["ticks"] += 1
            st["tick_s"] += dt
            st["occupancy_sum"] += len(self._active) / self.num_slots
            for slot, req in list(self._active.items()):
                self._deliver(req, int(toks[slot]))
                decoded += 1
            st["decode_tokens"] += decoded
            if self.telemetry is not None:
                self.telemetry.tick(
                    tick=st["ticks"], tick_ms=round(dt * 1e3, 3),
                    active=len(self._active), queued=len(self._queue),
                    slot_occupancy=round(decoded / self.num_slots, 4))
        return {"admitted": admitted, "decoded": decoded,
                "expired": expired, "active": len(self._active),
                "queued": len(self._queue)}

    def _expire_deadlines(self) -> int:
        """Retire every request past its ``deadline_s`` — still queued
        (shed before wasting a prefill on it) or resident in a slot (the
        slot frees for this very step's admissions). The engine keeps
        serving everything else; each expiry is a telemetry span plus the
        usual per-request row with the distinct finish reason."""
        now = time.perf_counter()

        def overdue(req: Request) -> bool:
            return (req.deadline_s is not None and req.submit_time is not None
                    and now - req.submit_time >= req.deadline_s)

        expired = ([r for r in self._queue if overdue(r)]
                   + [r for r in self._active.values() if overdue(r)])
        if not expired:
            return 0
        with self._span("serve/deadline_retire"):
            for req in expired:
                if req.slot is None:
                    self._queue.remove(req)
                self._retire(req, "deadline")
        return len(expired)

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        """Step until queue and slots drain (tests / batch-mode use)."""
        while self._queue or self._active:
            if max_steps <= 0:
                raise RuntimeError("serving loop did not drain")
            self.step()
            max_steps -= 1

    def stream(self, req: Request):
        """Iterator over one request's tokens, stepping the engine (and
        every other resident request) as needed — the single-consumer
        streaming shape; concurrent consumers share the same step()s."""
        sent = 0
        while True:
            while sent < len(req.new_tokens):
                yield req.new_tokens[sent]
                sent += 1
            if req.done:
                return
            self.step()

    def warmup(self, prompt_lens=None, max_new_tokens: int = 2) -> None:
        """Compile the steady state up front: run dummy requests through
        each prefill bucket plus the decode tick, then reset stats —
        after this, serving performs ZERO recompiles (TRACE_COUNTS and the
        jitted programs' _cache_size are the tests' tripwires) and the
        first real TTFT pays no compile.

        TWO serial rounds per bucket on purpose: the engine's fresh cache
        is an uncommitted array, so round one compiles each program
        against it, and jit then recompiles — without retracing — when
        the cache next arrives committed from another executable's
        output. Round two runs every program with exactly the
        steady-state (committed) input shardings."""
        lens = tuple(prompt_lens) if prompt_lens else (self.bucket,)
        for n in lens + lens:
            n = max(1, min(n, self.cfg.max_seq_len - max_new_tokens))
            self.submit(np.zeros(n, np.int32), max_new_tokens=max_new_tokens)
            self.run_until_idle()
        self.reset_stats()

    def close(self) -> None:
        if self.telemetry is not None:
            self.telemetry.close()

    # ------------------------------------------------------------------
    # internals

    def _mesh_ctx(self):
        return (jax.set_mesh(self.mesh) if self.mesh is not None
                else contextlib.nullcontext())

    def _span(self, name: str):
        return (self.telemetry.span(name) if self.telemetry is not None
                else contextlib.nullcontext())

    def _admit(self, req: Request) -> None:
        slot = self._free.pop()
        n = req.prompt.size
        padded_len = min(-(-n // self.bucket) * self.bucket,
                         self.cfg.max_seq_len)
        padded = np.zeros((1, padded_len), np.int32)
        padded[0, :n] = req.prompt
        kd = np.asarray(jax.random.key_data(
            jax.random.key(req.sampling.seed)))
        t0 = time.perf_counter()
        with self._span("serve/prefill"), self._mesh_ctx():
            self._cache, first = prefill_into_slot(
                self._prefill_model, self._weights, self._cache,
                jnp.asarray(padded), jnp.int32(n), jnp.int32(slot),
                jnp.asarray(kd),
                jnp.float32(req.sampling.temperature),
                jnp.int32(req.sampling.top_k),
                jnp.float32(req.sampling.top_p),
                candidates=self.candidates)
            first = int(first)  # sync: the TTFT timestamp is honest
        now = time.perf_counter()
        st = self._stats
        st["prefills"] += 1
        st["prefill_s"] += now - t0
        req.slot = slot
        req.first_token_time = now
        if req.submit_time is not None:
            st["ttft_s"].append(now - req.submit_time)
        self._active[slot] = req
        self._key_data[slot] = kd
        self._counts[slot] = 1  # token n samples with fold_in(key, n)
        self._temps[slot] = req.sampling.temperature
        self._top_ks[slot] = req.sampling.top_k
        self._top_ps[slot] = req.sampling.top_p
        self._deliver(req, first)

    def _deliver(self, req: Request, tok: int) -> None:
        req.new_tokens.append(tok)
        self._tokens[req.slot] = tok  # next tick's input for this slot
        if req.on_token is not None:
            req.on_token(req, tok)
        if tok in req.stop_ids:
            self._retire(req, "stop")
        elif len(req.new_tokens) >= req.max_new_tokens:
            self._retire(req, "length")

    def _retire(self, req: Request, reason: str) -> None:
        req.done = True
        req.finish_reason = reason
        req.finish_time = time.perf_counter()
        if req.slot is not None:  # deadline-expired in queue: no slot yet
            del self._active[req.slot]
            self._free.append(req.slot)
            self._temps[req.slot] = 0.0  # idle slots tick greedy garbage
        self._stats["completed"] += 1
        if reason == "deadline":
            self._stats["deadline_expired"] += 1
        if self.telemetry is not None:
            self.telemetry.request(req)

    # ------------------------------------------------------------------
    # stats

    def reset_stats(self) -> None:
        self._stats = dict(ticks=0, tick_s=0.0, prefills=0, prefill_s=0.0,
                           decode_tokens=0, occupancy_sum=0.0, completed=0,
                           deadline_expired=0, ttft_s=[])

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def active_count(self) -> int:
        return len(self._active)

    def summary(self) -> dict:
        """Aggregate serving metrics since the last reset_stats():
        steady-state decode tokens/s (decoded tokens over tick wall
        time, prefills excluded), TTFT percentiles, mean slot
        occupancy — the fields bench.py --mode serve stamps."""
        st = self._stats
        ttfts = np.asarray(st["ttft_s"], np.float64)
        out = {
            "requests_completed": st["completed"],
            "deadline_expired": st["deadline_expired"],
            "ticks": st["ticks"],
            "prefills": st["prefills"],
            "decode_tokens_per_s": (
                round(st["decode_tokens"] / st["tick_s"], 1)
                if st["tick_s"] > 0 else None),
            "slot_occupancy": (
                round(st["occupancy_sum"] / st["ticks"], 4)
                if st["ticks"] else None),
            "prefill_ms_mean": (
                round(st["prefill_s"] / st["prefills"] * 1e3, 3)
                if st["prefills"] else None),
        }
        if ttfts.size:
            out["ttft_ms_p50"] = round(
                float(np.percentile(ttfts, 50)) * 1e3, 3)
            out["ttft_ms_p99"] = round(
                float(np.percentile(ttfts, 99)) * 1e3, 3)
        return out
