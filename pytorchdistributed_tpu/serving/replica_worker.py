"""Subprocess serving replica — the worker half of ReplicaRouter's
multi-process mode (ISSUE 9).

    PTD_REPLICA_SPEC='{"model": "gpt2", "size": "test", ...}' \
    RANK=0 WORLD_SIZE=2 python -m pytorchdistributed_tpu.serving.replica_worker

Reads the same env contract run.py gives training workers (RANK is the
replica index; MASTER_* ride along for future cross-replica state) plus
a JSON ``PTD_REPLICA_SPEC`` describing the model/engine to build, then
serves a line-JSON protocol on stdin/stdout — one response per op:

    {"op": "warmup", "prompt_lens": [16]}        -> {"ok": true}
    {"op": "submit", "rid": 3, "prompt": [...], ...} -> {"ok": true}
    {"op": "step"}   -> {"ok": true, "delivered": [[rid, tok], ...],
                         "finished": [[rid, reason], ...],
                         "health": {...}}
    {"op": "probe"}  -> {"finite": true}
    {"op": "drain"}  -> {"ok": true, "finished": [...]}
    {"op": "close"}  -> {"ok": true}  (then exits 0)

Liveness: PTD_HEARTBEAT_DIR (the run.py contract) gets a beat after
every step op — each beat follows the engine's host sync of device
results, honoring runtime/heartbeat.py's device-sync rule. SIGTERM
drains the engine and exits 0 (the router forwards it on teardown;
kill_group escalation covers a wedged worker). PTD_FAULTS serving
faults fire HERE, against this worker's own RANK: ``replica_crash``
os._exits mid-protocol, ``replica_hang`` SIGSTOPs (alive, silent — the
router's watchdog must catch it), ``replica_nan`` NaNs the params so
the router's probe op must come back non-finite.

The spec: {"model": "gpt2"|"llama", "size": "test", "overrides": {...
TransformerConfig overrides}, "init_seed": 1, "engine": {...
ServingEngine kwargs}, "max_seq_len": ..., "checkpoint": <dir>,
"checkpoint_step": <int>, "compile_cache": <dir>}. Params come from
``"checkpoint"`` when set — training/checkpoint.py's VERIFIED
params-only restore (manifest-checked, corrupt steps quarantined and
walked past), falling back to ``init_seed`` with a logged
TelemetryEvent when the checkpoint is absent or unusable (a worker
that cannot load weights must still join the fleet deterministically,
not die in a respawn loop). ``"compile_cache"`` points the engine at
the persistent AOT executable cache (runtime/compile_cache.py; the
PTD_COMPILE_CACHE env works too) — together they are what makes a
router-respawned replica serve again in load-bound seconds instead of
compile-bound minutes (ISSUE 10).

Speculative drafts (ISSUE 16): ``spec["engine"]["draft"]`` = {"num_layers":
<int|null>, "spec_heads": <int>, "checkpoint": <dir>, "checkpoint_step":
<int>} builds the draft with ``inference.make_draft`` (truncating the
TARGET's own restored weights, attaching zero-init proposal heads) and,
when the draft checkpoint is present, hot-loads the distilled weights
through the engine's verified ``set_draft_params`` path. The
``set_draft_params`` wire op carries a CHECKPOINT PATH, never a weight
tree: the worker restores it locally (CheckpointManager.restore_params —
the same manifest-verified restore as boot) and the engine's
structure/shape check decides; streams in flight keep their K/V and
their token-for-token identity (the spec rejection kernel is lossless
under ANY draft).
"""

from __future__ import annotations

import json
import os
import signal
import sys


def _load_params(spec: dict, model):
    """The worker's weights: a verified checkpoint restore when the
    spec names one (TelemetryEvent either way), else deterministic
    seed-init — replicas agree on params without shipping weights over
    a pipe."""
    import jax
    import jax.numpy as jnp

    from pytorchdistributed_tpu.telemetry.events import (
        EVENT_REPLICA_RESTORE,
        EVENT_REPLICA_RESTORE_FALLBACK,
        EventLog,
    )

    events = EventLog.from_env(int(os.environ.get("RANK", "0")))
    ckpt = spec.get("checkpoint")
    if ckpt:
        try:
            from pytorchdistributed_tpu.training.checkpoint import (
                CheckpointManager,
            )

            mgr = CheckpointManager(ckpt)
            try:
                params, step = mgr.restore_params(
                    step=spec.get("checkpoint_step"))
            finally:
                mgr.close()
            # Restored-as-saved trees carry orbax's rendering of flax
            # metadata nodes (nn.Partitioned boxes become plain dicts),
            # so re-shape the leaves onto the MODEL's own abstract
            # params structure — leaf order is stable (both are DFS
            # over the same module-path dicts; a metadata box is a
            # singleton wrapper) and the shape check below turns any
            # genuine mismatch (wrong model for this checkpoint) into
            # the seed-init fallback instead of a garbled apply. Also
            # re-commits host-numpy leaves to device arrays once.
            import flax.linen as nn

            abstract = nn.meta.unbox(jax.eval_shape(
                lambda: model.init(jax.random.key(0),
                                   jnp.zeros((1, 8), jnp.int32))))
            treedef = jax.tree_util.tree_structure(abstract)
            leaves = jax.tree_util.tree_leaves(params)
            want = jax.tree_util.tree_leaves(abstract)
            if len(leaves) != len(want):
                raise ValueError(
                    f"checkpoint has {len(leaves)} param leaves, model "
                    f"expects {len(want)}")
            for have, sds in zip(leaves, want):
                if tuple(have.shape) != tuple(sds.shape):
                    raise ValueError(
                        f"checkpoint leaf shape {tuple(have.shape)} != "
                        f"model's {tuple(sds.shape)}")
            if events is not None:
                events.emit(EVENT_REPLICA_RESTORE, step=step,
                            checkpoint=str(ckpt))
            return jax.tree_util.tree_unflatten(
                treedef, [jnp.asarray(x) for x in leaves])
        except Exception as e:  # noqa: BLE001 — worker must still join
            if events is not None:
                events.emit(EVENT_REPLICA_RESTORE_FALLBACK, step=-1,
                            checkpoint=str(ckpt),
                            error=f"{type(e).__name__}: {e}"[:200])
    return jax.jit(model.init)(
        jax.random.key(int(spec.get("init_seed", 0))),
        jnp.zeros((1, 8), jnp.int32))


def _restore_draft_params(path, step=None):
    """Verified params-only restore for a DRAFT weight tree (boot-time
    ``draft.checkpoint`` and the ``set_draft_params`` wire op share it).
    Raises on a missing/corrupt checkpoint — the engine-side structure
    and shape check then decides whether the tree actually fits."""
    import jax.numpy as jnp
    import jax

    from pytorchdistributed_tpu.training.checkpoint import (
        CheckpointManager,
    )

    mgr = CheckpointManager(path)
    try:
        params, ckpt_step = mgr.restore_params(step=step)
    finally:
        mgr.close()
    # re-commit host-numpy leaves once, as _load_params does
    return jax.tree.map(jnp.asarray, params), ckpt_step


def _drain_demoted_sessions(engine) -> list:
    """Wire-encode whatever sessions the engine's HBM budget (or its
    drain) demoted since the last sweep — they ride step/drain replies
    to the router, which persists them into the store tiers."""
    demoted = engine.take_demoted_sessions()
    if not demoted:
        return []
    from pytorchdistributed_tpu.serving.engine import kv_payload_to_wire

    return [[sid, tenant, kv_payload_to_wire(payload)]
            for sid, tenant, payload in demoted]


def _build_engine(spec: dict):
    from pytorchdistributed_tpu.models import (
        GPT2,
        Llama,
        gpt2_config,
        llama_config,
    )
    from pytorchdistributed_tpu.serving.engine import ServingEngine
    from pytorchdistributed_tpu.serving.telemetry import ServingTelemetry

    kind = spec.get("model", "gpt2")
    size = spec.get("size", "test")
    overrides = dict(spec.get("overrides", {}))
    if kind == "llama":
        cfg = llama_config(size, **overrides)
        model = Llama(cfg)
    else:
        cfg = gpt2_config(size, **overrides)
        model = GPT2(cfg)
    params = _load_params(spec, model)
    telemetry = ServingTelemetry.from_env()
    # each worker writes its own trace_rank{RANK}.jsonl — None (and
    # zero per-request work) unless the launcher exported PTD_TRACE
    from pytorchdistributed_tpu.telemetry.tracing import RequestTracer

    trace = RequestTracer.from_env()
    engine_kwargs = dict(spec.get("engine", {}))
    if spec.get("compile_cache"):
        engine_kwargs.setdefault("compile_cache", spec["compile_cache"])
    draft = engine_kwargs.pop("draft", None)
    draft_ckpt = None
    if draft:
        from pytorchdistributed_tpu.inference import make_draft

        draft_model, draft_params = make_draft(
            model, params, num_layers=draft.get("num_layers"),
            spec_heads=int(draft.get("spec_heads", 0)),
            seed=int(draft.get("seed", 0)))
        engine_kwargs.setdefault("draft_config", draft_model.cfg)
        engine_kwargs.setdefault("draft_params", draft_params)
        draft_ckpt = draft.get("checkpoint")
    engine = ServingEngine(model, params, telemetry=telemetry,
                           trace=trace, **engine_kwargs)
    if draft_ckpt:
        # distilled weights ride the SAME verified path as a later
        # hot-swap — a bad draft checkpoint degrades to the warm-start
        # draft (still lossless), it never kills the worker
        try:
            restored, _ = _restore_draft_params(
                draft_ckpt, draft.get("checkpoint_step"))
            engine.set_draft_params(restored)
        except Exception as e:  # noqa: BLE001 — worker must still join
            print(f"draft checkpoint {draft_ckpt} unusable "
                  f"({type(e).__name__}: {e}); serving warm-start draft",
                  file=sys.stderr)
    return engine


def main() -> int:
    spec = json.loads(os.environ.get("PTD_REPLICA_SPEC", "{}"))
    rank = int(os.environ.get("RANK", "0"))

    from pytorchdistributed_tpu.faults.inject import FaultInjector
    from pytorchdistributed_tpu.runtime.heartbeat import Heartbeat

    engine = _build_engine(spec)
    heartbeat = Heartbeat.from_env()
    injector = FaultInjector.from_env()
    delivered: list[list[int]] = []
    finished: list[list] = []
    reqs: dict[int, object] = {}

    def on_token(req, tok):
        delivered.append([req.router_rid, int(tok)])

    def sweep_finished() -> None:
        for rid, req in list(reqs.items()):
            if req.done:
                finished.append([rid, req.finish_reason])
                del reqs[rid]

    def reply(**payload) -> None:
        sys.stdout.write(json.dumps(payload) + "\n")
        sys.stdout.flush()

    # SIGTERM must work while BLOCKED in the stdin read (the idle
    # worker's steady state — PEP 475 would otherwise retry the read
    # after a flag-setting handler and the drain would wait for the
    # next op that never comes): raise out of the read and let the
    # finally-drain run. Raising between ops is safe — the engine is
    # only ever mutated inside a fully-completed op handler.
    def _sigterm(signum, frame):
        raise SystemExit(0)

    signal.signal(signal.SIGTERM, _sigterm)
    closed = [False]

    def shutdown() -> None:
        if not closed[0]:
            closed[0] = True
            engine.drain()
            engine.close()
            if engine.trace is not None:
                engine.trace.close()

    try:
        return _serve(engine, heartbeat, injector, rank, delivered,
                      finished, reqs, on_token, sweep_finished, reply,
                      shutdown)
    finally:
        # every exit path — close op, stdin EOF, SIGTERM — drains the
        # engine (pool-leak invariant asserted) exactly once
        shutdown()


def _serve(engine, heartbeat, injector, rank, delivered, finished, reqs,
           on_token, sweep_finished, reply, shutdown) -> int:
    tick = 0
    slow_ms = 0.0   # injected straggler latency, paid on the next step
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        op = json.loads(line)
        kind = op.get("op")
        if kind == "warmup":
            engine.warmup(prompt_lens=op.get("prompt_lens") or None)
            if op.get("kv_stream"):
                # compile the KV gather/scatter pair now so the first
                # real handoff/prefix ship is dispatch-only
                engine.warmup_kv_stream()
            # report the real context bound so the router can validate
            # submits against it instead of trusting the spec — and a
            # first health snapshot, so role decisions (block_size,
            # free_slots) don't wait for the first step reply
            reply(ok=True, max_seq_len=engine.cfg.max_seq_len,
                  health=engine.health())
        elif kind == "submit":
            s = op.get("sampling", {})
            from pytorchdistributed_tpu.serving.engine import (
                SamplingParams,
            )
            try:
                req = engine.submit(
                    op["prompt"], max_new_tokens=op["max_new_tokens"],
                    sampling=SamplingParams(
                        temperature=float(s.get("temperature", 0.0)),
                        top_k=int(s.get("top_k", 0)),
                        top_p=float(s.get("top_p", 1.0)),
                        seed=int(s.get("seed", 0))),
                    stop_ids=tuple(op.get("stop_ids") or ()),
                    deadline_s=op.get("deadline_s"),
                    generated=op.get("generated") or None,
                    on_token=on_token,
                    prefill_only=bool(op.get("prefill_only")),
                    kv_window=op.get("kv_window"),
                    kv_sink=op.get("kv_sink"),
                    session_id=op.get("session_id"),
                    tenant=op.get("tenant", "default"),
                    trace=op.get("trace"),
                    origin_t=op.get("origin_t"))
            except ValueError as e:
                # a malformed request must cost ONE refusal, not the
                # worker process (and then, replica by replica, the
                # fleet as the router redispatches it)
                reply(ok=False, rid=op["rid"], error=str(e))
                continue
            req.router_rid = op["rid"]
            reqs[op["rid"]] = req
            reply(ok=True, rid=op["rid"])
        elif kind == "step":
            tick += 1
            if injector is not None:
                fault = injector.on_serving_tick(tick, rank)
                if fault == "replica_crash":
                    from pytorchdistributed_tpu.faults.inject import (
                        CRASH_EXIT_CODE,
                    )

                    sys.stdout.flush()
                    os._exit(CRASH_EXIT_CODE)
                elif fault == "replica_hang":
                    os.kill(os.getpid(), signal.SIGSTOP)
                elif fault == "replica_nan":
                    from pytorchdistributed_tpu.serving.engine import (
                        nan_params,
                    )

                    engine.set_params(nan_params(engine._weights))
                elif fault == "replica_slow":
                    spec = getattr(injector, "last_fired", None)
                    slow_ms += spec.ms if spec is not None else 100.0
            if slow_ms > 0:
                # a straggler, not a hang: the step still completes and
                # the progress watermark advances — just late
                import time as _time

                _time.sleep(slow_ms / 1e3)
                slow_ms = 0.0
            engine.step()
            sweep_finished()
            if heartbeat is not None:
                heartbeat.beat()  # after the engine's host sync
            step_reply = dict(
                ok=True, delivered=list(delivered),
                finished=list(finished), health=engine.health(),
                parked=[r.router_rid for r in engine.parked_requests
                        if hasattr(r, "router_rid")])
            demoted = _drain_demoted_sessions(engine)
            if demoted:
                step_reply["demoted_sessions"] = demoted
            reply(**step_reply)
            # clear IN PLACE: on_token/sweep_finished close over these
            delivered.clear()
            finished.clear()
        elif kind == "export_kv":
            from pytorchdistributed_tpu.serving.engine import (
                kv_payload_to_wire,
            )

            req = reqs.get(op["rid"])
            if req is None:
                reply(ok=False, error=f"unknown rid {op['rid']}")
                continue
            try:
                payload = engine.export_kv_blocks(req)
            except ValueError as e:
                reply(ok=False, error=str(e))
                continue
            del reqs[op["rid"]]  # the stream now lives in the payload
            reply(ok=True, rid=op["rid"],
                  payload=kv_payload_to_wire(payload))
        elif kind == "import_kv":
            from pytorchdistributed_tpu.serving.engine import (
                kv_payload_from_wire,
            )

            try:
                req = engine.import_kv_blocks(
                    kv_payload_from_wire(op["payload"]),
                    on_token=on_token, deadline_s=op.get("deadline_s"))
            except ValueError as e:
                reply(ok=False, error=str(e))
                continue
            if req is None:   # pool pressure: refuse, router requeues
                reply(ok=False, error="no free slot/blocks")
                continue
            req.router_rid = op["rid"]
            reqs[op["rid"]] = req
            reply(ok=True, rid=op["rid"])
        elif kind == "export_prefix":
            import numpy as np

            from pytorchdistributed_tpu.serving.engine import (
                prefix_payload_to_wire,
            )

            payload = engine.export_prefix_blocks(
                np.asarray(op["tokens"], np.int32))
            if payload is None:
                reply(ok=False)
            else:
                reply(ok=True, payload=prefix_payload_to_wire(payload))
        elif kind == "import_prefix":
            from pytorchdistributed_tpu.serving.engine import (
                prefix_payload_from_wire,
            )

            adopted = engine.import_prefix_blocks(
                prefix_payload_from_wire(op["payload"]))
            reply(ok=True, adopted=int(adopted))
        elif kind == "preempt":
            # admission-side preemption (ISSUE 15): evict the stream
            # losslessly — its tokens flow back as a "preempted" finish
            # through the next step reply and the router requeues it
            req = reqs.get(op["rid"])
            ok = req is not None and engine.preempt_request(req)
            if ok:
                finished.append([op["rid"], "preempted"])
                del reqs[op["rid"]]
            reply(ok=bool(ok), rid=op["rid"])
        elif kind == "set_draft_params":
            # fleet draft hot-swap (ISSUE 16): checkpoint-path payload,
            # restored locally and verified by the engine's structure/
            # shape check; in-flight spec streams keep their K/V and
            # stay token-for-token identical (lossless under any draft)
            try:
                params, step = _restore_draft_params(
                    op["checkpoint"], op.get("step"))
                engine.set_draft_params(params)
            except Exception as e:  # noqa: BLE001 — refusal, not death
                reply(ok=False, error=f"{type(e).__name__}: {e}"[:300])
                continue
            reply(ok=True, step=step,
                  draft_hash=engine.draft_params_hash(),
                  draft_swaps=engine.draft_swaps)
        elif kind == "probe":
            reply(finite=engine.check_params_finite())
        elif kind == "inject":
            # router-side rate-based chaos (ISSUE 19): the ChaosSchedule
            # lives in the ROUTER process (one seed, one decision
            # stream), so nan/slow verdicts arrive as a wire op the
            # worker applies to its own engine. crash/hang never ride
            # this path — the router kills/SIGSTOPs the process itself.
            what = op.get("kind")
            if what == "replica_nan":
                from pytorchdistributed_tpu.serving.engine import (
                    nan_params,
                )

                engine.set_params(nan_params(engine._weights))
            elif what == "replica_slow":
                slow_ms += float(op.get("ms", 100.0))
            reply(ok=True, kind=what)
        elif kind == "export_session":
            # persistent sessions (ISSUE 18): hand a RESIDENT parked
            # session's KV over the wire (cross-replica reattach pull)
            from pytorchdistributed_tpu.serving.engine import (
                kv_payload_to_wire,
            )

            payload = engine.export_session(op["session_id"])
            if payload is None:
                reply(ok=False, error="no such resident session")
            else:
                reply(ok=True, payload=kv_payload_to_wire(payload))
        elif kind == "seed_session":
            from pytorchdistributed_tpu.serving.engine import (
                kv_payload_from_wire,
            )

            seeded = engine.seed_session_blocks(
                kv_payload_from_wire(op["payload"]), remote=True)
            reply(ok=True, seeded=int(seeded))
        elif kind == "drain":
            engine.drain()
            sweep_finished()
            drain_reply = dict(ok=True, finished=list(finished))
            demoted = _drain_demoted_sessions(engine)
            if demoted:
                # the drain demoted every resident session — the router
                # persists them (clean drain) or discards (quarantine)
                drain_reply["demoted_sessions"] = demoted
            reply(**drain_reply)
            finished.clear()
        elif kind == "close":
            shutdown()  # drain + close exactly once (finally is a noop)
            sweep_finished()
            reply(ok=True, finished=finished)
            return 0
        else:
            reply(ok=False, error=f"unknown op {kind!r}")
    # stdin EOF: the router died — the caller's finally drains and
    # closes, so the worker never lingers as an orphan
    return 0


if __name__ == "__main__":
    sys.exit(main())
