"""Continuous-batching serving (the first layer above SURVEY.md's L6):

  * engine.py    — ServingEngine: fixed-slot KV cache + one compiled
                   decode tick + bucketed prefill-into-slot, with a
                   host-side admission/retirement scheduler and
                   per-request token streaming
  * telemetry.py — ServingTelemetry: TTFT / tokens-per-s / queue depth /
                   slot occupancy as spans + metric JSONL through the
                   existing telemetry/ package

`bench.py --mode serve` drives it under a Poisson arrival trace;
examples/serve.py is the train-then-serve demo.
"""

from pytorchdistributed_tpu.serving.engine import (  # noqa: F401
    Request,
    SamplingParams,
    ServingEngine,
    decode_tick,
    prefill_into_slot,
    slot_models,
)
from pytorchdistributed_tpu.serving.telemetry import (  # noqa: F401
    SERVE_METRICS_FILE,
    SERVE_METRICS_GLOB,
    ServingTelemetry,
)
