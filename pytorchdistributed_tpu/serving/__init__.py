"""Continuous-batching serving (the first layer above SURVEY.md's L6):

  * engine.py    — ServingEngine: fixed-slot KV cache + one compiled
                   decode tick + bucketed prefill-into-slot, with a
                   host-side admission/retirement scheduler and
                   per-request token streaming. ``block_size > 0``
                   switches to the PAGED engine (ISSUE 7): block-table
                   KV pool, radix prefix reuse, chunked prefill,
                   preempt-requeue. ``spec_k > 0`` adds SPECULATIVE
                   decoding (ISSUE 8): a draft model proposes k tokens
                   per slot, verified losslessly in one target forward
                   per tick (spec_decode_tick)
  * paging.py    — BlockAllocator (refcounted pool free-list, trash
                   block, leak invariant) + RadixPrefixCache
                   (block-granularity prefix trie, LRU eviction)
  * telemetry.py — ServingTelemetry: TTFT / tokens-per-s / queue depth /
                   slot occupancy / prefix-cache + block-pool metrics as
                   spans + metric JSONL through the existing telemetry/
                   package; RouterTelemetry: the router's per-replica /
                   event / summary JSONL stream
  * router.py    — ReplicaRouter (ISSUE 9): health-checked router over
                   N engine replicas (in-process or run.py-env-contract
                   subprocess workers) with lossless mid-stream
                   failover, load shedding, quarantine/rejoin and
                   graceful SIGTERM drain; replica_worker.py is the
                   subprocess side. ``roles=`` (ISSUE 12) splits the
                   fleet into prefill/decode resource classes — parked
                   prefills hand off KV blocks over the wire — and a
                   FleetPrefixIndex steers shared prefixes to the
                   replica that already holds them (or ships the
                   blocks), so a hot prefix is prefilled once per fleet
  * admission.py — AdmissionController (ISSUE 15): multi-tenant
                   admission — per-tenant queues under a priority-
                   tiered weighted-deficit-round-robin token scheduler,
                   per-tenant rate/queue caps, weighted shedding that
                   never touches a compliant tenant
  * autoscale.py — Autoscaler + SLOConfig (ISSUE 15): the control loop
                   that turns sustained SLO breaches in the router's
                   signal rings into warm add_replica / graceful
                   remove_replica, with hysteresis, cooldowns and
                   independent prefill/decode pool scaling
  * traffic.py   — seeded trace generators (steady/diurnal/flash,
                   heavy-tail lengths, shared-prefix tenant mixes,
                   multi-turn conversations with think-time gaps) and
                   the fake-clock replay()/replay_conversations()
                   drivers the bench and the quick test tier share
  * soak.py      — chaos soak (ISSUE 19): InvariantChecker (continuous
                   no-orphans / fairness / SLO-debt / zero-recompile /
                   all-streams-terminal assertions over a live fleet)
                   and run_soak(), which rides a seeded diurnal trace
                   with the autoscaler live and a faults.ChaosSchedule
                   firing rate-based replica + wire faults
  * sessions.py  — SessionStore (ISSUE 18): the host-DRAM + disk tiers
                   of the persistent-session KV hierarchy (manifest-
                   verified disk sessions, quarantine-on-corruption,
                   per-tenant caps, offline ls/verify/gc CLI); engines
                   park finished session streams in HBM, the router's
                   FleetSessionIndex steers reattaching turns to the
                   owner or pulls/seeds the payload over the wire

`bench.py --mode serve` drives it under a Poisson arrival trace (plus
the paged capacity, prefix-reuse and autoscale A/Bs); examples/serve.py
is the train-then-serve demo.
"""

from pytorchdistributed_tpu.serving.admission import (  # noqa: F401
    DEFAULT_TENANT,
    AdmissionController,
    TenantConfig,
)
from pytorchdistributed_tpu.serving.autoscale import (  # noqa: F401
    Autoscaler,
    SLOConfig,
)

from pytorchdistributed_tpu.serving.engine import (  # noqa: F401
    KVBlockPayload,
    PrefixBlockPayload,
    Request,
    SamplingParams,
    ServingEngine,
    decode_tick,
    kv_payload_from_wire,
    kv_payload_to_wire,
    paged_decode_tick,
    paged_prefill_chunk,
    paged_slot_models,
    prefill_into_slot,
    slot_models,
    spec_decode_tick,
)
from pytorchdistributed_tpu.serving.paging import (  # noqa: F401
    BlockAllocator,
    FleetPrefixIndex,
    FleetSessionIndex,
    RadixPrefixCache,
    block_hashes,
)
from pytorchdistributed_tpu.serving.router import (  # noqa: F401
    DEAD,
    DRAINING,
    HEALTHY,
    QUARANTINED,
    REMOVED,
    ROLE_BOTH,
    ROLE_DECODE,
    ROLE_PREFILL,
    ROLES,
    InProcessReplica,
    ReplicaCrashed,
    ReplicaRouter,
    RouterRequest,
    SubprocessReplica,
    WireFault,
)
from pytorchdistributed_tpu.serving.soak import (  # noqa: F401
    InvariantChecker,
    run_soak,
)
from pytorchdistributed_tpu.serving.telemetry import (  # noqa: F401
    ROUTER_METRICS_FILE,
    ROUTER_METRICS_GLOB,
    SERVE_METRICS_FILE,
    SERVE_METRICS_GLOB,
    RouterTelemetry,
    ServingTelemetry,
    SignalRing,
)
from pytorchdistributed_tpu.serving.sessions import (  # noqa: F401
    SessionStore,
    session_id_ok,
)
from pytorchdistributed_tpu.serving.traffic import (  # noqa: F401
    Conversation,
    ConversationTurn,
    FakeClock,
    TenantTraffic,
    TrafficRequest,
    WallClock,
    make_conversations,
    make_trace,
    replay,
    replay_conversations,
)
