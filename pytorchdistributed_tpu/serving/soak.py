"""Chaos soak (ISSUE 19): continuously-checked invariants over a live,
fault-riddled fleet.

``InvariantChecker`` rides ``traffic.replay()``'s ``on_tick`` hook and
watches the router the whole run — not a post-mortem: a violation is
stamped the tick it happens, with the tick and clock time attached.
The invariants are the serving layer's whole contract, restated as
runtime assertions:

  * **no orphan processes** — every worker PID ever seen is gone after
    ``router.close()`` (the torchrun elastic-agent contract: an agent
    that loses a worker tears down the rest, never leaks one);
  * **no compliant-tenant sheds** — a tenant inside its admission caps
    never pays for overload or for other tenants' bursts, even while
    replicas are being crashed/hung/corrupted under it;
  * **bounded per-tenant SLO debt** — queue-time debt per tenant stays
    under a budget (the autoscaler + failover are actually absorbing
    the faults, not just surviving them);
  * **zero fresh XLA traces on survivors** — a replica that stayed
    HEALTHY never recompiles mid-soak (``trace_count`` from the health
    snapshot is flat between quarantine episodes);
  * **every admitted stream terminal** — each submitted handle ends
    ``done`` with a finish reason; nothing is silently dropped;
  * **clean retire** — ``router.close()`` completes without raising
    (the paged engines' block-pool leak assertion lives inside it).

``run_soak()`` is the driver both ``bench.py --mode soak`` and the
quick-tier mini-soak share: replay a seeded (usually diurnal) trace
over a router whose ``faults=`` is a ``ChaosSchedule``, autoscaler
live, checker attached; it returns one report dict with the finish
accounting, SLO attainment, the per-fault-class recovery table
(injected → detected → recovered, MTTR percentiles) and the invariant
verdicts.
"""

from __future__ import annotations

import collections
import os
import time

from pytorchdistributed_tpu.faults.chaos import recovery_table
from pytorchdistributed_tpu.serving.router import HEALTHY
from pytorchdistributed_tpu.serving.traffic import replay

__all__ = ["InvariantChecker", "run_soak"]


def _percentile(values, q: float):
    if not values:
        return None
    vals = sorted(values)
    idx = min(len(vals) - 1, max(0, round(q * (len(vals) - 1))))
    return vals[idx]


class InvariantChecker:
    """Continuous invariant assertions over a running fleet.

    Attach via ``replay(..., on_tick=checker.on_tick)``; call
    ``finalize(handles)`` AFTER ``router.close()``. Violations
    accumulate in ``self.violations`` (each a dict with ``invariant``,
    the tick, and the evidence); ``strict=True`` makes ``finalize``
    raise AssertionError if any were recorded.

    The checker also taps the router's telemetry event stream into
    ``self.events`` — unbounded, unlike the telemetry ring — which is
    what feeds ``faults.recovery_table`` for MTTR attribution.
    """

    def __init__(self, router, *, compliant=(), debt_budget_s=None,
                 strict=True, check_every=25):
        self.router = router
        self.compliant = tuple(compliant)
        self.debt_budget_s = debt_budget_s
        self.strict = bool(strict)
        self.check_every = max(1, int(check_every))
        self.violations: list[dict] = []
        self.events: list[dict] = []
        self.checks = 0
        self._tick = -1
        self._pids: set[int] = set()
        self._shed_by_tenant: collections.Counter = collections.Counter()
        #: (replica index, process generation) -> trace_count baseline,
        #: dropped whenever the replica is seen non-HEALTHY so rejoin /
        #: respawn re-baselines instead of flagging recovery warmup
        self._trace_base: dict[tuple, int] = {}
        self._debt_flagged: set[str] = set()
        self._tap_events()

    # -- wiring --------------------------------------------------------

    def _tap_events(self) -> None:
        orig = self.router.telemetry.event

        def tap(event, **row):
            self.events.append(
                {"event": event, "time": time.time(), **row})
            if event == "shed":
                tenant = row.get("tenant")
                self._shed_by_tenant[tenant] += 1
                if tenant in self.compliant:
                    self._violate("compliant_tenant_shed",
                                  tenant=tenant,
                                  request=row.get("request"))
            orig(event, **row)

        self.router.telemetry.event = tap

    def _violate(self, invariant: str, **evidence) -> None:
        self.violations.append(
            dict(invariant=invariant, tick=self._tick, **evidence))

    # -- the per-tick sweep --------------------------------------------

    def on_tick(self, ticks: int, clock) -> None:
        self._tick = ticks
        # PID collection is every tick: a replica can be born and die
        # between two sweeps and its process must still be accounted for
        for r in self.router._replicas:
            proc = getattr(r, "proc", None)
            if proc is not None:
                self._pids.add(proc.pid)
        if ticks % self.check_every:
            return
        self.checks += 1
        self._check_traces()
        self._check_debt()

    def _check_traces(self) -> None:
        for r, h in zip(self.router._replicas, self.router.health()):
            count = h.get("trace_count")
            if count is None:
                continue
            gen = getattr(getattr(r, "proc", None), "pid", None) or id(r)
            key = (h["replica"], gen)
            if h.get("status") != HEALTHY:
                self._trace_base.pop(key, None)
                continue
            base = self._trace_base.setdefault(key, int(count))
            if count > base:
                self._violate("fresh_trace_on_survivor",
                              replica=h["replica"], baseline=base,
                              trace_count=int(count))
                self._trace_base[key] = int(count)  # flag once per jump

    def _check_debt(self) -> None:
        tracer = self.router.trace
        if tracer is None or self.debt_budget_s is None:
            return
        for tenant, rec in getattr(tracer, "slo_debt", {}).items():
            debt = float(rec.get("debt_s", 0.0))
            if debt > self.debt_budget_s and tenant not in self._debt_flagged:
                self._debt_flagged.add(tenant)
                self._violate("slo_debt_exceeded", tenant=tenant,
                              debt_s=round(debt, 4),
                              budget_s=self.debt_budget_s)

    # -- post-close ----------------------------------------------------

    def finalize(self, handles=None) -> dict:
        """Run AFTER ``router.close()``: the terminal-streams check and
        the orphan sweep. Returns the invariant report; raises
        AssertionError on any violation when ``strict``."""
        if handles is not None:
            stuck = [rr.id for rr in handles
                     if rr is not None and not rr.done]
            if stuck:
                self._violate("non_terminal_streams", count=len(stuck),
                              sample=stuck[:5])
        orphans = []
        for pid in sorted(self._pids):
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                continue
            except PermissionError:
                pass  # alive, just not ours to signal
            orphans.append(pid)
        if orphans:
            self._violate("orphan_processes", pids=orphans)
        report = dict(
            ok=not self.violations,
            checks=self.checks,
            violations=list(self.violations),
            pids_seen=len(self._pids),
            shed_by_tenant=dict(self._shed_by_tenant),
        )
        if self.strict and self.violations:
            raise AssertionError(
                f"soak invariants violated: {self.violations}")
        return report


def run_soak(router, trace, *, clock=None, tick_s: float = 0.02,
             autoscaler=None, compliant=(), debt_budget_s=None,
             strict: bool = True, check_every: int = 25,
             submit_kwargs: dict | None = None,
             max_ticks: int = 500_000) -> dict:
    """Drive ``router`` through ``trace`` under chaos and return the
    soak report. The router should have been built with
    ``faults=ChaosSchedule(...)`` (or a ``PTD_FAULTS`` spec carrying
    rate/period/wire kinds — the router auto-wraps those); pass the
    live ``autoscaler`` to exercise scaling under faults.

    Closes the router before returning. ``strict=False`` records
    violations in the report instead of raising — the bench uses that
    to stamp a failed soak rather than die mid-measurement."""
    checker = InvariantChecker(
        router, compliant=compliant, debt_budget_s=debt_budget_s,
        strict=strict, check_every=check_every)
    t0 = time.perf_counter()
    handles = replay(router, trace, clock=clock, tick_s=tick_s,
                     autoscaler=autoscaler, on_tick=checker.on_tick,
                     submit_kwargs=submit_kwargs, max_ticks=max_ticks)
    wall_s = time.perf_counter() - t0
    summary = router.summary()
    chaos = router._faults
    injected = list(getattr(chaos, "injected", ()))
    try:
        router.close()
    except Exception as e:  # noqa: BLE001 — a leak assertion IS a finding
        checker._violate("close_failed", error=f"{type(e).__name__}: {e}")
    invariants = checker.finalize(handles)

    reasons = collections.Counter(
        rr.finish_reason for rr in handles if rr is not None)
    ok_reasons = {"stop", "length"}
    finished = sum(n for r, n in reasons.items() if r in ok_reasons)
    admitted = len(handles) - reasons.get("shed", 0)
    ttfts = sorted(rr.ttft_s for rr in handles
                   if rr is not None and rr.ttft_s is not None)
    report = dict(
        requests=len(handles),
        admitted=admitted,
        finish_reasons=dict(reasons),
        slo_attainment=round(finished / admitted, 4) if admitted else None,
        ttft_p50_s=_percentile(ttfts, 0.50),
        ttft_p95_s=_percentile(ttfts, 0.95),
        wall_s=round(wall_s, 3),
        faults_injected=len(injected),
        injected_by_kind=dict(collections.Counter(
            row.get("kind") for row in injected)),
        recovery=recovery_table(checker.events),
        router=summary,
        invariants=invariants,
    )
    if autoscaler is not None and hasattr(autoscaler, "summary"):
        report["autoscaler"] = autoscaler.summary()
    return report
