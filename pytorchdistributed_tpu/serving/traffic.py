"""Traffic-replay harness (ISSUE 15): seeded generators for realistic
million-user arrival shapes, and a fake-clock replay driver.

The generators are PURE HOST + numpy — no jax, no wall clock, no global
state — so the same seed always produces the identical trace
(tests/test_autoscale.py pins that tripwire). Three shapes cover the
capacity-planning stories the autoscaler must survive:

  * ``steady``  — homogeneous Poisson at ``base_qps``;
  * ``diurnal`` — a sinusoidal ramp peaking at ``base_qps * peak_mult``
    mid-trace (the day/night cycle, compressed to ``duration_s``);
  * ``flash``   — ``base_qps`` background with a ``peak_mult`` flash
    crowd inside ``[flash_at_s, flash_at_s + flash_len_s)`` — the
    scale-up reaction-time story.

Non-homogeneous arrivals use Poisson thinning at the peak rate, so
every shape is exact (not binned). Request lengths are heavy-tailed
(lognormal, clipped to the pool), and each tenant can open with a
shared prefix — the radix/fleet prefix cache's hot-prompt shape.

``replay()`` drives a ReplicaRouter (or anything with submit/step)
through a trace against a FakeClock: arrivals are released when the
fake clock passes them, one router step per tick, optionally stepping
an Autoscaler — zero wall-clock sleeps, so the quick test tier and the
bench share one driver.
"""

from __future__ import annotations

import dataclasses
import math
import time
import zlib

import numpy as np

__all__ = [
    "Conversation",
    "ConversationTurn",
    "FakeClock",
    "TenantTraffic",
    "TrafficRequest",
    "WallClock",
    "make_conversations",
    "make_trace",
    "replay",
    "replay_conversations",
]


class FakeClock:
    """A monotonic clock you advance by hand — inject it wherever a
    component takes ``clock=`` (AdmissionController rate buckets,
    Autoscaler cooldowns, replay pacing) to make time a test input."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)

    def now(self) -> float:
        return self._now

    __call__ = now

    def advance(self, dt: float) -> None:
        if dt < 0:
            raise ValueError(f"clocks only run forward, got dt={dt}")
        self._now += float(dt)


class WallClock:
    """FakeClock's real-time twin for subprocess soaks: ``now()`` is
    seconds since construction, ``advance(dt)`` sleeps just enough to
    hold the replay cadence (no sleep at all when the fleet is already
    behind schedule — a slow tick eats its own budget)."""

    def __init__(self):
        self._t0 = time.perf_counter()
        self._target = 0.0

    def now(self) -> float:
        return time.perf_counter() - self._t0

    __call__ = now

    def advance(self, dt: float) -> None:
        if dt < 0:
            raise ValueError(f"clocks only run forward, got dt={dt}")
        self._target += float(dt)
        lag = self._target - self.now()
        if lag > 0:
            time.sleep(lag)


@dataclasses.dataclass(frozen=True)
class TenantTraffic:
    """One tenant's slice of a generated trace: ``share`` of arrivals
    (normalized over the mix), the priority class its requests carry
    (0 = highest), and the shared-prefix shape — with probability
    ``prefix_frac`` a request opens with the tenant's own
    ``prefix_len`` fixed tokens (deterministic per (seed, name)), the
    hot-prompt pattern prefix caching feeds on."""

    name: str
    share: float = 1.0
    priority: int = 0
    prefix_len: int = 0
    prefix_frac: float = 0.0


@dataclasses.dataclass(frozen=True, eq=False)
class TrafficRequest:
    """One generated arrival (host-side only)."""

    at_s: float
    tenant: str
    priority: int
    prompt: np.ndarray        # int32 [prompt_len]
    max_new_tokens: int


def _lognormal_len(rng, mean: float, sigma: float, lo: int, hi: int) -> int:
    """Heavy-tail length draw with the given (linear-space) mean."""
    mu = math.log(max(mean, 1.0)) - sigma * sigma / 2.0
    return int(np.clip(round(rng.lognormal(mu, sigma)), lo, hi))


def make_trace(*, seed: int, duration_s: float, base_qps: float,
               shape: str = "steady", peak_mult: float = 4.0,
               flash_at_s: float | None = None,
               flash_len_s: float | None = None,
               tenants: tuple[TenantTraffic, ...] | None = None,
               vocab_size: int = 64, prompt_mean: float = 8.0,
               prompt_sigma: float = 0.6, prompt_cap: int = 32,
               new_mean: float = 8.0, new_sigma: float = 0.5,
               new_cap: int = 16) -> list[TrafficRequest]:
    """Generate one deterministic arrival trace, sorted by ``at_s``.

    Same arguments -> byte-identical trace (prompts included): the only
    entropy source is ``np.random.default_rng(seed)`` plus a per-tenant
    crc32-derived stream for shared prefixes.
    """
    if shape not in ("steady", "diurnal", "flash"):
        raise ValueError(f"unknown traffic shape {shape!r}; one of "
                         f"('steady', 'diurnal', 'flash')")
    if base_qps <= 0 or duration_s <= 0:
        raise ValueError("base_qps and duration_s must be > 0")
    tenants = tenants or (TenantTraffic("default"),)
    total_share = sum(t.share for t in tenants)
    if total_share <= 0:
        raise ValueError("tenant shares must sum > 0")
    cum = np.cumsum([t.share / total_share for t in tenants])
    # deterministic per-tenant shared prefixes: keyed on (seed, name)
    # so two tenants never collide and a re-run reproduces them
    prefixes = {
        t.name: np.random.default_rng(
            (seed, zlib.crc32(t.name.encode()))
        ).integers(1, vocab_size, (t.prefix_len,)).astype(np.int32)
        for t in tenants if t.prefix_len > 0
    }

    if shape == "flash":
        flash_at_s = duration_s / 3.0 if flash_at_s is None else flash_at_s
        flash_len_s = (duration_s / 6.0 if flash_len_s is None
                       else flash_len_s)

    def rate(t: float) -> float:
        if shape == "steady":
            return base_qps
        if shape == "diurnal":
            return base_qps * (1.0 + (peak_mult - 1.0) * 0.5
                               * (1.0 - math.cos(2 * math.pi
                                                 * t / duration_s)))
        return base_qps * (peak_mult
                           if flash_at_s <= t < flash_at_s + flash_len_s
                           else 1.0)

    lam_max = base_qps if shape == "steady" else base_qps * peak_mult
    rng = np.random.default_rng(seed)
    out: list[TrafficRequest] = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / lam_max))
        if t >= duration_s:
            break
        if rng.random() >= rate(t) / lam_max:  # thinning rejection
            continue
        ti = int(np.searchsorted(cum, rng.random(), side="right"))
        ten = tenants[min(ti, len(tenants) - 1)]
        plen = _lognormal_len(rng, prompt_mean, prompt_sigma, 1, prompt_cap)
        prompt = rng.integers(1, vocab_size, (plen,)).astype(np.int32)
        if ten.prefix_len and rng.random() < ten.prefix_frac:
            pre = prefixes[ten.name]
            keep = max(1, plen - pre.size)
            prompt = np.concatenate([pre, prompt[:keep]])[:prompt_cap]
        out.append(TrafficRequest(
            at_s=round(t, 6), tenant=ten.name, priority=ten.priority,
            prompt=prompt,
            max_new_tokens=_lognormal_len(rng, new_mean, new_sigma, 1,
                                          new_cap)))
    return out


@dataclasses.dataclass(frozen=True, eq=False)
class ConversationTurn:
    """One user turn of a multi-turn conversation: only the NEW user
    tokens — the replay driver concatenates the session's full history
    (earlier prompts + model replies) in front, which is exactly what
    a stateful chat client resubmits. ``think_gap_s`` is the seeded
    think time between the previous turn's last token and this turn's
    arrival (0.0 on the opening turn — the open time lives on the
    Conversation)."""

    user_tokens: np.ndarray   # int32 [len] — this turn's NEW tokens
    max_new_tokens: int
    think_gap_s: float


@dataclasses.dataclass(frozen=True, eq=False)
class Conversation:
    """One generated multi-turn session: opens at ``open_at_s``, then
    each turn follows the previous turn's completion by its think gap.
    ``session_id`` is stable across turns — the persistent-session
    reattach key."""

    session_id: str
    tenant: str
    priority: int
    open_at_s: float
    turns: tuple[ConversationTurn, ...]


def make_conversations(*, seed: int, duration_s: float,
                       session_rate: float,
                       tenants: tuple[TenantTraffic, ...] | None = None,
                       turns_mean: float = 3.0, turns_sigma: float = 0.5,
                       turns_cap: int = 8,
                       think_mean_s: float = 1.0,
                       vocab_size: int = 64,
                       turn_mean: float = 6.0, turn_sigma: float = 0.5,
                       turn_cap: int = 16,
                       new_mean: float = 6.0, new_sigma: float = 0.5,
                       new_cap: int = 12) -> list[Conversation]:
    """Generate a deterministic multi-turn conversation mix, sorted by
    ``open_at_s`` (ISSUE 18's traffic shape).

    Session OPENS are Poisson at ``session_rate``; each session draws
    a lognormal turn count (clipped to [1, turns_cap]), exponential
    think-time gaps with mean ``think_mean_s`` between turns, and
    heavy-tailed per-turn user/new token lengths. Tenants come from the
    same ``TenantTraffic`` mix as :func:`make_trace` — a tenant with
    ``prefix_len``/``prefix_frac`` opens its sessions with the shared
    tenant prompt (the system-prompt shape prefix caching feeds on).
    ``session_id`` is ``f"{tenant}-s{k}"`` with k the global open order
    — same seed, same ids, same tokens."""
    if session_rate <= 0 or duration_s <= 0:
        raise ValueError("session_rate and duration_s must be > 0")
    tenants = tenants or (TenantTraffic("default"),)
    total_share = sum(t.share for t in tenants)
    if total_share <= 0:
        raise ValueError("tenant shares must sum > 0")
    cum = np.cumsum([t.share / total_share for t in tenants])
    prefixes = {
        t.name: np.random.default_rng(
            (seed, zlib.crc32(t.name.encode()))
        ).integers(1, vocab_size, (t.prefix_len,)).astype(np.int32)
        for t in tenants if t.prefix_len > 0
    }
    rng = np.random.default_rng((seed, 0x5e55))
    out: list[Conversation] = []
    t = 0.0
    k = 0
    while True:
        t += float(rng.exponential(1.0 / session_rate))
        if t >= duration_s:
            break
        ti = int(np.searchsorted(cum, rng.random(), side="right"))
        ten = tenants[min(ti, len(tenants) - 1)]
        n_turns = _lognormal_len(rng, turns_mean, turns_sigma, 1,
                                 turns_cap)
        turns = []
        for j in range(n_turns):
            ulen = _lognormal_len(rng, turn_mean, turn_sigma, 1, turn_cap)
            toks = rng.integers(1, vocab_size, (ulen,)).astype(np.int32)
            if j == 0 and ten.prefix_len \
                    and rng.random() < ten.prefix_frac:
                toks = np.concatenate(
                    [prefixes[ten.name], toks])[:ten.prefix_len + ulen]
            turns.append(ConversationTurn(
                user_tokens=toks,
                max_new_tokens=_lognormal_len(rng, new_mean, new_sigma,
                                              1, new_cap),
                think_gap_s=(0.0 if j == 0 else round(
                    float(rng.exponential(think_mean_s)), 6))))
        out.append(Conversation(
            session_id=f"{ten.name}-s{k}", tenant=ten.name,
            priority=ten.priority, open_at_s=round(t, 6),
            turns=tuple(turns)))
        k += 1
    return out


def replay_conversations(router, convs, *,
                         clock: FakeClock | None = None,
                         tick_s: float = 0.02, autoscaler=None,
                         on_turn=None, max_seq_len: int | None = None,
                         submit_kwargs: dict | None = None,
                         max_ticks: int = 500_000) -> dict[str, list]:
    """Drive ``router`` through a conversation mix against a fake
    clock. A session's turn t submits only after turn t-1 finished AND
    its think gap has elapsed — the stream-close/reattach rhythm the
    session tiers live on. Each submit carries ``session_id=`` and the
    FULL history (prior prompts + delivered replies) as its prompt,
    exactly like a stateful chat client; turns that would overflow
    ``max_seq_len`` end their conversation early. Returns
    {session_id: [turn handles...]} in submit order."""
    clock = clock or FakeClock()
    kwargs = submit_kwargs or {}
    # per-conversation cursor: next turn index, earliest release time,
    # accumulated token history, the in-flight handle (if any)
    state = [{"c": c, "turn": 0, "ready_at": c.open_at_s,
              "history": np.zeros(0, np.int32), "inflight": None}
             for c in sorted(convs, key=lambda c: c.open_at_s)]
    out: dict[str, list] = {c.session_id: [] for c in convs}
    for ticks in range(max_ticks):
        now = clock.now()
        live = False
        for s in state:
            c = s["c"]
            if s["inflight"] is not None:
                rr = s["inflight"]
                if not rr.done:
                    live = True
                    continue
                toks = np.asarray(rr.tokens, np.int32)
                s["history"] = np.concatenate(
                    [rr.prompt, toks]) if rr.finish_reason in (
                        "stop", "length") else s["history"]
                s["inflight"] = None
                s["turn"] += 1
                if (s["turn"] < len(c.turns)
                        and rr.finish_reason in ("stop", "length")):
                    s["ready_at"] = (now
                                     + c.turns[s["turn"]].think_gap_s)
                else:
                    s["turn"] = len(c.turns)  # shed/failed: close early
            if s["turn"] >= len(c.turns) or s["ready_at"] > now:
                live = live or s["turn"] < len(c.turns)
                continue
            turn = c.turns[s["turn"]]
            prompt = np.concatenate([s["history"], turn.user_tokens])
            if (max_seq_len is not None
                    and prompt.size + turn.max_new_tokens > max_seq_len):
                s["turn"] = len(c.turns)  # context exhausted
                continue
            rr = router.submit(prompt,
                               max_new_tokens=turn.max_new_tokens,
                               tenant=c.tenant, priority=c.priority,
                               session_id=c.session_id, **kwargs)
            out[c.session_id].append(rr)
            if on_turn is not None:
                on_turn(c, s["turn"], rr, clock)
            s["inflight"] = rr
            live = True
        router.step()
        if autoscaler is not None:
            autoscaler.step()
        if not live and all(s["turn"] >= len(s["c"].turns)
                            for s in state):
            return out
        clock.advance(tick_s)
    raise RuntimeError(
        f"conversation replay did not drain within {max_ticks} ticks")


def replay(router, trace, *, clock: FakeClock | None = None,
           tick_s: float = 0.02, autoscaler=None, on_tick=None,
           submit_kwargs: dict | None = None,
           max_ticks: int = 500_000) -> list:
    """Drive ``router`` through ``trace`` against a fake clock: release
    every arrival whose ``at_s`` the clock has passed, step the router
    (and the autoscaler, if given) once per tick, advance the clock by
    ``tick_s``, and keep ticking past the last arrival until the router
    drains. Returns the submitted request handles in arrival order —
    shed/failed ones included, exactly as ``router.submit`` returned
    them. No wall-clock sleeps anywhere: replay speed is whatever the
    engines can step."""
    clock = clock or FakeClock()
    kwargs = submit_kwargs or {}
    reqs: list = []
    i = 0
    for ticks in range(max_ticks):
        now = clock.now()
        while i < len(trace) and trace[i].at_s <= now:
            tr = trace[i]
            i += 1
            reqs.append(router.submit(
                tr.prompt, max_new_tokens=tr.max_new_tokens,
                tenant=tr.tenant, priority=tr.priority, **kwargs))
        router.step()
        if autoscaler is not None:
            autoscaler.step()
        if on_tick is not None:
            on_tick(ticks, clock)
        if (i >= len(trace) and not router.queue_depth
                and not router.in_flight):
            return reqs
        clock.advance(tick_s)
    raise RuntimeError(f"replay did not drain within {max_ticks} ticks "
                       f"({len(trace) - i} arrivals unreleased, "
                       f"queue={router.queue_depth}, "
                       f"in_flight={router.in_flight})")
