"""Serving telemetry bridge — the engine's observability half.

Emits through the existing telemetry/ package rather than growing a
parallel stack: host spans (``serve/prefill`` / ``serve/decode_tick``)
go through a SpanTracer and dump to the same ``spans_rank{rank}.trace
.json`` contract the Trainer uses (so `python -m pytorchdistributed_tpu.
telemetry merge-trace <dir>` folds serving and training onto one
timeline), and the serving metrics — per-tick queue depth / slot
occupancy / tick latency, per-request TTFT and decode tokens-per-s —
land as JSONL rows in ``serve_metrics_rank{rank}.jsonl`` via the shared
JsonlWriter (line-buffered append: rows survive a killed server).
"""

from __future__ import annotations

import os
import time

from pytorchdistributed_tpu.telemetry.events import (
    TELEMETRY_DIR_ENV,
    JsonlWriter,
)
from pytorchdistributed_tpu.telemetry.spans import SPAN_TRACE_FILE, SpanTracer

# writer filename / reader glob pair (same contract discipline as
# events.py's EVENTS_FILE/EVENTS_GLOB — rename together)
SERVE_METRICS_FILE = "serve_metrics_rank{rank}.jsonl"
SERVE_METRICS_GLOB = "serve_metrics_rank*.jsonl"

# the replica ROUTER's stream (ISSUE 9): per-replica health/occupancy
# rows, failover/shed/quarantine event rows, and the close-time summary
ROUTER_METRICS_FILE = "router_metrics_rank{rank}.jsonl"
ROUTER_METRICS_GLOB = "router_metrics_rank*.jsonl"


class ServingTelemetry:
    """Span tracer + serving-metric JSONL sink for one engine/rank."""

    def __init__(self, run_dir: str | os.PathLike, rank: int | None = None):
        self.run_dir = str(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.rank = (rank if rank is not None
                     else int(os.environ.get("RANK", "0")))
        self.tracer = SpanTracer(rank=self.rank)
        self.metrics = JsonlWriter(os.path.join(
            self.run_dir, SERVE_METRICS_FILE.format(rank=self.rank)))

    @classmethod
    def from_env(cls) -> "ServingTelemetry | None":
        """Construct from the launcher's PTD_TELEMETRY_DIR contract
        (None when unset) — the same env the Trainer reads."""
        d = os.environ.get(TELEMETRY_DIR_ENV)
        return cls(d) if d else None

    def span(self, name: str):
        return self.tracer.span(name)

    def tick(self, **row) -> None:
        """One decode-tick metric row (queue depth, occupancy, latency)."""
        self.metrics.write({"kind": "tick", "time": round(time.time(), 3),
                            **row})

    def request(self, req) -> None:
        """One completed-request row: TTFT + per-request decode rate,
        plus the paged-engine lifecycle (prefix-cache tokens admitted by
        reference, prefill chunks paid, preempt round-trips — all 0 on
        the dense engine) and the speculative counters (draft proposals
        made / accepted — both 0 when spec is off)."""
        ttft = req.ttft_s
        self.metrics.write({
            "kind": "request", "time": round(time.time(), 3),
            "id": req.id, "prompt_len": int(req.prompt.size),
            "new_tokens": len(req.new_tokens),
            "finish_reason": req.finish_reason,
            "ttft_ms": None if ttft is None else round(ttft * 1e3, 3),
            "decode_tokens_per_s": req.decode_tokens_per_s,
            "prefix_hit_tokens": getattr(req, "prefix_hit_tokens", 0),
            "prefill_chunks": getattr(req, "prefill_chunks", 0),
            "preemptions": getattr(req, "preemptions", 0),
            "draft_tokens": getattr(req, "draft_tokens", 0),
            "accepted_tokens": getattr(req, "accepted_tokens", 0),
            # > 0 when this request RESUMED from tokens (router
            # failover redispatch): the engine re-prefilled this many
            # already-generated tokens and only decoded past them
            "resumed_from": getattr(req, "resumed_from", 0),
        })

    def pool(self, **row) -> None:
        """One paged-pool summary row (engine close/summary time): the
        prefix-cache hit counters + block utilization the report CLI's
        serving table renders."""
        self.metrics.write({"kind": "pool", "time": round(time.time(), 3),
                            **row})

    def close(self) -> None:
        self.tracer.dump(os.path.join(
            self.run_dir, SPAN_TRACE_FILE.format(rank=self.rank)))
        self.metrics.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class RouterTelemetry:
    """The replica router's metric sink (ISSUE 9) — one JSONL stream per
    router under ``router_metrics_rank{rank}.jsonl``, next to the
    per-replica engines' own ``serve_metrics`` files. Three row kinds:

      * ``replica`` — a per-replica health/load sample (status, role,
        active, queued, parked KV handoffs, occupancy, progress
        watermark) at the router's sampling cadence;
      * ``event``   — one lifecycle transition (failover, redispatch,
        shed, quarantine, rejoin, drain) with its router tick: the
        post-mortem trail of WHY streams moved between replicas;
      * ``router``  — the close-time summary (failovers,
        redispatched_requests, shed_requests, quarantines, rejoins,
        per-replica occupancy balance) the report CLI's router table
        renders.
    """

    def __init__(self, run_dir: str | os.PathLike, rank: int | None = None):
        self.run_dir = str(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.rank = (rank if rank is not None
                     else int(os.environ.get("RANK", "0")))
        self.metrics = JsonlWriter(os.path.join(
            self.run_dir, ROUTER_METRICS_FILE.format(rank=self.rank)))

    @classmethod
    def from_env(cls) -> "RouterTelemetry | None":
        d = os.environ.get(TELEMETRY_DIR_ENV)
        return cls(d) if d else None

    def replica(self, **row) -> None:
        self.metrics.write({"kind": "replica",
                            "time": round(time.time(), 3), **row})

    def event(self, event: str, **row) -> None:
        self.metrics.write({"kind": "event", "event": event,
                            "time": round(time.time(), 3), **row})

    def summary(self, **row) -> None:
        self.metrics.write({"kind": "router",
                            "time": round(time.time(), 3), **row})

    def close(self) -> None:
        self.metrics.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
