"""Serving telemetry bridge — the engine's observability half.

Emits through the existing telemetry/ package rather than growing a
parallel stack: host spans (``serve/prefill`` / ``serve/decode_tick``)
go through a SpanTracer and dump to the same ``spans_rank{rank}.trace
.json`` contract the Trainer uses (so `python -m pytorchdistributed_tpu.
telemetry merge-trace <dir>` folds serving and training onto one
timeline), and the serving metrics — per-tick queue depth / slot
occupancy / tick latency, per-request TTFT and decode tokens-per-s —
land as JSONL rows in ``serve_metrics_rank{rank}.jsonl`` via the shared
JsonlWriter (line-buffered append: rows survive a killed server).
"""

from __future__ import annotations

import collections
import os
import time

from pytorchdistributed_tpu.telemetry.events import (
    TELEMETRY_DIR_ENV,
    JsonlWriter,
)
from pytorchdistributed_tpu.telemetry.spans import SPAN_TRACE_FILE, SpanTracer

# writer filename / reader glob pair (same contract discipline as
# events.py's EVENTS_FILE/EVENTS_GLOB — rename together)
SERVE_METRICS_FILE = "serve_metrics_rank{rank}.jsonl"
SERVE_METRICS_GLOB = "serve_metrics_rank*.jsonl"

# the replica ROUTER's stream (ISSUE 9): per-replica health/occupancy
# rows, failover/shed/quarantine event rows, and the close-time summary
ROUTER_METRICS_FILE = "router_metrics_rank{rank}.jsonl"
ROUTER_METRICS_GLOB = "router_metrics_rank*.jsonl"


class ServingTelemetry:
    """Span tracer + serving-metric JSONL sink for one engine/rank."""

    def __init__(self, run_dir: str | os.PathLike, rank: int | None = None):
        self.run_dir = str(run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.rank = (rank if rank is not None
                     else int(os.environ.get("RANK", "0")))
        self.tracer = SpanTracer(rank=self.rank)
        self.metrics = JsonlWriter(os.path.join(
            self.run_dir, SERVE_METRICS_FILE.format(rank=self.rank)))

    @classmethod
    def from_env(cls) -> "ServingTelemetry | None":
        """Construct from the launcher's PTD_TELEMETRY_DIR contract
        (None when unset) — the same env the Trainer reads."""
        d = os.environ.get(TELEMETRY_DIR_ENV)
        return cls(d) if d else None

    def span(self, name: str):
        return self.tracer.span(name)

    def tick(self, **row) -> None:
        """One decode-tick metric row (queue depth, occupancy, latency)."""
        self.metrics.write({"kind": "tick", "time": round(time.time(), 3),
                            **row})

    def request(self, req) -> None:
        """One completed-request row: TTFT + per-request decode rate,
        plus the paged-engine lifecycle (prefix-cache tokens admitted by
        reference, prefill chunks paid, preempt round-trips — all 0 on
        the dense engine) and the speculative counters (draft proposals
        made / accepted — both 0 when spec is off)."""
        ttft = req.ttft_s
        # end-to-end TTFT (ISSUE 17 satellite): measured from the
        # ORIGIN router submit carried across the handoff wire — on a
        # handed-off stream this is the client-visible number, while
        # ``ttft_ms`` stays decode-replica-local so existing BENCH
        # baselines remain comparable
        e2e = getattr(req, "ttft_e2e_s", None)
        if e2e is None:
            e2e = ttft
        self.metrics.write({
            "kind": "request", "time": round(time.time(), 3),
            "id": req.id, "prompt_len": int(req.prompt.size),
            "new_tokens": len(req.new_tokens),
            "finish_reason": req.finish_reason,
            "ttft_ms": None if ttft is None else round(ttft * 1e3, 3),
            "ttft_e2e_ms": None if e2e is None else round(e2e * 1e3, 3),
            "decode_tokens_per_s": req.decode_tokens_per_s,
            "prefix_hit_tokens": getattr(req, "prefix_hit_tokens", 0),
            "prefill_chunks": getattr(req, "prefill_chunks", 0),
            "preemptions": getattr(req, "preemptions", 0),
            "draft_tokens": getattr(req, "draft_tokens", 0),
            "accepted_tokens": getattr(req, "accepted_tokens", 0),
            # > 0 when this request RESUMED from tokens (router
            # failover redispatch): the engine re-prefilled this many
            # already-generated tokens and only decoded past them
            "resumed_from": getattr(req, "resumed_from", 0),
        })

    def pool(self, **row) -> None:
        """One paged-pool summary row (engine close/summary time): the
        prefix-cache hit counters + block utilization the report CLI's
        serving table renders."""
        self.metrics.write({"kind": "pool", "time": round(time.time(), 3),
                            **row})

    def close(self) -> None:
        self.tracer.dump(os.path.join(
            self.run_dir, SPAN_TRACE_FILE.format(rank=self.rank)))
        self.metrics.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class SignalRing:
    """One bounded time series: an EMA plus the last-N raw samples.
    Pure host state — the autoscaler's decision inputs, so everything
    here must work without a run_dir or a wall clock."""

    def __init__(self, maxlen: int = 256, alpha: float = 0.2):
        self.samples: collections.deque[float] = collections.deque(
            maxlen=maxlen)
        self.alpha = alpha
        self.ema: float | None = None
        self.count = 0

    def push(self, value: float) -> None:
        v = float(value)
        self.samples.append(v)
        self.ema = (v if self.ema is None
                    else (1 - self.alpha) * self.ema + self.alpha * v)
        self.count += 1

    def stats(self, window: int | None = None) -> dict:
        xs = list(self.samples)
        if window is not None:
            xs = xs[-window:]
        if not xs:
            return {"last": None, "ema": None, "n": 0,
                    "sum": 0.0, "mean": None, "max": None}
        return {"last": xs[-1], "ema": self.ema, "n": len(xs),
                "sum": float(sum(xs)), "mean": float(sum(xs) / len(xs)),
                "max": float(max(xs))}


class RouterTelemetry:
    """The replica router's metric sink (ISSUE 9) — one JSONL stream per
    router under ``router_metrics_rank{rank}.jsonl``, next to the
    per-replica engines' own ``serve_metrics`` files. Three row kinds:

      * ``replica`` — a per-replica health/load sample (status, role,
        active, queued, parked KV handoffs, occupancy, progress
        watermark) at the router's sampling cadence;
      * ``event``   — one lifecycle transition (failover, redispatch,
        shed, quarantine, rejoin, drain, scale_up/scale_down) with its
        router tick: the post-mortem trail of WHY streams moved
        between replicas — and WHY the fleet grew or shrank;
      * ``router``  — the close-time summary (failovers,
        redispatched_requests, shed_requests, quarantines, rejoins,
        per-replica occupancy balance, per-tenant table) the report
        CLI's router table renders.

    ISSUE 15 adds the in-memory half the autoscaler consumes: every
    ``signal()`` call lands in a bounded per-signal ring (EMA + last-N
    samples; ``snapshot()`` reads them), and ``run_dir=None``
    constructs a RING-ONLY instance — no directory, no JSONL, just the
    live time series — so a router always has signals to offer even
    when nobody asked for files.
    """

    def __init__(self, run_dir: str | os.PathLike | None = None,
                 rank: int | None = None, *, ring: int = 256,
                 ema_alpha: float = 0.2):
        self.run_dir = None if run_dir is None else str(run_dir)
        self.rank = (rank if rank is not None
                     else int(os.environ.get("RANK", "0")))
        if self.run_dir is None:
            self.metrics = None
        else:
            os.makedirs(self.run_dir, exist_ok=True)
            self.metrics = JsonlWriter(os.path.join(
                self.run_dir, ROUTER_METRICS_FILE.format(rank=self.rank)))
        self._ring_len = ring
        self._ema_alpha = ema_alpha
        self.rings: dict[str, SignalRing] = {}
        self.recent_events: collections.deque[dict] = collections.deque(
            maxlen=ring)

    @classmethod
    def from_env(cls) -> "RouterTelemetry | None":
        d = os.environ.get(TELEMETRY_DIR_ENV)
        return cls(d) if d else None

    def signal(self, **signals) -> None:
        """Feed one sample per named signal into its ring (creating
        rings on first sight). None values are skipped — a signal with
        no reading this tick simply has no sample."""
        for name, value in signals.items():
            if value is None:
                continue
            ring = self.rings.get(name)
            if ring is None:
                ring = self.rings[name] = SignalRing(
                    maxlen=self._ring_len, alpha=self._ema_alpha)
            ring.push(value)

    def snapshot(self, window: int | None = None) -> dict[str, dict]:
        """Per-signal {last, ema, n, sum, mean, max} over the ring (or
        its last ``window`` samples) — the autoscaler's whole view of
        the world, and the metric snapshot its decisions are stamped
        with."""
        return {name: ring.stats(window)
                for name, ring in sorted(self.rings.items())}

    def replica(self, **row) -> None:
        if self.metrics is not None:
            self.metrics.write({"kind": "replica",
                                "time": round(time.time(), 3), **row})

    def event(self, event: str, **row) -> None:
        self.recent_events.append({"event": event, "time": time.time(),
                                   **row})
        if self.metrics is not None:
            self.metrics.write({"kind": "event", "event": event,
                                "time": round(time.time(), 3), **row})

    def summary(self, **row) -> None:
        if self.metrics is not None:
            self.metrics.write({"kind": "router",
                                "time": round(time.time(), 3), **row})

    def close(self) -> None:
        if self.metrics is not None:
            self.metrics.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
