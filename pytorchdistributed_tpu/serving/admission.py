"""Multi-tenant admission control (ISSUE 15): per-tenant queues under a
weighted deficit-round-robin token-budget scheduler, replacing the
router's single FIFO when tenancy is enabled.

Design constraints, in order:

  * **Drop-in for the router's queue.** The ReplicaRouter touches its
    queue through exactly the deque surface — ``append`` /
    ``appendleft`` / ``popleft`` / ``remove`` / ``len`` / iteration —
    so the controller implements that protocol and the router swaps it
    in as ``self._queue`` untouched: failover requeues
    (``appendleft``), the dispatch loop (``popleft``), deadline expiry
    (iterate + ``remove``) and drain all keep working. The ONE new
    entry point is ``offer()``: the policed admission path
    ``ReplicaRouter.submit`` calls instead of ``append``.

  * **Token-budget fairness, not request counts.** A request's cost is
    ``prompt_len + max_new_tokens`` — the slot-time it will actually
    consume — so one tenant's 4k-token monsters can't starve another's
    one-liners by arriving at the same request rate. Scheduling is
    weighted deficit round-robin: each pop replenishes the competing
    tenants' deficit counters by ``quantum * weight`` rounds until one
    can afford its head, then serves the next affordable tenant in
    round-robin order. A tenant whose queue empties forfeits its
    deficit (classic DRR — no banking idle time).

  * **Strict priority tiers above fairness.** ``priority`` 0 is
    highest; WDRR only arbitrates among the tenants whose HEAD request
    sits in the best (lowest) priority tier currently queued.

  * **Weighted shedding, never from a compliant tenant.** When the
    global queue cap is hit, the victim is the newest queued request
    of the tenant FURTHEST OVER its weighted admitted-token share —
    the arrival itself when the arriving tenant is the most over. A
    tenant at or under its guarantee can lose work only to its own
    per-tenant caps (``max_queued``, rate bucket), never to another
    tenant's overload: the fairness property tests pin shed == 0 for a
    compliant tenant against a 10x hot neighbour.

  * **Pressure -> tighter windows.** With ``priority_windows`` set,
    once the backlog passes ``pressure_depth`` an admitted request's
    per-request KV window (ISSUE 15 satellite of ROADMAP item 2) is
    clamped to its priority class's budget — background traffic decodes
    under a short sliding window while the queue is deep, freeing pool
    blocks for latency-sensitive tiers.

Everything is pure host state; the only clock is the injectable
``clock=`` the rate buckets read, so tests drive it with a FakeClock.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import math
import time

__all__ = ["DEFAULT_TENANT", "AdmissionController", "TenantConfig"]

DEFAULT_TENANT = "default"


@dataclasses.dataclass(frozen=True)
class TenantConfig:
    """One tenant's admission contract.

    weight: WDRR share — guarantees ``weight / sum(weights)`` of
      admitted token throughput while the tenant has demand.
    max_queued: per-tenant backlog cap (requests); arrivals past it
      shed immediately, regardless of global queue room.
    rate_tokens_per_s: token-bucket rate cap on ADMITTED token cost
      (prompt + budget); None = uncapped.
    burst_s: bucket depth in seconds of the rate — how far above the
      sustained rate a burst may momentarily go.
    max_sessions: per-tenant cap on PERSISTENT sessions parked in the
      tiered KV store (ISSUE 18); past it the tenant's own coldest
      session evicts — one tenant's long-lived conversations can never
      squeeze another's out of the warm tiers. None = uncapped.
    """

    weight: float = 1.0
    max_queued: int | None = None
    rate_tokens_per_s: float | None = None
    burst_s: float = 2.0
    max_sessions: int | None = None

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"weight must be > 0, got {self.weight}")
        if self.max_queued is not None and self.max_queued < 1:
            raise ValueError(
                f"max_queued must be >= 1, got {self.max_queued}")
        if self.max_sessions is not None and self.max_sessions < 1:
            raise ValueError(
                f"max_sessions must be >= 1, got {self.max_sessions}")
        if (self.rate_tokens_per_s is not None
                and self.rate_tokens_per_s <= 0):
            raise ValueError(f"rate_tokens_per_s must be > 0, got "
                             f"{self.rate_tokens_per_s}")


class AdmissionController:
    """Per-tenant queues + WDRR scheduler behind the router's deque
    protocol. Unknown tenants get ``default_config`` lazily, so an
    untenanted ``submit()`` still works (everything lands on the
    ``"default"`` tenant and the controller degrades to plain FIFO)."""

    def __init__(self, tenants: dict[str, TenantConfig] | None = None, *,
                 default_config: TenantConfig | None = None,
                 max_queue: int | None = None, quantum_tokens: int = 64,
                 clock=time.monotonic, pressure_depth: int | None = None,
                 priority_windows: dict[int, int] | None = None):
        if quantum_tokens < 1:
            raise ValueError(
                f"quantum_tokens must be >= 1, got {quantum_tokens}")
        self._cfgs: dict[str, TenantConfig] = dict(tenants or {})
        self._default = default_config or TenantConfig()
        self._max_queue = max_queue
        self._quantum = float(quantum_tokens)
        self._clock = clock
        self._pressure_depth = pressure_depth
        self._priority_windows = dict(priority_windows or {})
        self._order: list[str] = []
        self._queues: dict[str, collections.deque] = {}
        self._deficit: dict[str, float] = {}
        self._charged: dict[str, float] = {}   # admitted token cost
        self._served: dict[str, float] = {}    # scheduled token cost
        self._bucket: dict[str, float] = {}
        self._bucket_t: dict[str, float] = {}
        self._rr = 0
        # register declared tenants up front: a declared-but-idle
        # tenant still shapes the weight denominator
        for name in self._cfgs:
            self._ensure(name)

    # -- config / bookkeeping ------------------------------------------

    def config(self, name: str) -> TenantConfig:
        return self._cfgs.get(name, self._default)

    def _ensure(self, name: str) -> None:
        if name not in self._queues:
            self._order.append(name)
            self._queues[name] = collections.deque()
            self._deficit[name] = 0.0
            self._charged[name] = 0.0
            self._served[name] = 0.0
            cfg = self.config(name)
            if cfg.rate_tokens_per_s:
                self._bucket[name] = cfg.rate_tokens_per_s * cfg.burst_s
                self._bucket_t[name] = self._clock()

    @staticmethod
    def _cost(rr) -> float:
        return float(int(rr.prompt.size) + int(rr.max_new_tokens))

    @staticmethod
    def _tenant_of(rr) -> str:
        name = getattr(rr, "tenant", None)
        return name if name is not None else DEFAULT_TENANT

    def _refill(self, name: str, cfg: TenantConfig) -> None:
        now = self._clock()
        cap = cfg.rate_tokens_per_s * cfg.burst_s
        self._bucket[name] = min(
            cap, self._bucket[name]
            + cfg.rate_tokens_per_s * (now - self._bucket_t[name]))
        self._bucket_t[name] = now

    # -- the policed admission path ------------------------------------

    def offer(self, rr):
        """Admit ``rr`` or pick what sheds for it. Returns None when
        admitted with room; otherwise the request the router must shed
        — ``rr`` itself (per-tenant cap, rate cap, or the arriving
        tenant is the one most over budget) or an evicted queued
        request from the most-over-budget tenant (``rr`` then takes
        the freed spot). The caller owns finishing the victim."""
        name = self._tenant_of(rr)
        rr.tenant = name
        self._ensure(name)
        cfg = self.config(name)
        cost = self._cost(rr)
        q = self._queues[name]
        if cfg.max_queued is not None and len(q) >= cfg.max_queued:
            return rr
        if cfg.rate_tokens_per_s:
            self._refill(name, cfg)
            if self._bucket[name] < cost:
                return rr
        victim = None
        if self._max_queue is not None and len(self) >= self._max_queue:
            victim = self._pick_victim(rr)
            if victim is rr:
                return rr
        if cfg.rate_tokens_per_s:
            self._bucket[name] -= cost
        if (self._priority_windows and self._pressure_depth is not None
                and len(self) >= self._pressure_depth):
            w = self._priority_windows.get(int(getattr(rr, "priority", 0)))
            if w is not None and (getattr(rr, "kv_window", None) is None
                                  or w < rr.kv_window):
                rr.kv_window = w
        q.append(rr)
        self._charged[name] += cost
        return victim

    def _pick_victim(self, rr):
        """The weighted-shedding rule: the tenant furthest over its
        weighted share of admitted token cost loses its NEWEST queued
        request (oldest work is closest to a slot — shedding it wastes
        the most). A tenant at/under its guarantee is untouchable; if
        the arriving tenant is the most over (or nobody is over), the
        arrival itself sheds."""
        arriving = self._tenant_of(rr)
        over = self.overages()
        live = [n for n in self._order
                if self._queues[n] or n == arriving]
        worst = max(live, key=lambda n: (over.get(n, 0.0), n == arriving))
        if (worst == arriving or over.get(worst, 0.0) <= 0.0
                or not self._queues[worst]):
            return rr
        victim = self._queues[worst].pop()
        self._charged[worst] -= self._cost(victim)
        return victim

    def overages(self) -> dict[str, float]:
        """Per-tenant (admitted token share - weight share): > 0 means
        the tenant has taken more than its guarantee, <= 0 means it is
        compliant. Tenants that never appeared don't exist yet."""
        names = self._order
        if not names:
            return {}
        tw = sum(self.config(n).weight for n in names)
        tc = sum(self._charged[n] for n in names)
        if tc <= 0:
            return {n: 0.0 for n in names}
        return {n: self._charged[n] / tc - self.config(n).weight / tw
                for n in names}

    def starved_head(self):
        """The head request of the best-priority COMPLIANT tenant with
        work queued (None when every queued tenant is over budget) —
        the router's preemption trigger: if this exists while the
        fleet is saturated by over-budget residents, one of theirs
        goes back to the queue."""
        over = self.overages()
        best = None
        for n in self._order:
            if self._queues[n] and over.get(n, 0.0) <= 0.0:
                head = self._queues[n][0]
                if best is None or head.priority < best.priority:
                    best = head
        return best

    # -- the deque protocol the router already speaks ------------------

    def append(self, rr) -> None:
        """Unpoliced enqueue (internal requeue paths); use ``offer``
        for arrivals."""
        name = self._tenant_of(rr)
        rr.tenant = name
        self._ensure(name)
        self._queues[name].append(rr)

    def appendleft(self, rr) -> None:
        """Head-of-line requeue (failover / preemption / dispatch
        deferral): the request was already admitted once — no caps, no
        re-charge."""
        name = self._tenant_of(rr)
        rr.tenant = name
        self._ensure(name)
        self._queues[name].appendleft(rr)

    def popleft(self):
        """WDRR pop: among the tenants whose head sits in the best
        queued priority tier, replenish deficits by whole
        ``quantum * weight`` rounds until someone can afford their
        head, then serve the next affordable tenant in round-robin
        order."""
        live = [n for n in self._order if self._queues[n]]
        if not live:
            raise IndexError("pop from an empty admission queue")
        top = min(self._queues[n][0].priority for n in live)
        cands = [n for n in live if self._queues[n][0].priority == top]
        costs = {n: self._cost(self._queues[n][0]) for n in cands}

        def rounds_needed(n):
            inc = self._quantum * self.config(n).weight
            return max(0, math.ceil((costs[n] - self._deficit[n]) / inc))

        k = min(rounds_needed(n) for n in cands)
        if k:
            for n in cands:
                self._deficit[n] += k * self._quantum * self.config(n).weight
        eligible = [n for n in cands if self._deficit[n] >= costs[n]]
        if not eligible:  # float-rounding edge: force the closest one
            eligible = [min(cands, key=rounds_needed)]
        pick = None
        for j in range(len(self._order)):
            n = self._order[(self._rr + j) % len(self._order)]
            if n in eligible:
                pick = n
                self._rr = (self._rr + j + 1) % len(self._order)
                break
        q = self._queues[pick]
        rr = q.popleft()
        # stamp for the tracer's queue/admission split (WDRR residency
        # ends HERE; dispatch/placement latency starts) — a host attr
        # write, free whether tracing is on or not
        rr.dequeue_time = time.perf_counter()
        self._deficit[pick] -= costs[pick]
        self._served[pick] += costs[pick]
        if not q:
            self._deficit[pick] = 0.0
        return rr

    def remove(self, rr) -> None:
        name = self._tenant_of(rr)
        q = self._queues.get(name)
        if q is not None:
            try:
                q.remove(rr)
                return
            except ValueError:
                pass
        for q in self._queues.values():  # tenant tag changed under us
            try:
                q.remove(rr)
                return
            except ValueError:
                continue
        raise ValueError("request not queued")

    def __iter__(self):
        return itertools.chain.from_iterable(
            list(self._queues[n]) for n in self._order)

    def __len__(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def __bool__(self) -> bool:
        return any(self._queues.values())

    # -- observability -------------------------------------------------

    def tenant_stats(self) -> dict[str, dict]:
        """Per-tenant snapshot for summaries/reports: queue depth,
        weight, admitted/served token cost, current overage."""
        over = self.overages()
        return {
            n: {
                "queued": len(self._queues[n]),
                "weight": self.config(n).weight,
                "charged_tokens": round(self._charged[n], 1),
                "served_tokens": round(self._served[n], 1),
                "overage": round(over.get(n, 0.0), 4),
            }
            for n in self._order
        }
