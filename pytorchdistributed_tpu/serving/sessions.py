"""Persistent sessions + the tiered KV memory hierarchy (ISSUE 18).

A multi-turn SESSION is a first-class object here: a conversation's KV
survives stream close, reattaches on a later ``submit(session_id=...)``
— on any replica, via the router's ``FleetSessionIndex`` — and
persists across restarts. Three tiers:

  * **HBM (resident)** — the engine's paged pool, untouched: a
    finished session stream PARKS its blocks (ownership transferred
    off the slot, refcounts held) instead of freeing them, up to the
    engine's ``session_hbm_max``; reattach on the same replica is a
    radix re-seed, zero bytes moved.
  * **host-DRAM (warm)** — this module's ``SessionStore``: a bounded
    LRU of PR 11 ``KVBlockPayload``s (int8-aware, ``wire_version``-
    checked), demoted out of HBM by the engine, promoted back on
    resume.
  * **disk (cold)** — ``SessionStore`` spills LRU sessions past its
    DRAM budget to ``<dir>/<session_id>/`` with the CheckpointManager
    discipline (utils/manifest): data file first, per-file sha256
    manifest published atomically LAST, quarantine on mismatch — a
    torn or bit-flipped session can only MISS (the request re-prefills
    losslessly), never serve wrong KV.

Eviction demotes cold-but-live sessions down the hierarchy instead of
preempting (LRU, with per-tenant session caps riding the PR 15
``TenantConfig`` vocabulary); ``prefetch()`` promotes up
asynchronously ahead of a predicted resume. Every decline — version
mismatch, evicted, corrupt — is a counted, evented miss whose fallback
is the engine's ordinary (bitwise-lossless) re-prefill.

The store is HOST-ONLY: no jax, no device work, no compiled programs —
the zero-steady-state-recompile contract is held by construction.

Offline CLI for the disk tier (mirrors the checkpoint/compile-cache
CLIs)::

    python -m pytorchdistributed_tpu.serving.sessions ls <dir>
    python -m pytorchdistributed_tpu.serving.sessions verify <dir>
    python -m pytorchdistributed_tpu.serving.sessions gc <dir> \
        [--max-age SECONDS] [--keep-bytes BYTES] [--dry-run]
"""

from __future__ import annotations

import json
import pathlib
import re
import time

from pytorchdistributed_tpu.utils.manifest import (
    QUARANTINE_DIR,
    quarantine_dir,
    read_manifest,
    verify_dir_manifest,
    write_dir_manifest,
)

__all__ = [
    "SessionStore",
    "session_id_ok",
    "main",
]

PAYLOAD_NAME = "payload.json"

# session ids become directory names on the disk tier: a strict charset
# (no leading dot — no traversal, no hidden dirs) is the whole
# sanitization story
_SID_RE = re.compile(r"[A-Za-z0-9][A-Za-z0-9._:-]{0,127}$")


def session_id_ok(session_id) -> bool:
    return bool(isinstance(session_id, str)
                and _SID_RE.fullmatch(session_id))


def _check_sid(session_id: str) -> str:
    if not (isinstance(session_id, str)
            and _SID_RE.fullmatch(session_id)):
        raise ValueError(
            f"session_id must match {_SID_RE.pattern!r} (it names a "
            f"directory on the disk tier), got {session_id!r}")
    return session_id


class _Record:
    """One DRAM-tier entry."""

    __slots__ = ("payload", "tenant", "nbytes", "last_used", "on_disk")

    def __init__(self, payload, tenant: str, now: float,
                 on_disk: bool = False):
        self.payload = payload
        self.tenant = tenant
        self.nbytes = int(payload.nbytes)
        self.last_used = now
        # True while the disk copy is byte-identical to ``payload`` —
        # a demotion then skips the rewrite; any fresh put() clears it
        self.on_disk = on_disk


class SessionStore:
    """The host-DRAM + disk tiers of the session hierarchy.

    Args:
      directory: disk-tier root (None = DRAM-only; demotions past the
        DRAM budget are DROPPED and counted instead of spilled).
        Reopening a store over an existing directory rediscovers every
        published session — restart survival.
      dram_bytes: DRAM-tier budget over payload ``nbytes``; LRU
        sessions demote to disk (or drop) once it's exceeded.
      disk_bytes: optional disk-tier budget; oldest disk sessions are
        dropped once exceeded (the online twin of ``gc --keep-bytes``).
      tenants: optional ``{name: TenantConfig}`` — a tenant at its
        ``max_sessions`` cap evicts its OWN least-recent session
        (demoted down-tier, dropped off the bottom) before a new one
        is admitted; other tenants are never touched.
      wire_version: the KV payload schema this store will serve;
        stored sessions carrying any other version DECLINE at get()
        (counted, never served). Defaults to the engine's current
        ``KV_WIRE_VERSION``.
      clock: injectable time source for ages/GC (tests).
      faults: optional ``faults.FaultInjector`` consulted on every
        disk-tier touch (``on_io``); ``None`` falls back to the
        process-global ``PTD_FAULTS`` injector. An injected io_err on
        spill or load is absorbed here — counted as ``io_errors``, the
        session dropped or missed (re-prefill recovers it) — never a
        crash."""

    def __init__(self, directory: str | pathlib.Path | None = None, *,
                 dram_bytes: int = 256 << 20,
                 disk_bytes: int | None = None,
                 tenants: dict | None = None,
                 wire_version: int | None = None,
                 clock=None,
                 faults=None):
        if wire_version is None:
            from pytorchdistributed_tpu.serving.engine import (
                KV_WIRE_VERSION,
            )

            wire_version = KV_WIRE_VERSION
        self.directory = (pathlib.Path(directory)
                          if directory is not None else None)
        self.dram_bytes = int(dram_bytes)
        self.disk_bytes = disk_bytes
        self.wire_version = int(wire_version)
        self._tenants = dict(tenants or {})
        self._faults = faults
        self._clock = clock or time.time
        self._dram: dict[str, _Record] = {}  # insertion order == LRU
        #: sid -> {"nbytes", "tenant", "time"} for every PUBLISHED disk
        #: session (manifest present) — rebuilt by scanning on open
        self._disk: dict[str, dict] = {}
        self._prefetch: dict[str, object] = {}
        self._pool = None  # lazy ThreadPoolExecutor for prefetch()
        self.reset_stats()
        if self.directory is not None:
            self.directory.mkdir(parents=True, exist_ok=True)
            self._scan_disk()

    # -- stats ---------------------------------------------------------

    def reset_stats(self) -> None:
        self._stats = dict(puts=0, hits_hbm=0, hits_dram=0, hits_disk=0,
                           misses=0, promotes=0, demotes=0,
                           spilled_bytes=0, dropped=0, tenant_evicted=0,
                           quarantined=0, version_declines=0, torn=0,
                           prefetches=0, io_errors=0)

    def _io_hook(self, what: str) -> None:
        """Consult the fault injector before a disk-tier touch.

        slow_io sleeps here (latency, not failure); io_err raises
        OSError, which the spill/load call sites absorb."""
        inj = self._faults
        if inj is None:
            from pytorchdistributed_tpu.faults import inject as _inject

            inj = _inject.active()
        if inj is not None:
            inj.on_io(what)

    def stats(self) -> dict:
        out = dict(self._stats)
        out["dram_sessions"] = len(self._dram)
        out["dram_bytes"] = sum(r.nbytes for r in self._dram.values())
        out["disk_sessions"] = len(self._disk)
        out["disk_bytes"] = sum(m["nbytes"] for m in self._disk.values())
        return out

    # -- the tiers -----------------------------------------------------

    def __contains__(self, session_id: str) -> bool:
        return self.peek_tier(session_id) is not None

    def peek_tier(self, session_id: str) -> str | None:
        """"dram" | "disk" | None — no promotion, no LRU touch."""
        if session_id in self._dram:
            return "dram"
        if session_id in self._disk or session_id in self._prefetch:
            return "disk"
        return None

    def put(self, session_id: str, payload, *,
            tenant: str = "default") -> None:
        """Admit (or refresh) a session into the DRAM tier, then
        rebalance: per-tenant cap first, DRAM budget next (LRU demotes
        to disk / drops), disk budget last."""
        _check_sid(session_id)
        self._drop_prefetch(session_id)
        now = float(self._clock())
        self._dram.pop(session_id, None)
        self._dram[session_id] = _Record(payload, tenant, now)
        # a refreshed session's disk copy (if any) is stale now
        if self._disk.pop(session_id, None) is not None:
            self._remove_disk_dir(session_id)
        self._stats["puts"] += 1
        self._enforce_tenant_cap(tenant)
        self._enforce_dram()
        self._enforce_disk()

    def get(self, session_id: str):
        """``(payload, tier)`` — "dram" or "disk" — or ``None`` on any
        miss/decline. A disk hit verifies the manifest BEFORE parsing
        (corruption quarantines, a missing manifest is a torn write:
        both are misses, never wrong KV) and promotes to DRAM."""
        rec = self._dram.get(session_id)
        if rec is not None:
            # LRU touch = move to the tail
            del self._dram[session_id]
            self._dram[session_id] = rec
            rec.last_used = float(self._clock())
            self._stats["hits_dram"] += 1
            return rec.payload, "dram"
        loaded = self._take_prefetch(session_id)
        if loaded is None:
            loaded = self._load_disk(session_id)
        if loaded is None:
            self._stats["misses"] += 1
            return None
        payload, tenant = loaded
        now = float(self._clock())
        self._dram[session_id] = _Record(payload, tenant, now,
                                         on_disk=True)
        self._stats["hits_disk"] += 1
        self._stats["promotes"] += 1
        self._enforce_dram()
        return payload, "disk"

    def prefetch(self, session_id: str) -> bool:
        """Start promoting a disk session to DRAM on a background
        thread (predicted resume); ``get()`` joins the in-flight read.
        Returns whether a prefetch was started."""
        if (session_id in self._dram or session_id in self._prefetch
                or session_id not in self._disk):
            return False
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="session-prefetch")
        self._prefetch[session_id] = self._pool.submit(
            self._load_disk, session_id)
        self._stats["prefetches"] += 1
        return True

    def drop(self, session_id: str) -> bool:
        """Forget a session everywhere (client delete)."""
        self._drop_prefetch(session_id)
        hit = self._dram.pop(session_id, None) is not None
        if session_id in self._disk:
            del self._disk[session_id]
            self._remove_disk_dir(session_id)
            hit = True
        return hit

    def flush(self) -> int:
        """Write every DRAM session without a current disk copy to the
        disk tier (shutdown path — restart survival for warm sessions).
        Returns how many landed; 0 with no directory."""
        if self.directory is None:
            return 0
        n = 0
        for sid, rec in list(self._dram.items()):
            if not rec.on_disk and self._write_disk(sid, rec):
                n += 1
        self._enforce_disk()
        return n

    def close(self) -> None:
        self.flush()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None

    # -- eviction / budgets --------------------------------------------

    def _tenant_count(self, tenant: str) -> int:
        return (sum(1 for r in self._dram.values() if r.tenant == tenant)
                + sum(1 for m in self._disk.values()
                      if m.get("tenant") == tenant))

    def _tenant_cap(self, tenant: str) -> int | None:
        cfg = self._tenants.get(tenant)
        return getattr(cfg, "max_sessions", None) if cfg else None

    def _enforce_tenant_cap(self, tenant: str) -> None:
        cap = self._tenant_cap(tenant)
        if cap is None:
            return
        while self._tenant_count(tenant) > cap:
            # coldest first: oldest disk session, else LRU DRAM one
            victim = next((sid for sid, m in self._disk.items()
                           if m.get("tenant") == tenant), None)
            if victim is not None:
                del self._disk[victim]
                self._remove_disk_dir(victim)
            else:
                victim = next(sid for sid, r in self._dram.items()
                              if r.tenant == tenant)
                del self._dram[victim]
            self._stats["tenant_evicted"] += 1

    def _enforce_dram(self) -> None:
        used = sum(r.nbytes for r in self._dram.values())
        while used > self.dram_bytes and len(self._dram) > 1:
            sid, rec = next(iter(self._dram.items()))  # LRU head
            del self._dram[sid]
            used -= rec.nbytes
            if self.directory is not None:
                landed = rec.on_disk
                if not landed and self._write_disk(sid, rec):
                    landed = True
                    self._stats["spilled_bytes"] += rec.nbytes
                if landed:
                    self._stats["demotes"] += 1
                else:
                    # spill failed (io_err / disk full): the session is
                    # gone from every tier — a counted drop the client
                    # recovers from by re-prefilling, never a crash
                    self._stats["dropped"] += 1
            else:
                self._stats["dropped"] += 1
        self._enforce_disk()

    def _enforce_disk(self) -> None:
        if self.disk_bytes is None:
            return
        used = sum(m["nbytes"] for m in self._disk.values())
        while used > self.disk_bytes and self._disk:
            sid = min(self._disk, key=lambda s: self._disk[s]["time"])
            used -= self._disk[sid]["nbytes"]
            del self._disk[sid]
            self._remove_disk_dir(sid)
            self._stats["dropped"] += 1

    # -- disk tier -----------------------------------------------------

    def _session_dir(self, session_id: str) -> pathlib.Path:
        return self.directory / session_id

    def _scan_disk(self) -> None:
        """Rediscover published sessions after a restart. Directories
        without a manifest are torn writes — invisible (counted once
        here), reaped by gc; never an error, never served."""
        for entry in sorted(self.directory.iterdir()):
            if not entry.is_dir() or entry.name == QUARANTINE_DIR:
                continue
            man = read_manifest(entry)
            if man is None:
                self._stats["torn"] += 1
                continue
            self._disk[entry.name] = dict(
                nbytes=int(man.get("nbytes", sum(
                    f["size"] for f in man.get("files", {}).values()))),
                tenant=str(man.get("tenant", "default")),
                time=float(man.get("time", 0.0)),
                wire_version=int(man.get("wire_version", 1)))

    def _write_disk(self, session_id: str, rec: _Record) -> bool:
        """Spill one DRAM session to disk; False on I/O failure. A
        failed spill never publishes (the manifest is the last write),
        so readers see a torn dir at worst — a miss, never wrong KV."""
        from pytorchdistributed_tpu.serving.engine import (
            kv_payload_to_wire,
        )

        sdir = self._session_dir(session_id)
        try:
            self._io_hook("session_spill")
            sdir.mkdir(parents=True, exist_ok=True)
            path = sdir / PAYLOAD_NAME
            tmp = path.with_name(path.name + ".tmp")
            tmp.write_text(json.dumps(kv_payload_to_wire(rec.payload)))
            import os

            os.replace(tmp, path)
            # the manifest IS the publish: until it lands, the session
            # is torn-by-definition and every reader treats it as a miss
            write_dir_manifest(sdir, extra=dict(
                session=session_id, tenant=rec.tenant, nbytes=rec.nbytes,
                wire_version=int(rec.payload.wire_version)))
        except OSError:
            self._stats["io_errors"] += 1
            return False
        rec.on_disk = True
        self._disk[session_id] = dict(
            nbytes=rec.nbytes, tenant=rec.tenant,
            time=float(self._clock()),
            wire_version=int(rec.payload.wire_version))
        return True

    def _load_disk(self, session_id: str):
        """Verify + parse one disk session; None on every decline
        (missing, torn, corrupt→quarantine, version mismatch)."""
        if self.directory is None:
            return None
        try:
            self._io_hook("session_load")
        except OSError:
            # transient read failure, NOT corruption evidence: count it
            # and miss (caller re-prefills); the disk copy stays put
            self._stats["io_errors"] += 1
            return None
        sdir = self._session_dir(session_id)
        if not sdir.is_dir():
            self._disk.pop(session_id, None)
            return None
        ok, verified, detail = verify_dir_manifest(sdir)
        if not verified:
            self._stats["torn"] += 1
            self._disk.pop(session_id, None)
            return None
        if not ok:
            # positive evidence of corruption: move it aside as
            # post-mortem evidence — this sid can now only MISS
            quarantine_dir(sdir, root=self.directory)
            self._disk.pop(session_id, None)
            self._stats["quarantined"] += 1
            return None
        from pytorchdistributed_tpu.serving.engine import (
            kv_payload_from_wire,
        )

        try:
            wire = json.loads((sdir / PAYLOAD_NAME).read_text())
            payload = kv_payload_from_wire(wire)
        except (OSError, ValueError, KeyError, TypeError):
            quarantine_dir(sdir, root=self.directory)
            self._disk.pop(session_id, None)
            self._stats["quarantined"] += 1
            return None
        if payload.wire_version != self.wire_version:
            # not corrupt — a schema from another era. Decline loudly;
            # gc reaps it by age
            self._stats["version_declines"] += 1
            return None
        meta = self._disk.get(session_id) or {}
        return payload, str(meta.get("tenant", "default"))

    def _remove_disk_dir(self, session_id: str) -> None:
        if self.directory is None:
            return
        sdir = self._session_dir(session_id)
        if sdir.exists():
            import shutil

            shutil.rmtree(sdir, ignore_errors=True)

    def _take_prefetch(self, session_id: str):
        fut = self._prefetch.pop(session_id, None)
        return None if fut is None else fut.result()

    def _drop_prefetch(self, session_id: str) -> None:
        fut = self._prefetch.pop(session_id, None)
        if fut is not None:
            try:
                fut.result()
            except Exception:
                pass

    # -- offline inventory (the CLI's engine) --------------------------

    def ls(self) -> list[dict]:
        now = float(self._clock())
        rows = []
        for sid, rec in self._dram.items():
            rows.append(dict(session=sid, tier="dram", tenant=rec.tenant,
                             nbytes=rec.nbytes,
                             age_s=round(now - rec.last_used, 1)))
        for sid, m in self._disk.items():
            if sid in self._dram:
                continue
            rows.append(dict(session=sid, tier="disk",
                             tenant=m.get("tenant", "default"),
                             nbytes=m["nbytes"],
                             age_s=round(now - m.get("time", now), 1)))
        return rows

    def verify(self) -> list[tuple[str, bool, bool, str]]:
        """Manifest-check every disk session (no payload parsing, no
        device work): ``(sid, ok, verified, detail)`` per directory."""
        if self.directory is None:
            return []
        out = []
        for entry in sorted(self.directory.iterdir()):
            if not entry.is_dir() or entry.name == QUARANTINE_DIR:
                continue
            ok, verified, detail = verify_dir_manifest(entry)
            out.append((entry.name, ok, verified, detail))
        return out

    def gc(self, *, max_age_s: float | None = None,
           keep_bytes: int | None = None,
           dry_run: bool = False) -> dict:
        """Reap the disk tier: torn directories always; published
        sessions older than ``max_age_s``; then oldest-first until the
        tier fits ``keep_bytes``. Never touches quarantine/ (evidence)
        or the DRAM tier."""
        if self.directory is None:
            return dict(removed=0, kept=0, bytes_kept=0)
        now = float(self._clock())
        removed = 0
        for entry in sorted(self.directory.iterdir()):
            if not entry.is_dir() or entry.name == QUARANTINE_DIR:
                continue
            sid = entry.name
            man = read_manifest(entry)
            stale = man is None  # torn write: always reap
            if (not stale and max_age_s is not None
                    and now - float(man.get("time", 0.0)) > max_age_s):
                stale = True
            if stale:
                removed += 1
                if not dry_run:
                    self._disk.pop(sid, None)
                    self._remove_disk_dir(sid)
        if keep_bytes is not None:
            order = sorted(self._disk, key=lambda s: self._disk[s]["time"])
            used = sum(self._disk[s]["nbytes"] for s in order)
            for sid in order:
                if used <= keep_bytes:
                    break
                used -= self._disk[sid]["nbytes"]
                removed += 1
                if not dry_run:
                    del self._disk[sid]
                    self._remove_disk_dir(sid)
        return dict(removed=removed, kept=len(self._disk),
                    bytes_kept=sum(m["nbytes"]
                                   for m in self._disk.values()))


def main(argv=None) -> int:
    """Offline disk-tier CLI (see module docstring). ``verify`` exits
    1 when any published session is corrupt (torn/unverified ones
    report but do not fail — they can only miss)."""
    import argparse

    parser = argparse.ArgumentParser(
        "pytorchdistributed_tpu.serving.sessions")
    sub = parser.add_subparsers(dest="cmd", required=True)
    ls = sub.add_parser("ls", help="list stored sessions")
    ls.add_argument("directory")
    ver = sub.add_parser("verify",
                         help="check every session's integrity manifest")
    ver.add_argument("directory")
    ver.add_argument("--strict", action="store_true",
                     help="also fail on torn sessions (no manifest)")
    gc = sub.add_parser("gc", help="reap torn/old/over-budget sessions")
    gc.add_argument("directory")
    gc.add_argument("--max-age", type=float, default=None,
                    metavar="SECONDS",
                    help="drop sessions older than this")
    gc.add_argument("--keep-bytes", type=int, default=None,
                    metavar="BYTES",
                    help="drop oldest sessions until the tier fits")
    gc.add_argument("--dry-run", action="store_true")
    args = parser.parse_args(argv)

    store = SessionStore(args.directory, dram_bytes=0)
    if args.cmd == "ls":
        rows = store.ls()
        for r in sorted(rows, key=lambda r: r["session"]):
            print(f"{r['session']:<32}  {r['tier']:<4}  "
                  f"{r['tenant']:<12}  {r['nbytes']:>12}  "
                  f"age {r['age_s']:.0f}s")
        total = sum(r["nbytes"] for r in rows)
        print(f"{len(rows)} session(s), {total} bytes")
        return 0
    if args.cmd == "verify":
        verdicts = store.verify()
        if not verdicts:
            print(f"no sessions under {args.directory}")
            return 1
        bad = 0
        for sid, ok, verified, detail in verdicts:
            status = ("OK" if ok and verified
                      else "TORN" if ok else "CORRUPT")
            if not ok or (args.strict and not verified):
                bad += 1
            print(f"{sid:<32}  {status:<8}  {detail}")
        print(f"{len(verdicts)} session(s), {bad} bad")
        return 1 if bad else 0
    out = store.gc(max_age_s=args.max_age, keep_bytes=args.keep_bytes,
                   dry_run=args.dry_run)
    tag = " (dry run)" if args.dry_run else ""
    print(f"removed {out['removed']} session(s){tag}, "
          f"{out['kept']} kept, {out['bytes_kept']} bytes")
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
