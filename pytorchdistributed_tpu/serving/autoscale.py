"""SLO-aware autoscaling control loop (ISSUE 15's tentpole).

The ``Autoscaler`` closes the loop the elastic pieces left open: PR 9's
SIGTERM drain and PR 10's respawn/warm-join machinery gave the fleet
lossless ways to SHRINK and GROW, but both waited for an operator (or a
crash). This control loop watches the router's own telemetry signal
rings — queue depth, TTFT EMA, shed rate, slot occupancy, prefill
backlog — against an ``SLOConfig``, and turns sustained breaches into
``router.add_replica()`` (the warm-join path: in-process joins share
the jit cache and compile NOTHING; subprocess joins restore from
checkpoint + the persistent AOT cache) and sustained idleness into
``router.remove_replica()`` (graceful DRAINING -> tombstone — no
stream is ever dropped by a scale-down).

Control-theory guardrails, all injectable for fake-clock tests:

  * **hysteresis** — a breach must persist ``breach_ticks`` consecutive
    evaluations before scaling up, idleness ``clear_ticks`` before
    scaling down (clear_ticks > breach_ticks by default: growing is
    cheap and urgent, shrinking is neither);
  * **per-direction cooldowns** — after a scale-up the loop waits
    ``up_cooldown_s`` before growing again (the new replica needs time
    to absorb load, or one flash crowd buys the whole max_replicas
    range), and ``down_cooldown_s`` before shrinking;
  * **bounds** — ``min_replicas``/``max_replicas`` per pool; in a
    disaggregated fleet the prefill and decode pools scale
    INDEPENDENTLY on their own signals (queue/backlog pressure is a
    prefill problem; occupancy/TTFT pressure a decode problem).

Every decision is durable: appended to ``decisions`` with the metric
snapshot that justified it, and emitted as an ``autoscale_up`` /
``autoscale_down`` TelemetryEvent — the report CLI's scaling timeline.
``reaction_times()`` joins scale-up decisions against
``router.first_token_times`` to measure decision -> first-token wall
latency, the bench's reaction stamp.

The router surface consumed here is deliberately narrow —
``telemetry.snapshot()``, ``pool_state()``, ``add_replica()`` /
``remove_replica()``, ``first_token_times`` — so the unit tests drive
the whole decision machine against a pure-host stub router, no jax.
"""

from __future__ import annotations

import dataclasses
import time

__all__ = ["SLOConfig", "Autoscaler"]

#: pool name -> the role a new replica of that pool is born with
_POOL_ROLE = {"fleet": "both", "prefill": "prefill", "decode": "decode"}


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """The serving objectives the autoscaler defends.

    ttft_target_ms: fleet TTFT EMA above this is a latency breach.
    shed_rate_max: windowed shed fraction (shed/submitted over the
      signal window) above this is a capacity breach.
    queue_high: router queue-depth EMA above this is a backlog breach.
    occupancy_high / occupancy_low: slot-occupancy band — above high
      breaches (decode/fleet pools); below low, with an empty queue and
      zero shed, counts toward scale-down.
    prefill_backlog_high: queue + prefilling + parked EMA above this
      breaches the PREFILL pool (disaggregated fleets only).
    """

    ttft_target_ms: float = 500.0
    shed_rate_max: float = 0.02
    queue_high: float = 8.0
    occupancy_high: float = 0.85
    occupancy_low: float = 0.25
    prefill_backlog_high: float = 8.0

    def __post_init__(self):
        if not 0.0 <= self.occupancy_low < self.occupancy_high:
            raise ValueError(
                f"need 0 <= occupancy_low < occupancy_high, got "
                f"{self.occupancy_low} / {self.occupancy_high}")
        if self.shed_rate_max < 0:
            raise ValueError("shed_rate_max must be >= 0")


class Autoscaler:
    """One evaluation per ``step()`` (call it right after
    ``router.step()`` — the replay harness does). Stateless between
    processes on purpose: everything it knows, it reads fresh from the
    router each tick."""

    def __init__(self, router, slo: SLOConfig | None = None, *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 pool_bounds: dict[str, tuple[int, int]] | None = None,
                 breach_ticks: int = 3, clear_ticks: int = 8,
                 up_cooldown_s: float = 0.5, down_cooldown_s: float = 2.0,
                 window: int = 64, hold_on_degraded: bool = True,
                 clock=time.monotonic):
        if min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}")
        if max_replicas < min_replicas:
            raise ValueError(
                f"max_replicas {max_replicas} < min_replicas "
                f"{min_replicas}")
        self.router = router
        self.slo = slo or SLOConfig()
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.pool_bounds = dict(pool_bounds or {})
        self.breach_ticks = max(1, breach_ticks)
        self.clear_ticks = max(1, clear_ticks)
        self.up_cooldown_s = up_cooldown_s
        self.down_cooldown_s = down_cooldown_s
        self.window = window
        self.hold_on_degraded = bool(hold_on_degraded)
        self._clock = clock
        self._breach: dict[str, int] = {}
        self._clear: dict[str, int] = {}
        self._last_up: dict[str, float] = {}
        self._last_down: dict[str, float] = {}
        self.decisions: list[dict] = []

    # -- signal extraction ---------------------------------------------

    def _bounds(self, pool: str) -> tuple[int, int]:
        return self.pool_bounds.get(
            pool, (self.min_replicas, self.max_replicas))

    @staticmethod
    def _sig(snap: dict, name: str, field: str = "ema"):
        return (snap.get(name) or {}).get(field)

    def _read(self, pool: str, st: dict, snap: dict) -> dict:
        """The pool's decision inputs, as one flat dict — also exactly
        what a decision event gets stamped with."""
        sub = self._sig(snap, "submitted", "sum") or 0.0
        shed = self._sig(snap, "shed", "sum") or 0.0
        m = {
            "queue_depth": self._sig(snap, "queue_depth") or 0.0,
            "ttft_ema_s": self._sig(snap, "ttft_ema_s", "last"),
            "shed_rate": (shed / sub) if sub else 0.0,
            "prefill_backlog": self._sig(snap, "prefill_backlog") or 0.0,
            "occupancy": st.get("occupancy"),
            "healthy": st.get("healthy", 0),
            "draining": st.get("draining", 0),
            "quarantined": st.get("quarantined", 0),
        }
        # when the router carries a request tracer (ISSUE 17), its live
        # per-tenant SLO-debt ledger rides the same decision snapshot —
        # "slo_debt_s" (total TTFT seconds beyond budget) and
        # "slo_debt_tenant" (the worst offender) land in every stamped
        # decision event
        tracer = getattr(self.router, "trace", None)
        if tracer is not None:
            m.update(tracer.debt_totals())
        return m

    def _breaches(self, pool: str, m: dict) -> list[str]:
        """Which SLO signals this pool is currently violating. Role-
        aware: backlog/queue/shed pressure belongs to the pool that
        ADMITS (prefill, or the whole fleet colocated); occupancy and
        TTFT to the pool that DECODES."""
        slo, out = self.slo, []
        admits = pool in ("fleet", "prefill")
        decodes = pool in ("fleet", "decode")
        if admits and m["queue_depth"] > slo.queue_high:
            out.append("queue_depth")
        if admits and m["shed_rate"] > slo.shed_rate_max:
            out.append("shed_rate")
        if (pool == "prefill"
                and m["prefill_backlog"] > slo.prefill_backlog_high):
            out.append("prefill_backlog")
        if decodes and (m["occupancy"] or 0.0) > slo.occupancy_high:
            out.append("occupancy")
        if (decodes and m["ttft_ema_s"] is not None
                and m["ttft_ema_s"] * 1e3 > slo.ttft_target_ms):
            out.append("ttft")
        return out

    def _idle(self, pool: str, m: dict) -> bool:
        slo = self.slo
        occ_ok = (m["occupancy"] is None
                  or m["occupancy"] < slo.occupancy_low)
        if pool == "prefill":
            return (m["prefill_backlog"] <= 1.0
                    and m["queue_depth"] < 1.0 and m["shed_rate"] == 0.0)
        return (occ_ok and m["queue_depth"] < 1.0
                and m["shed_rate"] == 0.0)

    # -- the control loop ----------------------------------------------

    def step(self) -> list[dict]:
        """One evaluation over every pool; returns the decisions made
        this tick (usually empty)."""
        snap = self.router.telemetry.snapshot(self.window)
        made: list[dict] = []
        for pool, st in self.router.pool_state().items():
            d = self._eval(pool, st, snap)
            if d is not None:
                made.append(d)
        return made

    def _eval(self, pool: str, st: dict, snap: dict) -> dict | None:
        m = self._read(pool, st, snap)
        breaches = self._breaches(pool, m)
        if breaches:
            self._breach[pool] = self._breach.get(pool, 0) + 1
            self._clear[pool] = 0
        elif self._idle(pool, m):
            self._clear[pool] = self._clear.get(pool, 0) + 1
            self._breach[pool] = 0
        else:
            self._breach[pool] = 0
            self._clear[pool] = 0
        if (self.hold_on_degraded
                and (st.get("dead", 0) or st.get("quarantined", 0))):
            # a degraded fleet can READ as idle (dead replicas serve
            # nothing); never scale down while recovery is in flight —
            # chaos soaks hit this constantly
            self._clear[pool] = 0
        now = self._clock()
        lo, hi = self._bounds(pool)
        # joins in flight (QUARANTINED warming) count toward the max —
        # a slow-warming subprocess join must not trigger a second one
        size = st.get("healthy", 0) + st.get("quarantined", 0)
        if (self._breach.get(pool, 0) >= self.breach_ticks
                and size < hi
                and now - self._last_up.get(pool, -1e18)
                >= self.up_cooldown_s):
            idx = self.router.add_replica(role=_POOL_ROLE[pool])
            self._last_up[pool] = now
            self._breach[pool] = 0
            return self._decide("scale_up", pool, idx, breaches, m, now)
        if (self._clear.get(pool, 0) >= self.clear_ticks
                and st.get("healthy", 0) > lo
                and st.get("draining", 0) == 0   # one drain at a time
                and now - self._last_down.get(pool, -1e18)
                >= self.down_cooldown_s):
            idx = self.router.remove_replica(
                role=None if pool == "fleet" else _POOL_ROLE[pool])
            if idx is None:
                return None   # the router vetoed (last capable replica)
            self._last_down[pool] = now
            self._clear[pool] = 0
            return self._decide("scale_down", pool, idx, ["idle"], m, now)
        return None

    def _decide(self, action: str, pool: str, replica: int,
                why: list[str], m: dict, now: float) -> dict:
        d = {"action": action, "pool": pool, "replica": replica,
             "why": list(why), "t": now,
             "wall_t": time.perf_counter(),
             **{f"m_{k}": v for k, v in m.items()}}
        self.decisions.append(d)
        self.router.telemetry.event(
            f"auto{action}", pool=pool, replica=replica,
            why=",".join(why),
            **{k: (round(v, 4) if isinstance(v, float) else v)
               for k, v in m.items() if v is not None})
        return d

    # -- measurement ---------------------------------------------------

    def reaction_times(self) -> list[dict]:
        """Per scale-up decision: wall seconds from the decision to the
        new replica's FIRST delivered token (None while it hasn't
        served yet) — the autoscale bench's reaction stamp."""
        ftt = self.router.first_token_times
        out = []
        for d in self.decisions:
            if d["action"] != "scale_up":
                continue
            t = ftt.get(d["replica"])
            out.append({"replica": d["replica"], "pool": d["pool"],
                        "reaction_s": (round(t - d["wall_t"], 4)
                                       if t is not None
                                       and t >= d["wall_t"] else None)})
        return out

    def summary(self) -> dict:
        ups = [d for d in self.decisions if d["action"] == "scale_up"]
        downs = [d for d in self.decisions
                 if d["action"] == "scale_down"]
        reacts = [r["reaction_s"] for r in self.reaction_times()
                  if r["reaction_s"] is not None]
        return {
            "scale_ups": len(ups),
            "scale_downs": len(downs),
            "reaction_s_max": max(reacts) if reacts else None,
            "reaction_s_mean": (round(sum(reacts) / len(reacts), 4)
                                if reacts else None),
        }
