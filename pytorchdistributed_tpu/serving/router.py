"""Replicated serving: a health-checked replica router with lossless
mid-stream failover (ISSUE 9 — ROADMAP item 3's traffic-scale half).

Everything below serving/engine.py serves from ONE engine on ONE mesh: a
single crash, hang, or NaN'd parameter tree kills every in-flight stream
and drops the queue. The reference tutorial's whole fault-tolerance story
is the torchrun elastic agent — detect a dead worker, relaunch the job
from the env-contract rendezvous (SURVEY §2b; reproduced for *training*
in PR 4). This module is the SERVING restatement of that contract:

  * a host-side ``ReplicaRouter`` owns N ``ServingEngine`` replicas —
    in-process (the CPU test tier and single-host multi-engine) or as
    SUBPROCESS workers launched with the same RANK/WORLD_SIZE/MASTER_*
    env contract ``run.py`` gives training workers, SIGTERM forwarding
    and ``kill_group`` escalation included;
  * ``submit()`` load-balances across replicas on the telemetry the
    engine already emits (slot occupancy, queue depth, pool pressure,
    TTFT EMA — ``ServingEngine.health()``);
  * every replica is health-checked per router tick: a **progress
    watermark** (monotonic completed-compiled-call counter, the serving
    analog of runtime/heartbeat.py's device-sync'd beats) catches hangs
    within a bounded number of ticks, process exit / pipe EOF catches
    crashes immediately, and a periodic compiled **params-finite probe**
    catches a NaN'd replica (the diagnostics-tripwire analog: garbage
    *tokens* are perfectly finite ints, the *params* are where the rot
    is visible);
  * the robustness core is **lossless mid-stream failover**: every
    request the router hands out carries its prompt, sampling params,
    seed and generated-so-far tokens, so when a replica dies its
    in-flight requests are redispatched to a survivor, which resumes by
    re-prefilling prompt+generated (``submit(generated=...)`` — the
    exact preempt-requeue mechanism the paged engine already proved
    bitwise-safe). The client-visible greedy stream is **bitwise
    identical** to an uninterrupted single-engine run, and seeded
    sampled streams continue their fold_in sequence exactly where the
    dead replica left them;
  * on top: a per-request retry budget with ``faults/retry.py`` backoff
    between redispatches, admission-control **load shedding** (bounded
    router queue → immediate ``finish_reason="shed"`` instead of
    unbounded latency), replica **quarantine/rejoin** with a warmup
    canary re-admission, and router-level graceful **drain on SIGTERM**
    (finish resident streams, shed the queue, leave no orphan replica);
  * and since ISSUE 10, **auto-respawn**: a DEAD replica is RELAUNCHED
    (``respawn_budget`` attempts with exponential backoff) — subprocess
    workers restart under the same env/spec contract, restoring weights
    from a verified checkpoint and their executables from the
    persistent AOT compile cache (runtime/compile_cache.py), so the
    relaunch is load-bound seconds, not compile-bound minutes — and
    rejoins through the same quarantine → clean-probe → canary gauntlet
    as a NaN recovery. A crash is a transient, not a permanent capacity
    loss; torchrun's elastic agent, restated for serving.

Chaos is first-class: ``faults/inject.py`` grew ``replica_crash`` /
``replica_hang`` / ``replica_nan`` serving faults (``PTD_FAULTS`` /
``run.py --faults`` syntax, targeted by replica index and router tick);
the router consults the process-global injector every tick and applies
whatever fires. tests/test_router.py is the chaos suite;
``bench.py --mode router`` stamps balanced-occupancy spread, shed rate
under overload, and failover recovery time.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import random
import select
import subprocess
import sys
import time

import numpy as np

from pytorchdistributed_tpu.faults import inject as faults_inject
from pytorchdistributed_tpu.faults.retry import RetryPolicy
from pytorchdistributed_tpu.serving.engine import (
    SamplingParams,
    ServingEngine,
    kv_payload_from_wire,
    kv_payload_to_wire,
    prefix_payload_from_wire,
    prefix_payload_to_wire,
)
from pytorchdistributed_tpu.serving.paging import (
    FleetPrefixIndex,
    FleetSessionIndex,
    block_hashes,
)
from pytorchdistributed_tpu.serving.telemetry import RouterTelemetry
from pytorchdistributed_tpu.telemetry.events import TELEMETRY_DIR_ENV
from pytorchdistributed_tpu.telemetry.tracing import (
    RequestTracer,
    to_unix as _trace_to_unix,
)

#: Replica lifecycle states. HEALTHY serves traffic; QUARANTINED is
#: alive but sick (params non-finite) — probed every tick, rejoined
#: after a clean streak + canary; DEAD is crashed or hung (its requests
#: were failed over) and never returns. ISSUE 15 adds the scale-down
#: pair: DRAINING still steps (resident streams finish, parked prefills
#: hand off) but admits nothing new, and REMOVED is a tombstone — the
#: parallel per-replica lists are never renumbered, so a removed
#: replica's counters and occupancy history survive into the summary.
HEALTHY, QUARANTINED, DEAD = "healthy", "quarantined", "dead"
DRAINING, REMOVED = "draining", "removed"

#: Replica roles (ISSUE 12 — prefill/decode disaggregation). A
#: ``prefill``-role replica runs chunked prefill only: its requests are
#: submitted ``prefill_only`` and PARK after the first token, then the
#: router's handoff sweep streams their KV blocks to a decode-capable
#: replica which activates the stream mid-flight. ``decode`` replicas
#: receive handoffs (and serve full requests only as a fallback when no
#: prefill-capable replica is healthy — availability beats role
#: purity). ``both`` (the default) is the colocated PR-9 behavior.
ROLE_PREFILL, ROLE_DECODE, ROLE_BOTH = "prefill", "decode", "both"
ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_BOTH)

#: Default redispatch backoff: immediate-ish (serving latency budgets are
#: milliseconds, not checkpoint-restore seconds), but still exponential
#: so a flapping replica set cannot melt the router in a redispatch storm.
ROUTER_RETRY = RetryPolicy(max_attempts=4, base_delay_s=0.005,
                           backoff=2.0, max_delay_s=0.25, jitter=0.25)

#: Default respawn backoff (ISSUE 10): a DEAD replica's relaunch
#: attempts space out exponentially — a crash-looping worker (bad
#: checkpoint, poisoned cache entry, broken node) must burn its budget
#: slowly instead of melting the router in a spawn storm. Slower than
#: ROUTER_RETRY on purpose: a respawn pays process start + restore +
#: (cached) warmup, not a redispatch.
RESPAWN_RETRY = RetryPolicy(max_attempts=4, base_delay_s=0.05,
                            backoff=2.0, max_delay_s=5.0, jitter=0.25)

#: env default for ``respawn_budget`` (relaunches per replica; 0 = the
#: pre-ISSUE-10 behavior where DEAD is forever)
ROUTER_RESPAWN_ENV = "PTD_ROUTER_RESPAWN"


class ReplicaCrashed(RuntimeError):
    """Raised by a replica's step when the replica is gone (injected
    crash in-process; dead pipe/process for a subprocess worker)."""


#: Global per-op wire timeout override (seconds); per-op overrides ride
#: ``PTD_WIRE_TIMEOUT_<OP>_S`` (op name upper-cased), e.g.
#: ``PTD_WIRE_TIMEOUT_WARMUP_S=120``. Unset → per-op defaults (warmup
#: 600 s; everything else max(hang_grace_s, 30 s)).
WIRE_TIMEOUT_ENV = "PTD_WIRE_TIMEOUT_S"
#: Soft deadline (seconds): any synchronous wire op slower than this
#: emits a ``wire_slow`` telemetry event — a *delayed* op is visible
#: long before the hard timeout declares it a hang.
WIRE_SOFT_ENV = "PTD_WIRE_SOFT_S"


class WireFault(TimeoutError):
    """A protocol-level fault on a replica's wire: a mangled/torn JSON
    line, or a response that never arrived inside its op timeout while
    the worker process is demonstrably alive. Subclasses TimeoutError
    so every existing call site's ``except (ReplicaCrashed,
    TimeoutError)`` contains it — a wire fault can NEVER escape a
    router tick — while new call sites (handoff, dispatch) can catch it
    first and choose quarantine-and-requeue over declare-dead."""

    def __init__(self, msg: str, *, kind: str = "wire_timeout"):
        super().__init__(msg)
        self.kind = kind


class RouterRequest:
    """One client-visible request: the router's durable record of
    everything needed to REDISPATCH the stream losslessly — prompt,
    sampling params (seed included), stop ids, budget, and the tokens
    delivered so far. The engine-side Request handle is disposable (it
    dies with its replica); this one is not."""

    _ids = itertools.count()

    def __init__(self, prompt, max_new_tokens: int,
                 sampling: SamplingParams, stop_ids, on_token=None,
                 deadline_s: float | None = None,
                 tenant: str | None = None, priority: int = 0,
                 kv_window: int | None = None,
                 kv_sink: int | None = None,
                 session_id: str | None = None):
        self.id = next(RouterRequest._ids)
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = max_new_tokens
        self.sampling = sampling
        self.stop_ids = stop_ids
        self.on_token = on_token
        self.deadline_s = deadline_s
        # multi-tenancy (ISSUE 15): the admission controller schedules,
        # rate-limits and sheds by tenant; priority 0 is highest
        self.tenant = tenant or "default"
        self.priority = int(priority)
        # per-request KV limits (tighten-only; the replica's engine
        # clamps to its pool config and may REFUSE incompatible pools)
        self.kv_window = kv_window
        self.kv_sink = kv_sink
        # persistent session (ISSUE 18): the multi-turn identity this
        # stream's KV survives under after the stream closes
        self.session_id = session_id
        self.tokens: list[int] = []          # the delivered stream
        self.done = False
        self.finish_reason: str | None = None
        self.submit_time: float | None = None
        self.first_token_time: float | None = None
        self.finish_time: float | None = None
        self.retries = 0                     # redispatches consumed
        self.replicas: list[int] = []        # placement history
        self._eligible_at = 0.0              # redispatch backoff gate
        self._handle = None                  # engine-side request/mirror
        self._replica: int | None = None
        self._hash_chain: list[str] | None = None  # fleet prefix index
        # distributed tracing (ISSUE 17): the TraceContext minted at
        # router submit (None when tracing is off), the current
        # queue-residency start (reset at every requeue), and the last
        # WDRR dequeue stamp (admission.popleft writes it)
        self.trace = None
        self._trace_enq_t: float | None = None
        self.dequeue_time: float | None = None

    @property
    def output_ids(self) -> np.ndarray:
        """prompt + delivered continuation (int32 [len])."""
        return np.concatenate(
            [self.prompt, np.asarray(self.tokens, np.int32)])

    @property
    def ttft_s(self) -> float | None:
        if self.first_token_time is None or self.submit_time is None:
            return None
        return self.first_token_time - self.submit_time


class InProcessReplica:
    """One ServingEngine behind the replica protocol — the CPU test
    tier's replica, and the single-host multi-engine deployment shape.
    Fault application is cooperative (an in-process replica cannot
    os._exit the router): ``apply_fault`` flips flags the step/health
    paths honor, which is exactly what makes the chaos suite
    deterministic."""

    #: extra wall-clock allowance before the router's tick-based hang
    #: watchdog may fire — 0 in-process (the engine steps synchronously
    #: inside router ticks, so a frozen watermark over hang_ticks ticks
    #: IS a hang); subprocess replicas answer asynchronously and set
    #: this > 0 so fast idle router spins can't out-run a healthy
    #: worker's response latency
    hang_grace_s = 0.0
    #: in-process faults are applied by the ROUTER (apply_fault);
    #: subprocess workers run the injector against their own RANK, so
    #: the router must not consult (and consume one-shot markers of)
    #: the same spec on their behalf
    faults_in_worker = False

    def __init__(self, index: int, factory, *, warmup_lens=None):
        self.index = index
        self._factory = factory
        self.engine: ServingEngine = factory()
        self.warmup_lens = warmup_lens
        self.alive = True
        self._hung = False
        self._crash_next = False
        self._slow_ms = 0.0

    def warmup(self, prompt_lens=None, kv_stream: bool = True) -> None:
        self.engine.warmup(prompt_lens=prompt_lens or self.warmup_lens)
        if kv_stream:
            # the KV stream's gather/scatter pair (no-op dense): warmed
            # unconditionally so a handoff or fleet prefix ship never
            # compiles mid-serving
            self.engine.warmup_kv_stream()

    def submit(self, rr: RouterRequest, *, generated, deadline_s,
               on_token, prefill_only: bool = False):
        return self.engine.submit(
            rr.prompt, max_new_tokens=rr.max_new_tokens,
            sampling=rr.sampling, stop_ids=rr.stop_ids,
            deadline_s=deadline_s, generated=generated, on_token=on_token,
            prefill_only=prefill_only,
            kv_window=rr.kv_window, kv_sink=rr.kv_sink,
            session_id=rr.session_id, tenant=rr.tenant,
            trace=rr.trace,
            origin_t=(None if rr.submit_time is None
                      else _trace_to_unix(rr.submit_time)))

    def preempt(self, rr: RouterRequest) -> bool:
        """Evict the stream losslessly (admission-pressure preemption):
        the engine frees its slot/blocks and finishes the handle
        ``"preempted"`` — the router's reap sweep requeues it."""
        return (rr._handle is not None
                and self.engine.preempt_request(rr._handle))

    # -- KV block stream (ISSUE 12) -----------------------------------

    def export_kv(self, rr: RouterRequest):
        return self.engine.export_kv_blocks(rr._handle)

    def import_kv(self, rr: RouterRequest, payload, *, deadline_s,
                  on_token):
        return self.engine.import_kv_blocks(
            payload, on_token=on_token, deadline_s=deadline_s)

    def export_prefix(self, tokens):
        return self.engine.export_prefix_blocks(tokens)

    def import_prefix(self, payload) -> int:
        return self.engine.import_prefix_blocks(payload)

    # -- persistent sessions (ISSUE 18) -------------------------------

    def export_session(self, session_id: str):
        """Pull a RESIDENT parked session off this replica (cross-
        replica reattach: the turn landed elsewhere)."""
        return self.engine.export_session(session_id)

    def seed_session(self, payload) -> int:
        """Seed a session payload into this replica's prefix cache so
        the reattaching submit rides an ordinary prefix hit. Returns
        tokens seeded (0 = declined → re-prefill)."""
        return self.engine.seed_session_blocks(payload, remote=True)

    def take_demoted_sessions(self):
        return self.engine.take_demoted_sessions()

    def step(self) -> None:
        if self._crash_next:
            self.alive = False
            raise ReplicaCrashed(
                f"replica {self.index}: injected crash")
        if self._hung:
            return  # frozen: alive, silent, zero progress
        if self._slow_ms > 0:
            # a straggler, not a hang: the step completes (progress
            # advances, the watchdog stays quiet) — it just takes the
            # injected latency to do so
            time.sleep(self._slow_ms / 1e3)
            self._slow_ms = 0.0
        self.engine.step()

    def health(self) -> dict:
        h = self.engine.health()
        h["alive"] = self.alive
        if self._hung:
            # a wedged device makes no progress but the HOST snapshot
            # still reads fresh — freeze the watermark, as a real hang
            # would
            h["progress"] = -1
        return h

    def probe(self, exclusive: bool = False) -> bool:
        """Device-level params-finite check (the sick tripwire);
        ``exclusive`` is the subprocess wire-scheduling hint — a
        synchronous in-process probe has no wire to share."""
        return self.engine.check_params_finite()

    def apply_fault(self, kind: str, ms: float = 100.0) -> None:
        if kind == "replica_crash":
            self._crash_next = True
        elif kind == "replica_hang":
            self._hung = True
        elif kind == "replica_nan":
            self.poison_params()
        elif kind == "replica_slow":
            self._slow_ms += float(ms)

    def set_draft_params(self, params=None, *, checkpoint=None,
                         step=None) -> dict:
        """Hot-swap the engine's speculative draft weights (ISSUE 16).
        In-process the tree is handed over directly (the router restores
        a checkpoint once for the whole fleet); the engine's structure/
        shape check is the gate. Returns the new draft identity."""
        if params is None:
            if checkpoint is None:
                raise ValueError("pass params or checkpoint")
            from pytorchdistributed_tpu.training.checkpoint import (
                CheckpointManager,
            )

            with CheckpointManager(checkpoint) as mgr:
                params, _ = mgr.restore_params(step=step)
        self.engine.set_draft_params(params)
        return {"draft_hash": self.engine.draft_params_hash(),
                "draft_swaps": self.engine.draft_swaps}

    def poison_params(self) -> None:
        """NaN every inexact param leaf (engine.nan_params): outputs
        rot instantly, and only the params-finite tripwire can say
        why."""
        from pytorchdistributed_tpu.serving.engine import nan_params

        self._saved_weights = self.engine._weights
        self.engine.set_params(nan_params(self.engine._weights))

    def restore_params(self) -> None:
        """The operator's repair step (tests: undo poison_params) —
        rejoin still requires the router's probe streak + canary."""
        if getattr(self, "_saved_weights", None) is not None:
            self.engine.set_params(self._saved_weights)
            self._saved_weights = None

    def quarantine_reset(self) -> None:
        """Entering quarantine: retire resident garbage streams (the
        router already redispatched them) and drop every cached prefix
        block — K/V written under NaN params must never serve a future
        prefix hit."""
        self.engine.drain()
        self.engine.invalidate_prefix_cache()

    def drain(self) -> list:
        return self.engine.drain()

    def close(self) -> None:
        if self.alive and not self._hung:
            self.engine.close()


class _Mirror:
    """Router-side stand-in for a request living in a subprocess
    worker: done/finish_reason arrive in step replies; ``parked``
    flips when the worker reports the request prefilled-and-parked
    (the handoff sweep's trigger)."""

    done = False
    finish_reason = None
    parked = False


class SubprocessReplica:
    """One replica as a SEPARATE PROCESS (`python -m pytorchdistributed_
    tpu.serving.replica_worker`), spawned with the same env contract
    run.py gives training workers — RANK (the replica index),
    WORLD_SIZE, MASTER_ADDR/MASTER_PORT, PTD_HEARTBEAT_DIR /
    PTD_TELEMETRY_DIR / PTD_FAULTS pass-through — and driven over a
    line-JSON stdin/stdout protocol with AT MOST ONE op in flight.

    The async single-outstanding-op design is what makes hang detection
    honest: the router never blocks on a wedged worker — a step op's
    response simply fails to arrive, the progress watermark stalls, and
    the watchdog fires after ``hang_ticks`` router ticks, exactly like
    the in-process path. Death is immediate: process exit or pipe EOF
    raises ReplicaCrashed at the next interaction. Teardown forwards
    SIGTERM and escalates through run.py's ``kill_group`` — a drained
    router can never leave an orphan worker."""

    faults_in_worker = True
    #: router-installed ChaosSchedule (or None): consulted on every
    #: received line so wire faults hit the real recv path, not a mock
    wire_chaos = None
    #: router-installed event sink: ``on_wire_event(event, **row)`` —
    #: wire_fault / wire_slow / wire_retry / wire_timeout land in the
    #: router telemetry stream with the replica index stamped
    on_wire_event = None
    #: hard-timeout defaults per op (seconds); anything absent falls
    #: back to max(hang_grace_s, 30). Env overrides: WIRE_TIMEOUT_ENV
    #: globally, ``PTD_WIRE_TIMEOUT_<OP>_S`` per op.
    OP_TIMEOUTS_S = {"warmup": 600.0, "set_draft_params": 60.0,
                     "drain": 60.0}

    def __init__(self, index: int, spec: dict, *, world_size: int = 1,
                 env: dict | None = None, hang_grace_s: float = 10.0,
                 heartbeat_dir: str | None = None,
                 master_port: int | None = None):
        from pytorchdistributed_tpu.run import free_port

        self.index = index
        self.hang_grace_s = hang_grace_s
        self._mirrors: dict[int, object] = {}
        self._on_token: dict[int, object] = {}
        # the run.py liveness contract: the worker touches
        # rank<index> after every step's host sync; health() surfaces
        # the age next to the protocol-level progress watermark
        self.heartbeat_path = (
            os.path.join(heartbeat_dir, f"rank{index}")
            if heartbeat_dir else None)
        self.alive = True
        self._health: dict = {"alive": True, "progress": -1, "active": 0,
                              "queued": 0, "free_slots": 0,
                              "prefilling": 0, "num_slots": 1,
                              "occupancy": 0.0, "pool_free_frac": 1.0,
                              "ttft_ema_s": None, "sick": False}
        self._pending_op: str | None = None
        self._probe_result: bool | None = None
        # wire-protocol fault accounting (ISSUE 19): bad lines never
        # raise out of recv — they set the flag the router's health
        # sweep converts into a quarantine
        self.protocol_faults = 0
        self._protocol_fault = False
        self.wire_stats: dict[str, int] = collections.Counter()
        # session payloads demoted by the worker, awaiting the router's
        # store-persist sweep: [(sid, tenant, wire_payload), ...]
        self._demoted: list = []
        full_env = dict(os.environ)
        if env:
            full_env.update(env)
        full_env.update({
            "RANK": str(index), "LOCAL_RANK": str(index),
            "WORLD_SIZE": str(world_size),
            "MASTER_ADDR": "localhost",
            # ONE port shared by the whole worker fleet (the run.py
            # group contract): a future cross-replica rendezvous must
            # find every rank agreeing on it
            "MASTER_PORT": str(master_port if master_port is not None
                               else free_port()),
            "PTD_REPLICA_SPEC": json.dumps(spec),
        })
        if heartbeat_dir:
            from pytorchdistributed_tpu.runtime.heartbeat import (
                HEARTBEAT_DIR_ENV,
            )

            os.makedirs(heartbeat_dir, exist_ok=True)
            full_env[HEARTBEAT_DIR_ENV] = heartbeat_dir
        self.proc = subprocess.Popen(
            [sys.executable, "-m",
             "pytorchdistributed_tpu.serving.replica_worker"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            env=full_env, text=True, bufsize=1)

    # -- wire ---------------------------------------------------------

    def _send(self, op: dict) -> None:
        if not self.alive or self.proc.poll() is not None:
            self.alive = False
            raise ReplicaCrashed(f"replica {self.index}: worker exited "
                                 f"(code {self.proc.poll()})")
        try:
            self.proc.stdin.write(json.dumps(op) + "\n")
            self.proc.stdin.flush()
        except (BrokenPipeError, OSError) as e:
            self.alive = False
            raise ReplicaCrashed(
                f"replica {self.index}: pipe broke ({e})") from None
        self._pending_op = op["op"]
        self._last_sent = op["op"]

    def _try_recv(self, timeout: float = 0.0) -> dict | None:
        """Non-blocking (or bounded) read of the pending response; None
        when the worker hasn't answered yet — the router moves on and
        the watermark records the silence. A line that fails to parse
        is a PROTOCOL FAULT, not an exception: the flag is set, the
        line dropped, and the router's health sweep quarantines the
        replica through the ordinary clean-probe→canary path."""
        if self._pending_op is None:
            return None
        r, _, _ = select.select([self.proc.stdout], [], [], timeout)
        if not r:
            if self.proc.poll() is not None:
                self.alive = False
                raise ReplicaCrashed(
                    f"replica {self.index}: worker exited "
                    f"(code {self.proc.poll()})")
            return None
        line = self.proc.stdout.readline()
        if not line:
            self.alive = False
            raise ReplicaCrashed(f"replica {self.index}: EOF "
                                 f"(code {self.proc.poll()})")
        if self.wire_chaos is not None:
            line, fault = self.wire_chaos.mangle_recv(self.index, line)
            if fault is not None:
                self.wire_stats[fault] += 1
                if self.on_wire_event is not None:
                    self.on_wire_event("wire_fault", fault=fault,
                                       op=self._pending_op)
            if line is None:
                # wire_drop: the response is simply GONE. The op stays
                # pending — exactly what real message loss looks like —
                # and surfaces through wait_response's timeout or the
                # tick loop's progress watermark.
                return None
        op = self._pending_op
        self._pending_op = None
        try:
            return json.loads(line)
        except json.JSONDecodeError:
            self.protocol_faults += 1
            self._protocol_fault = True
            self.wire_stats["bad_lines"] += 1
            sys.stderr.write(
                f"[router] replica {self.index}: unparseable wire line "
                f"for op {op!r} ({len(line)} bytes) — protocol fault\n")
            return None

    def _op_timeout(self, op: str | None) -> float:
        """Hard response deadline for ``op``: per-op env override >
        global env override > per-op default > max(hang_grace_s, 30)."""
        if op:
            v = os.environ.get(f"PTD_WIRE_TIMEOUT_{op.upper()}_S")
            if v:
                return float(v)
        v = os.environ.get(WIRE_TIMEOUT_ENV)
        if v:
            return float(v)
        base = self.OP_TIMEOUTS_S.get(op or "")
        if base is None:
            return max(self.hang_grace_s, 30.0)
        return max(self.hang_grace_s, base)

    def wait_response(self, timeout: float | None = None, *,
                      op: str | None = None, retries: int = 1) -> dict:
        """Blocking receive for the synchronous phases (warmup, close,
        handoffs) where the caller legitimately waits — never used in
        the steady-state tick loop. ``timeout=None`` resolves the
        per-op policy (``_op_timeout``); crossing the soft deadline
        emits one ``wire_slow`` event (a DELAYED op is observable long
        before it times out); a hard timeout with the worker still
        alive grants ``retries`` extra window(s) (``wire_retry``)
        before giving up with WireFault (``wire_timeout``) — a torn
        line observed while waiting raises WireFault immediately."""
        op = op or self._pending_op
        if timeout is None:
            timeout = self._op_timeout(op)
        soft = float(os.environ.get(WIRE_SOFT_ENV, "5.0"))
        faults_before = self.protocol_faults
        start = time.perf_counter()
        deadline = start + timeout
        soft_fired = False
        retries_left = max(0, int(retries))
        while True:
            resp = self._try_recv(timeout=0.2)
            if self.protocol_faults > faults_before:
                raise WireFault(
                    f"replica {self.index}: protocol fault while "
                    f"waiting for {op!r}", kind="wire_protocol")
            if resp is not None:
                return resp
            now = time.perf_counter()
            if not soft_fired and now - start > soft:
                soft_fired = True
                if self.on_wire_event is not None:
                    self.on_wire_event("wire_slow", op=op,
                                       waited_s=round(now - start, 3))
            if now > deadline:
                if retries_left > 0 and self.proc.poll() is None:
                    retries_left -= 1
                    deadline = now + min(timeout, 5.0)
                    self.wire_stats["retries"] += 1
                    if self.on_wire_event is not None:
                        self.on_wire_event("wire_retry", op=op,
                                           waited_s=round(now - start, 3))
                    continue
                if self.on_wire_event is not None:
                    self.on_wire_event("wire_timeout", op=op,
                                       waited_s=round(now - start, 3))
                raise WireFault(
                    f"replica {self.index}: no response within "
                    f"{timeout}s (op {op})")

    # -- replica protocol ---------------------------------------------

    def warmup(self, prompt_lens=None, kv_stream: bool = True) -> None:
        self._send({"op": "warmup",
                    "prompt_lens": list(prompt_lens or []),
                    "kv_stream": bool(kv_stream)})
        # first warmup pays the worker's jax import + compiles; the
        # default 600 s hard deadline is env-tunable (WIRE_TIMEOUT_ENV /
        # PTD_WIRE_TIMEOUT_WARMUP_S)
        self._consume(self.wait_response(op="warmup"))

    def warmup_async(self, prompt_lens=None, kv_stream: bool = True
                     ) -> None:
        """Send the warmup op WITHOUT waiting — the respawn path
        (ISSUE 10): a replacement worker's startup (jax import +
        checkpoint restore + cached warmup) must not stall the router's
        tick loop. While ``_warming``, probe() reports un-ready, so the
        quarantine machine keeps the replica parked; the warmup reply
        is consumed by the probe path's receive whenever it lands."""
        self._warming = True
        self._send({"op": "warmup",
                    "prompt_lens": list(prompt_lens or []),
                    "kv_stream": bool(kv_stream)})

    def submit(self, rr: RouterRequest, *, generated, deadline_s,
               on_token, prefill_only: bool = False):
        self._drain_wire()
        op = {"op": "submit", "rid": rr.id,
              "prompt": rr.prompt.tolist(),
              "max_new_tokens": rr.max_new_tokens,
              "sampling": {
                  "temperature": rr.sampling.temperature,
                  "top_k": rr.sampling.top_k,
                  "top_p": rr.sampling.top_p,
                  "seed": rr.sampling.seed},
              "stop_ids": list(rr.stop_ids),
              "generated": list(generated or []),
              "deadline_s": deadline_s,
              "prefill_only": bool(prefill_only),
              "kv_window": rr.kv_window,
              "kv_sink": rr.kv_sink}
        # session identity rides only when set, keeping the off-wire
        # byte-identical to pre-session traffic
        if rr.session_id is not None:
            op["session_id"] = rr.session_id
            op["tenant"] = rr.tenant
        # origin submit + trace identity (ISSUE 17): unix-epoch and a
        # plain dict so the worker needs no shared clock or objects;
        # trace keys ride only when tracing minted a context, so the
        # off-wire is byte-identical to pre-ISSUE-17 traffic minus the
        # always-on origin stamp (the TTFT-e2e bugfix is not gated on
        # tracing)
        if rr.submit_time is not None:
            op["origin_t"] = _trace_to_unix(rr.submit_time)
        if rr.trace is not None:
            op["trace"] = rr.trace.to_wire()
        self._send(op)
        self._on_token[rr.id] = on_token
        m = _Mirror()
        self._mirrors[rr.id] = m
        return m

    def preempt(self, rr: RouterRequest) -> bool:
        """Synchronous preempt roundtrip (rare — admission pressure
        only, so the one-in-flight wire cost is acceptable, same as a
        KV handoff). The worker's reply is consumed HERE, not through
        ``_consume`` — an ok=False preempt must not be mistaken for a
        submit refusal and fail a perfectly live stream."""
        self._drain_wire()
        self._send({"op": "preempt", "rid": rr.id})
        resp = self.wait_response(op="preempt")
        self._pending_op = None
        if not resp.get("ok"):
            return False
        m = self._mirrors.pop(rr.id, None)
        if m is not None:
            m.done, m.finish_reason = True, "preempted"
        self._on_token.pop(rr.id, None)
        return True

    def set_draft_params(self, params=None, *, checkpoint=None,
                         step=None) -> dict:
        """Draft hot-swap over the wire (ISSUE 16): the payload is a
        CHECKPOINT PATH, never a weight tree — the worker restores it
        locally through the same manifest-verified path as its boot
        weights, and the engine's structure/shape check accepts or
        refuses. Synchronous roundtrip (rare, like a handoff); a
        refusal raises ValueError with the worker's reason."""
        if checkpoint is None:
            raise ValueError(
                "subprocess replicas take set_draft_params(checkpoint=...)"
                " — weight trees do not cross the wire")
        self._drain_wire()
        self._send({"op": "set_draft_params",
                    "checkpoint": str(checkpoint),
                    "step": step})
        resp = self.wait_response(op="set_draft_params")
        self._pending_op = None
        if resp.get("ok") is not True:
            raise ValueError(
                f"replica {self.index}: set_draft_params refused: "
                f"{resp.get('error')}")
        return {"draft_hash": resp.get("draft_hash"),
                "draft_swaps": int(resp.get("draft_swaps", 0))}

    # -- KV block stream (ISSUE 12) -----------------------------------
    # Handoffs are synchronous wire roundtrips by design: the payload
    # op and its reply must not interleave with step traffic (the
    # one-in-flight invariant), and a handoff is rare relative to
    # ticks. A wedged worker surfaces as TimeoutError — the caller's
    # dead-replica path, same as submit.

    def export_kv(self, rr: RouterRequest):
        self._drain_wire()
        self._send({"op": "export_kv", "rid": rr.id})
        resp = self.wait_response(op="export_kv")
        if resp.get("ok") is not True or not resp.get("payload"):
            raise ValueError(
                f"replica {self.index}: export_kv({rr.id}) refused: "
                f"{resp.get('error')}")
        self._mirrors.pop(rr.id, None)
        self._on_token.pop(rr.id, None)
        return kv_payload_from_wire(resp["payload"])

    def import_kv(self, rr: RouterRequest, payload, *, deadline_s,
                  on_token):
        self._drain_wire()
        self._send({"op": "import_kv", "rid": rr.id,
                    "deadline_s": deadline_s,
                    "payload": kv_payload_to_wire(payload)})
        resp = self.wait_response(op="import_kv")
        if resp.get("ok") is not True:
            return None  # no capacity / mismatch: resume-from-tokens
        m = _Mirror()
        self._mirrors[rr.id] = m
        self._on_token[rr.id] = on_token
        return m

    def export_prefix(self, tokens):
        self._drain_wire()
        self._send({"op": "export_prefix",
                    "tokens": [int(t) for t in tokens]})
        resp = self.wait_response(op="export_prefix")
        if resp.get("ok") is not True or not resp.get("payload"):
            return None
        return prefix_payload_from_wire(resp["payload"])

    def import_prefix(self, payload) -> int:
        self._drain_wire()
        self._send({"op": "import_prefix",
                    "payload": prefix_payload_to_wire(payload)})
        resp = self.wait_response(op="import_prefix")
        return int(resp.get("adopted", 0)) if resp.get("ok") else 0

    # -- persistent sessions (ISSUE 18) -------------------------------
    # Like handoffs, session pulls/seeds are synchronous roundtrips:
    # rare relative to ticks, and the payload must not interleave with
    # step traffic on the one-in-flight wire.

    def export_session(self, session_id: str):
        self._drain_wire()
        self._send({"op": "export_session", "session_id": session_id})
        resp = self.wait_response(op="export_session")
        if resp.get("ok") is not True or not resp.get("payload"):
            return None
        return kv_payload_from_wire(resp["payload"])

    def seed_session(self, payload) -> int:
        self._drain_wire()
        self._send({"op": "seed_session",
                    "payload": kv_payload_to_wire(payload)})
        resp = self.wait_response(op="seed_session")
        return int(resp.get("seeded", 0)) if resp.get("ok") else 0

    def take_demoted_sessions(self):
        """Drain session payloads the worker demoted (reported in step
        replies) — the router persists them into the store tiers."""
        out, self._demoted = self._demoted, []
        return [(sid, tenant, kv_payload_from_wire(wire))
                for sid, tenant, wire in out]

    def _drain_wire(self, timeout: float | None = None) -> None:
        """Consume the pending response (if any) before sending a new
        op — the one-in-flight invariant. Only submit/drain/close use
        it; the steady-state step path is fully non-blocking. The
        default bound is ``hang_grace_s``: a healthy worker answers in
        milliseconds, and a wedged one must not stall the whole router
        longer than the hang watchdog would have tolerated anyway (the
        TimeoutError surfaces as a dead-replica declaration)."""
        if self._pending_op is not None:
            resp = self.wait_response(
                self.hang_grace_s if timeout is None else timeout)
            self._consume(resp)

    def _consume(self, resp: dict) -> None:
        if resp.get("ok") is False and "rid" in resp:
            # the worker REFUSED the submit (validation error): the
            # request is terminal — redispatching it would only collect
            # the same refusal fleet-wide
            m = self._mirrors.pop(resp["rid"], None)
            if m is not None:
                m.done, m.finish_reason = True, "failed"
            self._on_token.pop(resp["rid"], None)
            return
        if "max_seq_len" in resp:
            self.reported_max_seq_len = int(resp["max_seq_len"])
            self._warming = False  # the async-warmup reply landed
        if resp.get("health"):
            self._health = resp["health"]
            self._health["alive"] = True
        for rid, tok in resp.get("delivered", []):
            cb = self._on_token.get(rid)
            if cb is not None:
                cb(rid, tok)
        for rid in resp.get("parked", []):
            m = self._mirrors.get(rid)
            if m is not None:
                m.parked = True
        for item in resp.get("demoted_sessions", []):
            self._demoted.append(tuple(item))
        for rid, reason in resp.get("finished", []):
            m = self._mirrors.pop(rid, None)
            if m is not None:
                m.done, m.finish_reason = True, reason
            # drop the per-request closure too, or a long-lived worker
            # retains every RouterRequest it ever served
            self._on_token.pop(rid, None)
        if "finite" in resp:
            self._probe_result = bool(resp["finite"])

    def step(self) -> None:
        """One async protocol turn: collect whatever the worker answered
        since last tick, then (if the wire is idle) send the next step
        op. No response → no progress recorded → the hang watchdog's
        evidence accumulates."""
        resp = self._try_recv()
        if resp is not None:
            self._consume(resp)
        if self._pending_op is None:
            self._send({"op": "step"})

    def health(self) -> dict:
        h = dict(self._health)
        h["alive"] = self.alive
        if self.heartbeat_path is not None:
            from pytorchdistributed_tpu.runtime.heartbeat import (
                last_beat_age,
            )

            h["heartbeat_age_s"] = last_beat_age(self.heartbeat_path)
        return h

    def probe(self, exclusive: bool = False) -> bool:
        """Params-finite probe over the wire. Answered asynchronously:
        returns the LAST verdict (optimistically True before the first
        answer arrives) and keeps the pipeline moving. RECEIVE before
        deciding to send: the steady-state loop always leaves a step op
        pending, so a send-first probe would be skipped every single
        time and a NaN'd worker would never be caught. Never send two
        probes back to back: at health_every=1 that would monopolize
        the one-in-flight wire and STARVE the step ops — probe and step
        alternate instead. ``exclusive=True`` (a QUARANTINED replica,
        which is never stepped, so probes are the only traffic) lifts
        the alternation."""
        resp = self._try_recv()
        if resp is not None:
            self._consume(resp)
        if getattr(self, "_warming", False):
            # async-respawn startup in flight: not ready is the honest
            # verdict (the optimistic True below would let the rejoin
            # streak run out before the worker can even serve)
            return False
        if (self._pending_op is None
                and (exclusive
                     or getattr(self, "_last_sent", None) != "probe")):
            self._send({"op": "probe"})
        return self._probe_result if self._probe_result is not None else True

    def apply_fault(self, kind: str, ms: float = 100.0) -> None:
        """One-shot tick-targeted faults ride PTD_FAULTS into the
        worker itself (it runs the injector against its own RANK), but
        RATE-BASED chaos decisions live router-side (the ChaosSchedule
        is seeded once, in one process) — so the router plays the
        cluster: crash kills the process, hang SIGSTOPs it (alive,
        silent — the watchdog's problem), nan/slow ride a wire op the
        worker applies to its own engine."""
        import signal as _signal

        if kind == "replica_crash":
            self.proc.kill()
        elif kind == "replica_hang":
            try:
                os.kill(self.proc.pid, _signal.SIGSTOP)
            except (OSError, ProcessLookupError):
                pass
        elif kind in ("replica_nan", "replica_slow"):
            try:
                self._drain_wire()
                self._send({"op": "inject", "kind": kind,
                            "ms": float(ms)})
                self.wait_response(op="inject")
                self._pending_op = None
            except (ReplicaCrashed, TimeoutError):
                pass  # the health sweep owns the diagnosis

    def quarantine_reset(self) -> None:
        try:
            self._drain_wire()
            self._send({"op": "drain"})
            self._consume(self.wait_response(op="drain"))
        except WireFault:
            # the wire hiccuped DURING the reset: the replica is
            # already quarantined — the probe streak decides its fate,
            # no need to escalate a torn line into a death sentence
            pass
        except (ReplicaCrashed, TimeoutError):
            self.alive = False

    def drain(self) -> list:
        self.quarantine_reset()
        return []

    def close(self, grace: float = 10.0) -> None:
        """Graceful protocol close, then the run.py teardown escalation
        (SIGTERM → SIGCONT → SIGKILL after grace) — no orphans, even if
        the worker is wedged or SIGSTOPped."""
        from pytorchdistributed_tpu.run import kill_group

        if self.alive and self.proc.poll() is None:
            try:
                self._drain_wire(timeout=5.0)
                self._send({"op": "close"})
            except (ReplicaCrashed, TimeoutError):
                pass
        kill_group([self.proc], grace=grace)
        self.alive = False
        for pipe in (self.proc.stdin, self.proc.stdout):
            try:
                pipe.close()
            except OSError:
                pass


class ReplicaRouter:
    """The health-checked, failover-capable front of N serving replicas.

    Construction (pick one):
      * ``ReplicaRouter(model, params, replicas=N, engine_kwargs={...})``
        — N in-process ServingEngines over shared weights (they also
        share the jit cache: N replicas compile once);
      * ``ReplicaRouter(factories=[...])`` — explicit per-replica
        engine factories (different pool sizes, meshes, ...);
      * ``ReplicaRouter(workers=[spec, ...])`` — subprocess replicas:
        each spec is a replica_worker model/engine description, each
        worker is launched under the run.py env contract.

    Knobs:
      roles: one of ROLE_PREFILL / ROLE_DECODE / ROLE_BOTH per replica
        (ISSUE 12) — None means all ``both`` (the colocated default).
        With any split role, new requests dispatch to prefill-capable
        replicas as ``prefill_only`` admissions; the handoff sweep
        streams each parked request's KV blocks to the decode-capable
        replica the health scorer picks, which activates the stream
        mid-flight (bitwise-equal to colocated — the blocks carry
        exact K/V). Any handoff failure falls back to resume-from-
        tokens redispatch, so disaggregation can only cost a re-
        prefill, never a stream. Independently of roles, the router
        keeps a fleet-wide prefix index over every replica's published
        radix frontier: the dispatcher steers prefix-sharing requests
        to the deepest match, shipping the owner's cached blocks to
        the chosen replica when they differ.
      max_queue: router admission bound — a submit arriving with this
        many requests already queued is SHED immediately
        (``finish_reason="shed"``): bounded latency for everyone
        admitted beats unbounded latency for everyone.
      max_retries: redispatches a single request may consume before it
        is failed (``finish_reason="failed"``) — the retry budget.
      retry_policy: faults/retry.py backoff between a request's
        redispatches (default ROUTER_RETRY: ms-scale, exponential,
        jittered).
      hang_ticks: consecutive router ticks a replica may hold work
        without moving its progress watermark before it is declared
        hung — the watchdog bound (detection latency ≤ hang_ticks
        ticks, asserted in the chaos suite).
      health_every: params-finite probe cadence in ticks (the probe is
        one compiled scalar reduction; every tick would double the
        tick's device dispatches for tiny models).
      rejoin_after: consecutive CLEAN probes a quarantined replica
        needs before the warmup canary + re-admission.
      respawn_budget: relaunches each DEAD replica may consume
        (ISSUE 10; default the PTD_ROUTER_RESPAWN env, else 0 = DEAD
        is forever). A crashed/hung replica is rebuilt — subprocess
        workers relaunch under the same spec/env contract (a
        ``"checkpoint"`` + ``"compile_cache"`` spec makes that a
        load-bound-seconds restart), in-process replicas re-run their
        engine factory — then rejoins through the EXISTING
        quarantine → clean-probe → canary path, so a recovered
        replica proves itself before real traffic returns. Its
        former streams were already failed over; respawn restores
        CAPACITY, turning a crash into a transient instead of a
        permanent fleet shrink.
      respawn_policy: faults/retry.py backoff between one replica's
        relaunch attempts (default RESPAWN_RETRY: exponential,
        jittered, capped at seconds).
      respawn_warmup_s: startup bound for a respawned subprocess
        worker's ASYNC warmup — past it the replacement is declared
        hung and the next budgeted attempt proceeds (mirrors the
        synchronous warmup()'s 600 s response timeout).
      faults: a FaultInjector, None to disable chaos entirely, or
        "auto" (default: the process-global ``faults.active()`` —
        the PTD_FAULTS contract).
      telemetry / telemetry_dir: RouterTelemetry sink (per-replica
        rows + event rows + close-time summary).
      seed: the jitter RNG for redispatch backoff (deterministic
        schedules for the chaos suite).
    """

    def __init__(self, model=None, params=None, *, replicas: int = 2,
                 engine_kwargs: dict | None = None, factories=None,
                 workers=None, warmup_lens=None, roles=None,
                 max_queue: int | None = None, max_retries: int = 2,
                 retry_policy: RetryPolicy = ROUTER_RETRY,
                 hang_ticks: int = 8, health_every: int = 4,
                 rejoin_after: int = 3, max_pending: int = 1,
                 respawn_budget: int | None = None,
                 respawn_policy: RetryPolicy = RESPAWN_RETRY,
                 respawn_warmup_s: float = 600.0,
                 faults="auto", telemetry: RouterTelemetry | None = None,
                 telemetry_dir=None, sample_every: int = 1,
                 tenants=None, admission=None,
                 preempt_every: int = 8, seed: int = 0,
                 trace="auto", slo_ttft_s: float | None = None,
                 session_store=None):
        self.warmup_lens = tuple(warmup_lens) if warmup_lens else None
        # distributed request tracing (ISSUE 17): OFF unless asked —
        # trace=True (needs telemetry_dir for the files), a
        # RequestTracer instance, or the default "auto" which honors
        # the PTD_TRACE env contract (so subprocess fleets flip one
        # env var and every worker's tracer comes up with the router's).
        # In-process engines SHARE this tracer (one process, one file);
        # subprocess workers build their own per-RANK one from the env.
        if isinstance(trace, RequestTracer):
            self.trace = trace
        elif trace is True:
            if telemetry_dir is None:
                raise ValueError(
                    "trace=True needs telemetry_dir= — the per-rank "
                    "trace_rank*.jsonl files land there")
            self.trace = RequestTracer(
                telemetry_dir, rank="router",
                **({} if slo_ttft_s is None
                   else {"slo_ttft_s": slo_ttft_s}))
        elif trace == "auto" and telemetry_dir is not None \
                and os.environ.get("PTD_TRACE", "0").lower() in (
                    "1", "true", "yes", "on"):
            self.trace = RequestTracer(
                telemetry_dir, rank="router",
                **({} if slo_ttft_s is None
                   else {"slo_ttft_s": slo_ttft_s}))
        else:
            self.trace = None
        self._hb_dir = None
        self._worker_specs = None
        self._worker_port = None
        self._worker_env = None
        self._factory_fn = None
        if workers is not None:
            import tempfile

            from pytorchdistributed_tpu.run import free_port

            # one liveness dir + ONE master port for the worker fleet
            # (the run.py group env contract); dir removed at close().
            # spec list + port kept: respawn relaunches a DEAD worker
            # under the exact same contract
            self._hb_dir = tempfile.mkdtemp(prefix="ptd_router_hb_")
            port = free_port()
            self._worker_specs = list(workers)
            # scale-up template: a new replica index i reuses spec
            # i % len(base) — homogeneous fleets (the common case) just
            # clone spec 0
            self._base_specs = list(workers)
            self._worker_port = port
            # a programmatic trace=True must reach the workers too —
            # export the same env contract the "auto" path reads, so
            # every worker's RequestTracer.from_env comes up
            if self.trace is not None:
                self._worker_env = {
                    "PTD_TRACE": "1",
                    TELEMETRY_DIR_ENV: self.trace.run_dir}
            self._replicas = [
                SubprocessReplica(i, spec, world_size=len(workers),
                                  heartbeat_dir=self._hb_dir,
                                  master_port=port,
                                  env=self._worker_env)
                for i, spec in enumerate(workers)]
            self.max_seq_len = min(
                int(s.get("max_seq_len",
                          s.get("overrides", {}).get("max_seq_len",
                                                     1 << 30)))
                for s in workers)
        else:
            if factories is None:
                if model is None or params is None:
                    raise ValueError(
                        "pass (model, params), factories=, or workers=")
                kw = dict(engine_kwargs or {})
                # with a telemetry_dir, each engine gets its own
                # ServingTelemetry at rank=replica-index, so the
                # serve_metrics/span files land per replica (the report
                # CLI's serving table then reads as a per-replica
                # table) instead of being silently dropped
                wire_tele = (telemetry_dir is not None
                             and "telemetry" not in kw
                             and "telemetry_dir" not in kw)
                # in-process engines emit request spans through the
                # ROUTER's tracer (same process, same clock, one file)
                wire_trace = self.trace is not None and "trace" not in kw

                def make_factory(i):
                    def factory():
                        ekw = dict(kw)
                        if wire_tele:
                            from pytorchdistributed_tpu.serving.telemetry \
                                import ServingTelemetry

                            ekw["telemetry"] = ServingTelemetry(
                                telemetry_dir, rank=i)
                        if wire_trace:
                            ekw["trace"] = self.trace
                        return ServingEngine(model, params, **ekw)
                    return factory

                factories = [make_factory(i) for i in range(replicas)]
                self._factory_fn = make_factory
            else:
                factories = list(factories)
                self._factory_fn = (
                    lambda i, fs=factories: fs[i % len(fs)])
            self._replicas = [
                InProcessReplica(i, f, warmup_lens=self.warmup_lens)
                for i, f in enumerate(factories)]
            self.max_seq_len = min(
                r.engine.cfg.max_seq_len for r in self._replicas)
        if not self._replicas:
            raise ValueError("need at least one replica")
        if roles is None:
            roles = [ROLE_BOTH] * len(self._replicas)
        roles = list(roles)
        if len(roles) != len(self._replicas):
            raise ValueError(
                f"roles has {len(roles)} entries for "
                f"{len(self._replicas)} replicas")
        for role in roles:
            if role not in ROLES:
                raise ValueError(f"unknown role {role!r} (want one of "
                                 f"{ROLES})")
        self._roles = roles
        self._disagg = any(role != ROLE_BOTH for role in roles)
        if self._disagg and not any(
                role in (ROLE_DECODE, ROLE_BOTH) for role in roles):
            raise ValueError(
                "a disaggregated topology needs at least one decode-"
                "capable replica (role 'decode' or 'both') to receive "
                "KV handoffs")
        # the fleet-wide prefix index (ISSUE 12): every replica's
        # published radix frontier, refreshed from health snapshots
        self._prefix_index = FleetPrefixIndex()
        # the fleet-wide session index (ISSUE 18): session → owning
        # replica, refreshed from the same health snapshots; with a
        # SessionStore attached, demoted sessions flow into the host-
        # DRAM/disk tiers and reattaching turns are pulled back up
        self._session_index = FleetSessionIndex()
        self.session_store = session_store
        self.max_queue = max_queue
        self.max_retries = max_retries
        self.retry_policy = retry_policy
        self.hang_ticks = max(1, hang_ticks)
        self.health_every = max(1, health_every)
        self.rejoin_after = max(1, rejoin_after)
        self.max_pending = max(0, max_pending)
        if respawn_budget is None:
            respawn_budget = int(os.environ.get(ROUTER_RESPAWN_ENV, "0"))
        self.respawn_budget = max(0, respawn_budget)
        self.respawn_policy = respawn_policy
        self.respawn_warmup_s = respawn_warmup_s
        self._respawns = [0 for _ in self._replicas]
        self._respawn_eligible = [0.0 for _ in self._replicas]
        self._warming_deadline = [0.0 for _ in self._replicas]
        # "auto" = the process-global PTD_FAULTS contract; None = chaos
        # explicitly off (bench baseline legs); or a FaultInjector
        self._faults = (faults_inject.active() if faults == "auto"
                        else faults)
        if (self._faults is not None
                and not hasattr(self._faults, "mangle_recv")):
            # a plain injector whose plan carries wire or rate/period
            # specs needs the ChaosSchedule machinery — upgrade in
            # place so `PTD_FAULTS="wire_torn@rate=0.1" just works
            plan = getattr(self._faults, "plan", None)
            if plan is not None and any(
                    s.kind in faults_inject._WIRE_KINDS
                    or s.rate is not None or s.period is not None
                    for s in plan.specs):
                from pytorchdistributed_tpu.faults.chaos import (
                    ChaosSchedule,
                )

                self._faults = ChaosSchedule(
                    plan, seed=seed, rank=self._faults.rank,
                    state_dir=self._faults.state_dir,
                    events=self._faults.events)
        self._rng = random.Random(seed)
        if telemetry is None:
            # no dir -> RING-ONLY telemetry: zero files, but the signal
            # rings / recent-events the autoscaler consumes always exist
            telemetry = RouterTelemetry(telemetry_dir)
        self.telemetry = telemetry
        self.sample_every = max(1, sample_every)
        # multi-tenant admission (ISSUE 15): when tenants/admission is
        # given, the router queue IS the AdmissionController — it speaks
        # the deque protocol (append/appendleft/popleft/remove/iter), so
        # every existing queue path (dispatch, failover requeue,
        # deadline expiry, drain) runs unchanged, but popleft order is
        # priority-tiered weighted deficit round-robin and submit goes
        # through offer()'s rate caps + weighted shedding
        self._admission = None
        if admission is not None or tenants:
            from pytorchdistributed_tpu.serving.admission import (
                AdmissionController,
            )

            if admission is None:
                admission = AdmissionController(tenants,
                                                max_queue=max_queue)
            self._admission = admission
            self._queue = admission
        else:
            self._queue: collections.deque[RouterRequest] = \
                collections.deque()
        self.preempt_every = max(1, preempt_every)
        self._last_preempt_tick = -10**9
        self._retiring: set[int] = set()
        self._first_token_t: dict[int, float] = {}
        self._last_signal_counts = (0, 0)
        self._assigned: list[dict[int, RouterRequest]] = [
            {} for _ in self._replicas]
        self._status = [HEALTHY for _ in self._replicas]
        self._last_progress = [None for _ in self._replicas]
        self._last_progress_t = [time.perf_counter()
                                 for _ in self._replicas]
        self._stale = [0 for _ in self._replicas]
        self._clean_probes = [0 for _ in self._replicas]
        self._health: list[dict] = [r.health() for r in self._replicas]
        self._placements = [0 for _ in self._replicas]
        self._ticks = 0
        self._draining = False
        # per-replica draft identity after a hot-swap (ISSUE 16):
        # {index: {"draft_hash", "draft_swaps"}} — survives reset_stats
        # (identity is state, not a counter)
        self._draft_info: dict[int, dict] = {}
        self._recovering: list[dict] = []
        self._occ_sum = [0.0 for _ in self._replicas]
        self._occ_n = [0 for _ in self._replicas]
        for r in self._replicas:
            self._wire_hooks(r)
        self.reset_stats()

    # ------------------------------------------------------------------
    # submission + shedding

    def submit(self, prompt, *, max_new_tokens: int,
               sampling: SamplingParams | None = None, stop_ids=None,
               on_token=None, deadline_s: float | None = None,
               tenant: str | None = None, priority: int = 0,
               kv_window: int | None = None,
               kv_sink: int | None = None,
               session_id: str | None = None) -> RouterRequest:
        """Queue one request with the router (dispatch to a replica
        happens inside step(), against fresh health snapshots). Returns
        the durable RouterRequest handle — ``handle.tokens`` is the
        client stream and survives any number of failovers.

        Admission control: when the router queue already holds
        ``max_queue`` requests, the request is REJECTED here —
        ``done=True, finish_reason="shed"``, zero tokens — instead of
        joining an unbounded line. Shedding at submit is the load-
        shedding contract: overload costs the shed request one cheap
        refusal, not every admitted request its latency SLO."""
        from pytorchdistributed_tpu.inference import stop_ids_tuple

        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("prompt must have at least one token")
        if max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        if prompt.size + max_new_tokens > self.max_seq_len:
            raise ValueError(
                f"prompt_len {prompt.size} + max_new_tokens "
                f"{max_new_tokens} exceeds max_seq_len "
                f"{self.max_seq_len}")
        if kv_window is not None and kv_window < 1:
            raise ValueError(f"kv_window must be >= 1, got {kv_window}")
        if kv_sink is not None and kv_sink < 0:
            raise ValueError(f"kv_sink must be >= 0, got {kv_sink}")
        if priority < 0:
            raise ValueError(f"priority must be >= 0, got {priority}")
        if session_id is not None:
            from pytorchdistributed_tpu.serving.sessions import (
                session_id_ok,
            )

            if not session_id_ok(session_id):
                raise ValueError(
                    f"malformed session_id {session_id!r} (want "
                    f"[A-Za-z0-9][A-Za-z0-9._:-]*, <= 128 chars)")
        rr = RouterRequest(prompt, max_new_tokens,
                           sampling or SamplingParams(),
                           stop_ids_tuple(stop_ids), on_token,
                           deadline_s=deadline_s, tenant=tenant,
                           priority=priority, kv_window=kv_window,
                           kv_sink=kv_sink, session_id=session_id)
        rr.submit_time = time.perf_counter()
        if self.trace is not None:
            # mint the request's fleet-wide trace identity here — the
            # single origin every later emitter (admission, engines on
            # any replica, the handoff wire) parents to
            rr.trace = self.trace.new_trace()
            rr._trace_enq_t = rr.submit_time
        self._stats["submitted"] += 1
        self._tenant_stats(rr.tenant)["submitted"] += 1
        if self._draining:
            self._finish(rr, "drained")
            return rr
        if (self.max_queue is not None
                and len(self._queue) >= self.max_queue):
            # last look before refusing: place whatever the replicas
            # can already hold, so the bound sheds on CAPACITY, not on
            # how recently the caller interleaved a step()
            self._dispatch()
        if self._admission is not None:
            # weighted shedding: offer() admits, rate-refuses, or —
            # when the global bound is hit — picks the victim from the
            # tenant FURTHEST OVER its weight share (the arrival
            # itself when its own tenant is the worst offender). A
            # compliant tenant's requests are untouchable.
            victim = self._queue.offer(rr)
            if victim is not None:
                self._stats["shed_requests"] += 1
                self._event("shed", request=victim.id,
                            tenant=victim.tenant,
                            queued=len(self._queue))
                self._finish(victim, "shed")
            return rr
        if (self.max_queue is not None
                and len(self._queue) >= self.max_queue):
            self._stats["shed_requests"] += 1
            self._event("shed", request=rr.id, tenant=rr.tenant,
                        queued=len(self._queue))
            self._finish(rr, "shed")
            return rr
        self._queue.append(rr)
        return rr

    # ------------------------------------------------------------------
    # the router loop

    def step(self) -> dict:
        """One router iteration:

          1. consult the fault injector (chaos schedule) per replica;
          2. refresh health snapshots; run the hang watchdog and the
             sick-probe/quarantine/rejoin state machine;
          3. dispatch queued requests to the least-loaded replicas
             with room;
          4. step every healthy replica one engine step (a crash here
             is caught and becomes a failover);
          5. reap finished requests and expired router-queue deadlines.
        """
        if self._draining:
            self.drain()
            return self._step_stats(0)
        self._ticks += 1
        # 1. chaos schedule. One-shot tick specs: in-process replicas
        # only (subprocess workers fire the injector against their own
        # RANK — consulting it here too would consume the one-shot
        # marker and log an injection that never happened). RATE-BASED
        # schedules (ChaosSchedule) are consulted for EVERY replica —
        # their seeded decisions live router-side, and the router
        # applies them (kill/SIGSTOP/wire op) playing the cluster —
        # with ``rate_only`` guarding subprocess one-shots.
        if self._faults is not None:
            rate_based = getattr(self._faults, "rate_based", False)
            for r in self._replicas:
                if self._status[r.index] in (DEAD, REMOVED):
                    continue
                in_worker = getattr(r, "faults_in_worker", False)
                if in_worker and not rate_based:
                    continue
                kind = (self._faults.on_serving_tick(
                            self._ticks, r.index, rate_only=True)
                        if in_worker else
                        self._faults.on_serving_tick(self._ticks,
                                                     r.index))
                if kind:
                    spec = getattr(self._faults, "last_fired", None)
                    self._stats["faults_injected"] += 1
                    self._event("fault_injected", replica=r.index,
                                fault=kind,
                                spec=(spec.describe() if spec
                                      else kind))
                    try:
                        r.apply_fault(kind, ms=(spec.ms if spec
                                                else 100.0))
                    except (ReplicaCrashed, TimeoutError):
                        self._declare_dead(r, "crashed")
        # 2. health + watchdog + quarantine machine
        self._check_health()
        # 2b. respawn DEAD replicas with budget left (ISSUE 10) —
        # recovered capacity rejoins through the quarantine machine
        self._maybe_respawn()
        # 3. dispatch
        dispatched = self._dispatch()
        # 3b. admission-pressure preemption: a starved compliant tenant
        # at the head of a saturated fleet may evict an over-budget
        # tenant's newest stream (losslessly — preempt-requeue)
        self._maybe_preempt()
        # 4. step replicas — DRAINING ones too: their resident streams
        # must finish before the tombstone
        for r in self._replicas:
            if self._status[r.index] not in (HEALTHY, DRAINING):
                continue
            try:
                r.step()
            except ReplicaCrashed:
                self._declare_dead(r, "crashed")
        # 4a. persist replica-demoted sessions into the store tiers
        # (ISSUE 18) — the engine's HBM budget pushed them out; the
        # store's DRAM/disk tiers keep them reattachable
        if self.session_store is not None:
            for r in self._replicas:
                if self._status[r.index] not in (HEALTHY, DRAINING):
                    continue
                try:
                    demoted = r.take_demoted_sessions()
                except (ReplicaCrashed, TimeoutError):
                    self._declare_dead(r, "crashed")
                    continue
                for sid, tenant, payload in demoted:
                    self.session_store.put(sid, payload, tenant=tenant)
                    self._session_index.discard(sid)
                    self._stats["session_demotes"] += 1
        # 4b. sweep parked prefill-role admissions onto decode-capable
        # replicas over the KV stream (ISSUE 12)
        self._handoffs()
        # 5. reap
        self._reap()
        self._expire_queued_deadlines()
        # 5b. finalize scale-downs: a DRAINING replica with nothing
        # resident closes and becomes a tombstone
        self._finalize_removals()
        if self._ticks % self.sample_every == 0:
            for r in self._replicas:
                if self._status[r.index] == REMOVED:
                    continue
                h = self._health[r.index]
                self.telemetry.replica(
                    tick=self._ticks, replica=r.index,
                    status=self._status[r.index],
                    role=self._roles[r.index],
                    active=h.get("active", 0), queued=h.get("queued", 0),
                    parked=h.get("parked", 0),
                    occupancy=round(h.get("occupancy", 0.0), 4),
                    progress=h.get("progress", -1))
        self._feed_signals()
        return self._step_stats(dispatched)

    def _feed_signals(self) -> None:
        """One sample per autoscaler signal per tick, into the
        telemetry rings — queue depth, mean healthy occupancy, fleet
        TTFT EMA, per-tick submitted/shed deltas (windowed shed RATE is
        computed ring-side), prefill backlog, healthy count."""
        healthy = [self._health[i] for i, s in enumerate(self._status)
                   if s == HEALTHY]
        occ = (sum(h.get("occupancy", 0.0) for h in healthy)
               / len(healthy)) if healthy else None
        emas = [h.get("ttft_ema_s") for h in healthy]
        emas = [e for e in emas if e]
        backlog = len(self._queue) + sum(
            h.get("prefilling", 0) + h.get("parked", 0) for h in healthy)
        sub, shed = (self._stats["submitted"],
                     self._stats["shed_requests"])
        dsub = sub - self._last_signal_counts[0]
        dshed = shed - self._last_signal_counts[1]
        self._last_signal_counts = (sub, shed)
        self.telemetry.signal(
            queue_depth=len(self._queue), occupancy=occ,
            ttft_ema_s=(sum(emas) / len(emas)) if emas else None,
            submitted=dsub, shed=dshed, prefill_backlog=backlog,
            healthy=sum(s == HEALTHY for s in self._status),
            in_flight=self.in_flight)

    def _step_stats(self, dispatched: int) -> dict:
        return {"tick": self._ticks, "dispatched": dispatched,
                "queued": len(self._queue),
                "in_flight": sum(len(a) for a in self._assigned),
                "healthy": sum(s == HEALTHY for s in self._status)}

    # -- health machine ------------------------------------------------

    def _check_health(self) -> None:
        for r in self._replicas:
            i = r.index
            if self._status[i] in (DEAD, REMOVED):
                continue
            try:
                h = r.health()
            except ReplicaCrashed:
                self._declare_dead(r, "crashed")
                continue
            self._health[i] = h
            if "prefix_frontier" in h:
                self._prefix_index.update(i, h["prefix_frontier"])
            if "session_frontier" in h:
                self._session_index.update(i, h["session_frontier"])
            if not h.get("alive", True):
                self._declare_dead(r, "crashed")
                continue
            # wire protocol fault (ISSUE 19): an unparseable line set
            # the replica's flag in _try_recv — classify it as SICK
            # (quarantine → clean-probe streak → canary rejoin, the
            # same path a NaN'd replica walks), never an uncaught raise
            if getattr(r, "_protocol_fault", False):
                r._protocol_fault = False
                self._stats["wire_faults"] += 1
                self._event("wire_fault_detected", replica=i,
                            bad_lines=getattr(r, "protocol_faults", 0))
                if self._status[i] == HEALTHY:
                    self._quarantine(r)
                    continue
                # already quarantined/draining: the torn line resets
                # the streak — rejoin must be earned on a clean wire
                self._clean_probes[i] = 0
            # DRAINING replicas keep the watchdog: a scale-down target
            # that hangs mid-drain must still be shot (its streams fail
            # over) instead of stranding them behind a tombstone-to-be
            if self._status[i] in (HEALTHY, DRAINING):
                self._occ_sum[i] += h.get("occupancy", 0.0)
                self._occ_n[i] += 1
                # hang watchdog: work assigned + watermark frozen for
                # hang_ticks ticks AND (async replicas) longer than the
                # replica's wall-clock grace — a fast-spinning idle
                # router must not out-run a healthy subprocess worker's
                # response latency
                now = time.perf_counter()
                prog = h.get("progress", -1)
                # a stream parked for KV handoff (or queued behind
                # parked slots) is waiting on a decode slot, not on this
                # replica's compiled step — only work the engine has
                # actually admitted freezes the watermark, or a
                # saturated decode fleet would get every prefill replica
                # shot as "hung" while its exports queue
                working = (h.get("active", 0)
                           + h.get("prefilling", 0)) > 0
                if (self._assigned[i] and working
                        and prog == self._last_progress[i]):
                    self._stale[i] += 1
                else:
                    self._stale[i] = 0
                    self._last_progress_t[i] = now
                self._last_progress[i] = prog
                if (self._stale[i] >= self.hang_ticks
                        and now - self._last_progress_t[i]
                        >= getattr(r, "hang_grace_s", 0.0)):
                    self._declare_dead(r, "hung")
                    continue
                # periodic sick probe (HEALTHY only: a DRAINING replica
                # is leaving regardless — quarantining it would erase
                # the scale-down marker, and its streams are minutes
                # from done; crash/hang detection still covers it)
                if (self._status[i] == HEALTHY
                        and self._ticks % self.health_every == 0):
                    try:
                        ok = r.probe()
                    except ReplicaCrashed:
                        self._declare_dead(r, "crashed")
                        continue
                    if not ok:
                        self._quarantine(r)
            elif self._status[i] == QUARANTINED:
                # a respawned worker still WARMING past its startup
                # bound is wedged (bad node, poisoned restore): the
                # sync warmup() path had wait_response(600) — the async
                # path must enforce the same bound, or the slot parks
                # forever with respawn budget unspent
                if (getattr(r, "_warming", False)
                        and 0 < self._warming_deadline[i]
                        < time.perf_counter()):
                    self._declare_dead(r, "hung")
                    continue
                try:
                    ok = r.probe(exclusive=True)
                except ReplicaCrashed:
                    self._declare_dead(r, "crashed")
                    continue
                self._clean_probes[i] = self._clean_probes[i] + 1 if ok \
                    else 0
                if self._clean_probes[i] >= self.rejoin_after:
                    self._rejoin(r)

    def _declare_dead(self, r, why: str) -> None:
        if self._status[r.index] == DEAD:
            return
        self._status[r.index] = DEAD
        self._prefix_index.remove(r.index)
        # resident sessions died with the replica: forget the ownership
        # claims so reattaches fall through to the store tiers
        self._session_index.remove(r.index)
        # a respawn reboots from the SPEC's draft (if any) — the swapped
        # identity died with the process
        self._draft_info.pop(r.index, None)
        self._stats["replicas_lost"] += 1
        if why == "hung":
            self._stats["hangs_detected"] += 1
        if self.respawn_budget:
            # arm the respawn gate: attempt k waits the policy's k-th
            # exponential delay, so a crash-looping replica burns its
            # budget slowly instead of spawn-storming
            self._respawn_eligible[r.index] = (
                time.perf_counter()
                + self.respawn_policy.delay(1 + self._respawns[r.index],
                                            self._rng))
        self._event("replica_dead", replica=r.index, why=why,
                    stale_ticks=self._stale[r.index])
        self._failover(r, why)

    # -- respawn (ISSUE 10) --------------------------------------------

    def _maybe_respawn(self) -> None:
        """Relaunch DEAD replicas that still have respawn budget and
        whose backoff gate has opened. A fresh replica enters
        QUARANTINED, not HEALTHY: it must earn its way back through the
        same clean-probe streak + warmup canary a NaN-recovered replica
        does — a respawn that comes up broken (corrupt checkpoint, bad
        node) costs probes, never traffic."""
        if not self.respawn_budget or self._draining:
            return
        now = time.perf_counter()
        for i, r in enumerate(self._replicas):
            if (self._status[i] != DEAD
                    or i in self._retiring  # scale-down target: stay down
                    or self._respawns[i] >= self.respawn_budget
                    or now < self._respawn_eligible[i]):
                continue
            self._respawns[i] += 1
            attempt = self._respawns[i]
            # arm the NEXT attempt's gate up front — a failed spawn
            # below must not retry on the very next tick
            self._respawn_eligible[i] = (
                now + self.respawn_policy.delay(1 + attempt, self._rng))
            self._dispose_corpse(r)
            fresh = None
            try:
                fresh = self._build_replacement(r)
                if isinstance(fresh, SubprocessReplica):
                    # NON-blocking: the replacement's startup (jax
                    # import + restore + warmup) runs while the router
                    # keeps ticking the healthy replicas; probe()
                    # reports un-ready until the warmup reply lands,
                    # so the quarantine machine holds it parked —
                    # bounded by respawn_warmup_s (checked in
                    # _check_health), or a wedged startup would park
                    # the slot forever
                    fresh.warmup_async(self.warmup_lens)
                    self._warming_deadline[i] = (
                        time.perf_counter() + self.respawn_warmup_s)
                else:
                    # in-process engines share the router's thread by
                    # construction; their warmup is the (cached) fast
                    # path and cannot be deferred off-thread
                    fresh.warmup(self.warmup_lens)
            except Exception as e:  # noqa: BLE001 — spawn is best-effort
                if fresh is not None:
                    try:  # a half-spawned worker must not linger
                        fresh.close()
                    except Exception:  # noqa: BLE001
                        pass
                self._stats["respawn_failures"] += 1
                self._event("respawn_failed", replica=i, attempt=attempt,
                            error=f"{type(e).__name__}: {e}"[:200])
                if attempt >= self.respawn_budget:
                    self._event("respawn_exhausted", replica=i,
                                attempts=attempt)
                continue
            self._replicas[i] = fresh
            self._status[i] = QUARANTINED
            self._clean_probes[i] = 0
            self._stale[i] = 0
            self._last_progress[i] = None
            self._last_progress_t[i] = time.perf_counter()
            try:
                self._health[i] = fresh.health()
            except ReplicaCrashed:
                self._declare_dead(fresh, "crashed")
                continue
            self._stats["respawns"] += 1
            self._event("respawn", replica=i, attempt=attempt)

    def _dispose_corpse(self, r) -> None:
        """Tear down a DEAD replica without the graceful-close protocol
        (it is dead — there is nobody to drain) and with a SHORT
        kill_group grace, so reclaiming a wedged corpse costs the tick
        loop ~a second, not the full shutdown escalation."""
        try:
            if isinstance(r, SubprocessReplica):
                from pytorchdistributed_tpu.run import kill_group

                kill_group([r.proc], grace=1.0)
                r.alive = False
                for pipe in (r.proc.stdin, r.proc.stdout):
                    try:
                        pipe.close()
                    except OSError:
                        pass
            else:
                r.close()
        except Exception:  # noqa: BLE001 — the corpse can't block us
            pass

    def _build_replacement(self, r):
        if isinstance(r, SubprocessReplica):
            fresh = SubprocessReplica(
                r.index, self._worker_specs[r.index],
                world_size=len(self._replicas),
                heartbeat_dir=self._hb_dir,
                master_port=self._worker_port,
                env=self._worker_env)
            self._wire_hooks(fresh)
            return fresh
        if isinstance(r, InProcessReplica):
            return InProcessReplica(r.index, r._factory,
                                    warmup_lens=r.warmup_lens)
        raise TypeError(f"cannot respawn replica type {type(r).__name__}")

    def _wire_hooks(self, r) -> None:
        """Install the wire-fault surface on a subprocess replica
        (fresh fleet, respawn and scale-up alike): the ChaosSchedule
        mangler when one is active, and the event sink that lands
        wire_fault/wire_slow/wire_retry/wire_timeout rows in router
        telemetry with the replica index stamped."""
        if not isinstance(r, SubprocessReplica):
            return
        if (self._faults is not None
                and hasattr(self._faults, "mangle_recv")):
            r.wire_chaos = self._faults
        r.on_wire_event = (
            lambda ev, _i=r.index, **row: self._event(
                ev, replica=_i, **row))

    def _fleet_unrecoverable(self) -> bool:
        """All replicas DEAD *and* no respawn can ever bring one back —
        the only state where waiting on the router is hopeless."""
        if any(s not in (DEAD, REMOVED) for s in self._status):
            return False
        if all(s == REMOVED for s in self._status):
            return True   # fully scaled away: nothing respawns a tombstone
        if not self.respawn_budget:
            return True
        return all(n >= self.respawn_budget or i in self._retiring
                   for i, n in enumerate(self._respawns)
                   if self._status[i] == DEAD)

    def _quarantine(self, r) -> None:
        """Sick (params non-finite): fail its streams over NOW — every
        token it would emit is garbage — then park it out of rotation,
        probing for recovery."""
        self._status[r.index] = QUARANTINED
        self._prefix_index.remove(r.index)
        # KV written under non-finite params is poison: drop ownership
        # AND discard any pending demoted-session payloads instead of
        # persisting them — a reattach must re-prefill, never resume
        # from a sick replica's blocks
        self._session_index.remove(r.index)
        self._clean_probes[r.index] = 0
        self._stats["quarantines"] += 1
        self._event("quarantine", replica=r.index)
        self._failover(r, "sick")
        try:
            r.quarantine_reset()
            r.take_demoted_sessions()
        except (ReplicaCrashed, TimeoutError):
            self._declare_dead(r, "crashed")

    def _rejoin(self, r) -> None:
        """Probe streak clean → warmup re-admission: run one canary
        request end-to-end on the replica (re-exercising prefill +
        tick on the repaired weights) before real traffic returns.
        In-process the canary is synchronous and cheap (the programs
        are already compiled — a rejoin costs zero recompiles)."""
        if isinstance(r, InProcessReplica):
            try:
                n = min(self.warmup_lens[0] if self.warmup_lens else 8,
                        self.max_seq_len - 2)
                canary = r.engine.submit(np.zeros(n, np.int32),
                                         max_new_tokens=2)
                r.engine.run_until_idle()
                if not canary.done or not r.probe():
                    self._clean_probes[r.index] = 0
                    return  # not actually ready — keep quarantined
            except ReplicaCrashed:
                self._declare_dead(r, "crashed")
                return
        self._status[r.index] = HEALTHY
        self._stale[r.index] = 0
        self._last_progress[r.index] = None
        self._last_progress_t[r.index] = time.perf_counter()
        self._stats["rejoins"] += 1
        self._event("rejoin", replica=r.index)

    # -- elastic scaling (ISSUE 15) ------------------------------------

    def add_replica(self, role: str = ROLE_BOTH) -> int:
        """Grow the fleet by one replica at a NEW index (tombstoned
        indices are never reused — the per-replica parallel lists are
        append-only, so every replica's counters and occupancy history
        survive into the summary).

        In-process replicas warm synchronously and join HEALTHY at
        once: they share the fleet's jit cache, so warmup is a cache
        hit — ZERO fresh compiles (the warm-join property the
        flash-crowd test pins). Subprocess replicas launch under the
        same spec/env contract as an ISSUE-10 respawn — checkpoint
        restore + persistent AOT compile cache — warm ASYNCHRONOUSLY
        and join through the quarantine -> clean-probe gauntlet,
        exactly like a recovered crash."""
        if role not in ROLES:
            raise ValueError(
                f"unknown role {role!r} (want one of {ROLES})")
        i = len(self._replicas)
        if self._worker_specs is not None:
            spec = self._base_specs[i % len(self._base_specs)]
            self._worker_specs.append(spec)
            fresh = SubprocessReplica(
                i, spec, world_size=i + 1, heartbeat_dir=self._hb_dir,
                master_port=self._worker_port,
                env=self._worker_env)
            self._wire_hooks(fresh)
        else:
            fresh = InProcessReplica(i, self._factory_fn(i),
                                     warmup_lens=self.warmup_lens)
        self._replicas.append(fresh)
        self._roles.append(role)
        self._assigned.append({})
        self._status.append(QUARANTINED)
        self._last_progress.append(None)
        self._last_progress_t.append(time.perf_counter())
        self._stale.append(0)
        self._clean_probes.append(0)
        self._health.append({"alive": True, "progress": -1})
        self._placements.append(0)
        self._respawns.append(0)
        self._respawn_eligible.append(0.0)
        self._warming_deadline.append(0.0)
        self._occ_sum.append(0.0)
        self._occ_n.append(0)
        self._disagg = any(x != ROLE_BOTH for x in self._roles)
        if isinstance(fresh, SubprocessReplica):
            fresh.warmup_async(self.warmup_lens)
            self._warming_deadline[i] = (time.perf_counter()
                                         + self.respawn_warmup_s)
        else:
            fresh.warmup(self.warmup_lens)
            self._status[i] = HEALTHY
            self._health[i] = fresh.health()
        self._stats["scale_ups"] += 1
        self._event("scale_up", replica=i, role=role,
                    mode=("async" if isinstance(fresh, SubprocessReplica)
                          else "warm"))
        return i

    def remove_replica(self, index: int | None = None,
                       role: str | None = None) -> int | None:
        """Begin a graceful scale-down: pick the least-loaded HEALTHY
        replica (optionally a specific ``index``, optionally matching
        ``role``), mark it DRAINING — it keeps stepping its resident
        streams (and handing off parked prefills) but admits nothing
        new, then closes into a REMOVED tombstone once empty. Returns
        the chosen index, or None when nothing can be spared: never
        the last healthy replica, and in a disaggregated fleet never
        the last healthy prefill- or decode-capable one."""
        healthy = [i for i, s in enumerate(self._status)
                   if s == HEALTHY]

        def sparable(i: int) -> bool:
            rest = [j for j in healthy if j != i]
            if not rest:
                return False
            if self._disagg:
                for caps in ((ROLE_DECODE, ROLE_BOTH),
                             (ROLE_PREFILL, ROLE_BOTH)):
                    if (self._roles[i] in caps
                            and not any(self._roles[j] in caps
                                        for j in rest)):
                        return False
            return True

        cands = [i for i in healthy
                 if (index is None or i == index)
                 and (role is None or self._roles[i] == role)
                 and sparable(i)]
        if not cands:
            return None
        # least resident work first; highest index breaks ties (LIFO
        # scale-down pairs with append-only scale-up)
        i = min(cands, key=lambda j: (
            len(self._assigned[j]),
            self._health[j].get("occupancy", 0.0), -j))
        self._status[i] = DRAINING
        self._retiring.add(i)
        self._prefix_index.remove(i)
        self._stats["scale_downs"] += 1
        self._event("scale_down", replica=i, role=self._roles[i],
                    resident=len(self._assigned[i]))
        return i

    def _persist_replica_sessions(self, r) -> None:
        """Demote-and-persist a replica's resident sessions before it
        goes away (close / scale-down tombstone): drain the engine —
        which pushes every parked session into its demote queue — then
        sweep the queue into the store tiers. Restart survival for the
        warm tier; best-effort (a wedged replica just loses its HBM
        tier and reattaches re-prefill)."""
        if self.session_store is None:
            return
        try:
            r.drain()
            demoted = r.take_demoted_sessions()
        except (ReplicaCrashed, TimeoutError):
            return
        for sid, tenant, payload in demoted:
            self.session_store.put(sid, payload, tenant=tenant)
            self._session_index.discard(sid)
            self._stats["session_demotes"] += 1

    def _finalize_removals(self) -> None:
        for i, s in enumerate(self._status):
            if s != DRAINING or self._assigned[i]:
                continue
            self._persist_replica_sessions(self._replicas[i])
            try:
                self._replicas[i].close()
            except Exception:  # noqa: BLE001 — the tombstone wins
                pass
            self._status[i] = REMOVED
            self._event("replica_removed", replica=i)

    def pool_state(self) -> dict[str, dict]:
        """Aggregate per-pool capacity view (the autoscaler's scaling
        input): one ``"fleet"`` pool colocated; separate ``"prefill"``
        and ``"decode"`` pools when disaggregated (ROLE_BOTH counts
        decode — it receives handoffs)."""
        def agg(idxs):
            idxs = list(idxs)
            healthy = [i for i in idxs if self._status[i] == HEALTHY]
            hs = [self._health[i] for i in healthy]
            return {
                "replicas": len(idxs),
                "healthy": len(healthy),
                "draining": sum(self._status[i] == DRAINING
                                for i in idxs),
                "quarantined": sum(self._status[i] == QUARANTINED
                                   for i in idxs),
                "dead": sum(self._status[i] == DEAD for i in idxs),
                "removed": sum(self._status[i] == REMOVED
                               for i in idxs),
                "occupancy": (sum(h.get("occupancy", 0.0) for h in hs)
                              / len(hs)) if hs else None,
                "free_slots": sum(h.get("free_slots", 0) for h in hs),
                "queued": sum(h.get("queued", 0) for h in hs),
                "prefilling": sum(h.get("prefilling", 0) for h in hs),
                "parked": sum(h.get("parked", 0) for h in hs),
            }

        if not self._disagg:
            return {"fleet": agg(range(len(self._replicas)))}
        return {
            "prefill": agg(i for i, ro in enumerate(self._roles)
                           if ro == ROLE_PREFILL),
            "decode": agg(i for i, ro in enumerate(self._roles)
                          if ro in (ROLE_DECODE, ROLE_BOTH)),
        }

    # -- admission-pressure preemption (ISSUE 15) ----------------------

    def _maybe_preempt(self) -> None:
        """When a COMPLIANT tenant's request heads the queue and the
        fleet is saturated, evict the newest active stream of the
        tenant furthest over its weight share — losslessly, over the
        engine's preempt-requeue path (the evicted stream resumes from
        its delivered tokens once capacity frees). Rate-limited to one
        eviction per ``preempt_every`` ticks: preemption pays a
        re-prefill, so it must relieve starvation, not thrash."""
        if self._admission is None or self._draining:
            return
        if self._ticks - self._last_preempt_tick < self.preempt_every:
            return
        starved = self._queue.starved_head()
        if starved is None:
            return
        # only under saturation: with room anywhere, plain dispatch
        # serves the starved head next tick
        for i, s in enumerate(self._status):
            if s != HEALTHY:
                continue
            h = self._health[i]
            load = (h.get("active", 0) + h.get("queued", 0)
                    + h.get("prefilling", 0) + h.get("parked", 0))
            if load < h.get("num_slots", 1) + self.max_pending:
                return
        over = self._queue.overages()
        best = None
        for i, s in enumerate(self._status):
            if s != HEALTHY:
                continue
            for rr in self._assigned[i].values():
                o = over.get(rr.tenant, 0.0)
                if o <= 0 or rr.tenant == starved.tenant:
                    continue
                key = (o, rr.id)   # worst overage; newest stream
                if best is None or key > best[0]:
                    best = (key, rr, i)
        if best is None:
            return
        _, rr, idx = best
        try:
            ok = self._replicas[idx].preempt(rr)
        except WireFault:
            # the wire mangled the preempt reply: the stream is still
            # resident and live — skip this round; the protocol-fault
            # sweep decides the replica's fate
            return
        except (ReplicaCrashed, TimeoutError):
            self._declare_dead(self._replicas[idx], "crashed")
            return
        if ok:
            self._last_preempt_tick = self._ticks
            self._stats["preemptions"] += 1
            self._event("preempt", request=rr.id, tenant=rr.tenant,
                        replica=idx, for_tenant=starved.tenant,
                        tokens_so_far=len(rr.tokens))

    # -- failover ------------------------------------------------------

    def _failover(self, r, why: str) -> None:
        """Redispatch every in-flight request of a lost replica. The
        RouterRequest carries prompt + sampling + seed + delivered
        tokens, so survivors resume the stream losslessly
        (submit(generated=...)); a retry budget caps how many deaths a
        single request may surf, and the backoff gate keeps a flapping
        fleet from a redispatch storm."""
        victims = list(self._assigned[r.index].values())
        self._assigned[r.index].clear()
        if not victims:
            self._stats["failovers"] += 1
            return
        now = time.perf_counter()
        self._stats["failovers"] += 1
        pending = set()
        for rr in reversed(victims):  # appendleft keeps arrival order
            if rr._handle is not None and getattr(rr._handle, "done",
                                                  False):
                # finished on the replica in its final moments, not yet
                # reaped — deliverable as-is, no redispatch needed
                self._finish(rr, rr._handle.finish_reason)
                continue
            rr._handle = None
            rr._replica = None
            rr.retries += 1
            if rr.retries > self.max_retries:
                self._event("retries_exhausted", request=rr.id,
                            retries=rr.retries)
                self._finish(rr, "failed")
                continue
            delay = self.retry_policy.delay(rr.retries, self._rng)
            rr._eligible_at = now + delay
            self._queue.appendleft(rr)
            pending.add(rr.id)
            self._stats["redispatched_requests"] += 1
            self._event("redispatch", request=rr.id, from_replica=r.index,
                        why=why, retries=rr.retries,
                        delay_ms=round(delay * 1e3, 3),
                        tokens_so_far=len(rr.tokens))
            if self.trace is not None and rr.trace is not None:
                # marker span: the failover edge itself; queue
                # residency restarts here, so the NEXT queue span
                # (and the backoff gap, as stall) attribute correctly
                self.trace.span(rr.trace, "redispatch", now, now,
                                from_replica=r.index, why=why,
                                retries=rr.retries)
                rr._trace_enq_t = now
        if pending:
            self._recovering.append(
                {"start": self._ticks, "start_t": now, "pending": pending})

    # -- dispatch ------------------------------------------------------

    def _replica_score(self, h: dict, mean_ttft: float | None) -> float:
        """Lower = less loaded. Occupancy and queue depth dominate;
        pool pressure breaks slot ties (a paged replica about to
        preempt is a worse home than one with headroom); the TTFT EMA
        nudges traffic away from a replica whose admissions have been
        slow (relative to the fleet, so the signal is scale-free)."""
        ns = max(1, h.get("num_slots", 1))
        score = (h.get("occupancy", 0.0)
                 + (h.get("queued", 0) + h.get("prefilling", 0)) / ns
                 + 0.5 * (1.0 - h.get("pool_free_frac", 1.0)))
        ema = h.get("ttft_ema_s")
        if ema is not None and mean_ttft:
            score += 0.25 * min(ema / mean_ttft, 2.0)
        return score

    def _prefix_chain(self, rr: RouterRequest) -> list[str]:
        """The request's prompt as a chained block-hash list, computed
        once and cached on the RouterRequest. Empty when no paged
        replica has published a block size yet (dense fleet, or first
        ticks before health snapshots arrive)."""
        chain = getattr(rr, "_hash_chain", None)
        if chain is not None:
            return chain
        bs = 0
        for h in self._health:
            if h.get("block_size"):
                bs = int(h["block_size"])
                break
        if not bs:
            return []   # not cached: block_size may appear next tick
        chain = block_hashes(np.asarray(rr.prompt), bs)
        rr._hash_chain = chain
        return chain

    def _maybe_ship_prefix(self, rr: RouterRequest, chain: list[str],
                           best) -> None:
        """Fleet-wide prefix reuse: if another healthy replica holds a
        deeper cached match for this prompt than the chosen target, ship
        the matched blocks over the KV stream so the prefix is prefilled
        once per fleet, not once per replica. Best-effort — any failure
        just means the target prefills locally."""
        eligible = {r.index for r in self._replicas
                    if self._status[r.index] == HEALTHY}
        owner, depth = self._prefix_index.best_match(chain,
                                                     eligible=eligible)
        if (owner is None or owner == best.index or depth < 1
                or self._prefix_index.match_depth(best.index,
                                                  chain) >= depth):
            return
        try:
            payload = self._replicas[owner].export_prefix(
                np.asarray(rr.prompt))
            if payload is None:
                return
            adopted = best.import_prefix(payload)
        except (ReplicaCrashed, TimeoutError):
            return  # health machinery will notice on its own
        if adopted:
            self._stats["prefix_ships"] += 1
            self._stats["kv_stream_bytes"] += payload.nbytes
            # optimistic: the target now holds these blocks — steer
            # follow-on siblings there before its next health refresh
            self._prefix_index.add(best.index, chain[:depth])
            self._event("prefix_ship", request=rr.id, owner=owner,
                        target=best.index, blocks=adopted, depth=depth)

    def _prepare_session(self, rr: RouterRequest, r) -> None:
        """Reattach plumbing before placement (ISSUE 18): make the
        session's KV resident on the TARGET replica so the submit rides
        an ordinary prefix hit. Tier order — already home (the index
        steered us to the owner: the engine adopts internally), pull
        from the owning replica over the wire, then the store's
        host-DRAM/disk tiers. Every decline falls through; when the
        session was KNOWN somewhere and still ends up re-prefilling,
        that's the LOUD lossless fallback (session_fallback event)."""
        sid = rr.session_id
        eligible = [i for i, s in enumerate(self._status)
                    if s in (HEALTHY, DRAINING)]
        owner = self._session_index.owner(sid, eligible)
        if owner == r.index:
            self._stats["session_reattach"]["hbm"] += 1
            self._event("session_reattach", session=sid, tier="hbm",
                        replica=r.index)
            return
        known = owner is not None or (
            self.session_store is not None
            and self.session_store.peek_tier(sid) is not None)
        payload, tier = None, "hbm"
        if owner is not None:
            try:
                payload = self._replicas[owner].export_session(sid)
            except (ReplicaCrashed, TimeoutError):
                payload = None  # health machinery will notice
            # the export popped it (or the owner never had it): either
            # way the claim is stale now
            self._session_index.discard(sid)
        if payload is None and self.session_store is not None:
            got = self.session_store.get(sid)
            if got is not None:
                payload, tier = got
        if payload is not None:
            try:
                seeded = r.seed_session(payload)
            except (ReplicaCrashed, TimeoutError):
                seeded = 0
            if seeded > 0:
                self._stats["session_reattach"][tier] += 1
                if tier == "hbm":
                    # crossed the wire replica→replica
                    self._stats["session_ships"] += 1
                    self._stats["kv_stream_bytes"] += payload.nbytes
                self._event("session_reattach", session=sid, tier=tier,
                            replica=r.index, owner=owner, tokens=seeded)
                return
            if tier == "hbm" and self.session_store is not None:
                # seed declined but the payload was already popped off
                # the owner — park it in the store rather than lose it
                self.session_store.put(sid, payload, tenant=rr.tenant)
        if known:
            self._stats["session_fallbacks"] += 1
            self._event("session_fallback", session=sid,
                        replica=r.index, owner=owner,
                        tier=(tier if payload is not None else None))

    def _dispatch(self) -> int:
        healthy = [r for r in self._replicas
                   if self._status[r.index] == HEALTHY]
        if not healthy or not self._queue:
            return 0
        # disaggregated fleet: new admissions go to prefill-capable
        # replicas (role prefill/both); if none survive, availability
        # beats role purity and any healthy replica may admit
        cands = healthy
        if self._disagg:
            pref = [r for r in healthy
                    if self._roles[r.index] in (ROLE_PREFILL, ROLE_BOTH)]
            cands = pref or healthy
        emas = [self._health[r.index].get("ttft_ema_s") for r in cands]
        emas = [e for e in emas if e]
        mean_ttft = sum(emas) / len(emas) if emas else None
        now = time.perf_counter()
        dispatched = 0
        deferred: list[RouterRequest] = []
        while self._queue:
            rr = self._queue.popleft()
            if rr.done:
                continue
            if rr._eligible_at > now:   # redispatch backoff
                deferred.append(rr)
                continue
            if rr.deadline_s is not None:
                remaining = rr.deadline_s - (now - rr.submit_time)
                if remaining <= 0:
                    self._finish(rr, "deadline")
                    continue
            # room = the replica can hold it without unbounded queueing;
            # ties break toward the replica with fewer lifetime
            # placements (deterministic round-robin under light load —
            # a pure index tie-break would starve the higher indices).
            # A published prefix match dominates the key: landing on the
            # replica that already holds the blocks skips whole prefill
            # chunks, which is worth more than any load delta
            chain = self._prefix_chain(rr)
            # session affinity dominates even prefix depth: the owner
            # replica holds the WHOLE conversation's blocks resident —
            # landing there costs zero wire bytes and zero re-prefill
            sowner = (self._session_index.owner(
                rr.session_id, [r.index for r in cands])
                if rr.session_id is not None else None)
            best, best_key = None, None
            for r in cands:
                h = self._health[r.index]
                load = (h.get("active", 0) + h.get("queued", 0)
                        + h.get("prefilling", 0) + h.get("parked", 0))
                if load >= h.get("num_slots", 1) + self.max_pending:
                    continue
                depth = (self._prefix_index.match_depth(r.index, chain)
                         if chain else 0)
                key = (0 if sowner == r.index else 1,
                       -depth, self._replica_score(h, mean_ttft),
                       self._placements[r.index], r.index)
                if best_key is None or key < best_key:
                    best, best_key = r, key
            if best is None:
                deferred.append(rr)   # every replica full: wait
                break
            if chain and not rr.tokens:
                self._maybe_ship_prefix(rr, chain, best)
            if not self._place(rr, best):
                # the pick died at placement (request was requeued);
                # stop this pass — the next tick re-dispatches against
                # refreshed health, never against this stale snapshot
                break
            dispatched += 1
        # untouched tail keeps FIFO order behind the deferred heads
        for rr in reversed(deferred):
            self._queue.appendleft(rr)
        return dispatched

    def _place(self, rr: RouterRequest, r) -> bool:
        remaining = None
        if rr.deadline_s is not None:
            remaining = max(
                0.001,
                rr.deadline_s - (time.perf_counter() - rr.submit_time))

        # first arg is the engine Request (in-process) or the rid
        # (subprocess) — either way the RouterRequest closure is the
        # identity that matters
        def cb(_handle, tok, rr=rr, idx=r.index):
            self._on_token(rr, idx, tok)

        # a prefill-role replica parks the stream after its first token
        # for KV handoff — but only while a decode-capable replica is
        # alive to receive it; otherwise it decodes in place (lossy
        # topology never beats a lost stream)
        prefill_only = (
            self._disagg
            and self._roles[r.index] == ROLE_PREFILL
            and bool(self._health[r.index].get("block_size"))
            and any(self._status[x.index] == HEALTHY
                    and self._roles[x.index] in (ROLE_DECODE, ROLE_BOTH)
                    for x in self._replicas))
        # reattach prep (ISSUE 18): fresh turns only — a failover
        # redispatch resumes from its delivered tokens, and a non-paged
        # target (no block_size in health) has no tiers to seed
        if (rr.session_id is not None and not rr.tokens
                and self._health[r.index].get("block_size")):
            self._prepare_session(rr, r)
        try:
            handle = r.submit(rr, generated=rr.tokens or None,
                              deadline_s=remaining, on_token=cb,
                              prefill_only=prefill_only)
        except WireFault:
            # the wire mangled something DURING placement: the replica
            # is suspect, not dead — requeue the request and let the
            # protocol-fault sweep quarantine it (no death sentence
            # for a torn line)
            self._queue.appendleft(rr)
            if self.trace is not None and rr.trace is not None:
                now = time.perf_counter()
                self.trace.span(rr.trace, "redispatch", now, now,
                                from_replica=r.index, why="wire_fault")
                rr._trace_enq_t = now
            return False
        except (ReplicaCrashed, TimeoutError):
            # the pick died (or stopped answering) between health check
            # and placement: requeue the request, let the health
            # machinery take the replica down
            self._queue.appendleft(rr)
            if self.trace is not None and rr.trace is not None:
                now = time.perf_counter()
                self.trace.span(rr.trace, "redispatch", now, now,
                                from_replica=r.index, why="place_crash")
                rr._trace_enq_t = now
            self._declare_dead(r, "crashed")
            return False
        except ValueError as e:
            # the replica REFUSED the request (e.g. a per-request KV
            # override its pool can't honor): terminal — every replica
            # in a homogeneous fleet would refuse it the same way, so
            # fail LOUDLY rather than redispatch-storm
            self._finish(rr, "failed")
            self._event("rejected", request=rr.id, replica=r.index,
                        tenant=rr.tenant, error=str(e)[:200])
            return True
        rr._handle = handle
        rr._replica = r.index
        rr.replicas.append(r.index)
        if rr.session_id is not None:
            # optimistic ownership: the stream parks HERE at finish —
            # steer the next turn before the health refresh catches up
            self._session_index.add(r.index, rr.session_id)
        self._placements[r.index] += 1
        self._assigned[r.index][rr.id] = rr
        # keep this tick's snapshot honest for the next pick
        self._health[r.index]["queued"] = \
            self._health[r.index].get("queued", 0) + 1
        if self.trace is not None and rr.trace is not None:
            # queue = residency start -> WDRR dequeue; admission =
            # dequeue -> the engine accepting the stream. The dequeue
            # stamp comes from AdmissionController.popleft (falls back
            # to now on the plain-deque path)
            now = time.perf_counter()
            t0 = rr._trace_enq_t if rr._trace_enq_t is not None \
                else rr.submit_time
            dq = rr.dequeue_time if rr.dequeue_time is not None else now
            dq = min(max(dq, t0), now)
            self.trace.span(rr.trace, "queue", t0, dq,
                            request=rr.id, replica=r.index)
            self.trace.span(rr.trace, "admission", dq, now,
                            replica=r.index,
                            role=self._roles[r.index],
                            prefill_only=prefill_only)
            rr._trace_enq_t = None
        return True

    def _on_token(self, rr: RouterRequest, replica: int, tok: int) -> None:
        if rr.done or rr._replica != replica:
            return  # stale delivery from a replaced placement
        rr.tokens.append(int(tok))
        # each replica's first-ever delivery (the scale-up reaction
        # clock's far edge: decision wall time -> this entry appearing)
        self._first_token_t.setdefault(replica, time.perf_counter())
        if rr.first_token_time is None:
            rr.first_token_time = time.perf_counter()
        if rr.on_token is not None:
            rr.on_token(rr, int(tok))
        for rec in self._recovering:
            rec["pending"].discard(rr.id)
        self._gc_recovering()

    def _gc_recovering(self) -> None:
        done = [rec for rec in self._recovering if not rec["pending"]]
        for rec in done:
            self._recovering.remove(rec)
            self._stats["failover_recovery_ticks"].append(
                self._ticks - rec["start"])
            self._stats["failover_recovery_s"].append(
                round(time.perf_counter() - rec["start_t"], 4))

    def _reap(self) -> None:
        for r in self._replicas:
            assigned = self._assigned[r.index]
            for rid in [rid for rid, rr in assigned.items()
                        if rr._handle is not None and rr._handle.done]:
                rr = assigned.pop(rid)
                if rr._handle.finish_reason == "preempted":
                    # admission-pressure eviction: NOT a client-visible
                    # finish — requeue immediately (no backoff: the
                    # request did nothing wrong) and resume-from-tokens
                    # replays it losslessly when capacity frees
                    rr._handle = None
                    rr._replica = None
                    rr._eligible_at = 0.0
                    self._queue.appendleft(rr)
                    self._stats["preempted_requeues"] += 1
                    self._event("preempt_requeue", request=rr.id,
                                tenant=rr.tenant,
                                tokens_so_far=len(rr.tokens))
                    if self.trace is not None and rr.trace is not None:
                        now = time.perf_counter()
                        self.trace.span(rr.trace, "redispatch", now,
                                        now, from_replica=r.index,
                                        why="preempt")
                        rr._trace_enq_t = now
                    continue
                self._finish(rr, rr._handle.finish_reason)

    # -- prefill→decode handoff (ISSUE 12) -----------------------------

    def _handoffs(self) -> None:
        """Move every stream a prefill-role replica has parked onto a
        decode-capable replica over the KV stream. Every failure mode
        degrades to the lossless resume-from-tokens path: the first
        token was already delivered, so requeueing the RouterRequest
        replays the prompt + delivered tokens on any survivor."""
        if not self._disagg:
            return
        for src in self._replicas:
            # DRAINING sources sweep too: a scale-down target's parked
            # prefills must reach a decode home before the tombstone
            if (self._status[src.index] not in (HEALTHY, DRAINING)
                    or self._roles[src.index] != ROLE_PREFILL):
                continue
            parked = [rr for rr in self._assigned[src.index].values()
                      if rr._handle is not None
                      and getattr(rr._handle, "parked", False)
                      and not getattr(rr._handle, "done", False)]
            for rr in parked:
                self._handoff(rr, src)

    def _handoff(self, rr: RouterRequest, src) -> None:
        # target FIRST, export second: with no decode-capable home the
        # stream simply stays parked on src (its blocks intact) and the
        # sweep retries next tick — exporting eagerly would strand the
        # KV in a payload and force a full re-prefill via requeue
        tgt, tgt_key = None, None
        for r in self._replicas:
            if (self._status[r.index] != HEALTHY
                    or r.index == src.index
                    or self._roles[r.index] not in (ROLE_DECODE,
                                                    ROLE_BOTH)):
                continue
            # LIVE snapshot, not this tick's _check_health copy: the
            # drain loop runs handoffs without health sweeps, and a
            # freed decode slot must be visible there too
            try:
                h = r.health()
            except ReplicaCrashed:
                continue   # the health machinery will take it down
            if not h.get("free_slots", 0):
                continue
            key = (self._replica_score(h, None), self._placements[r.index],
                   r.index)
            if tgt_key is None or key < tgt_key:
                tgt, tgt_key = r, key
        if tgt is None:
            return   # parked, not failed: wait for a decode slot
        t_h0 = time.perf_counter()
        try:
            payload = src.export_kv(rr)
        except WireFault:
            # the transfer ABORTED mid-wire (torn/corrupt/lost payload
            # line): lossless fallback — requeue for re-prefill via
            # resume-from-tokens; the protocol-fault sweep judges src.
            # Counted + traced separately from a refused export: an
            # abort is the wire's fault, not the worker's.
            del self._assigned[src.index][rr.id]
            rr._handle = None
            rr._replica = None
            rr._eligible_at = 0.0
            self._queue.appendleft(rr)
            self._stats["handoff_aborts"] += 1
            self._event("handoff_aborted", request=rr.id,
                        from_replica=src.index, to_replica=None,
                        phase="export")
            if self.trace is not None and rr.trace is not None:
                now = time.perf_counter()
                self.trace.span(rr.trace, "redispatch", now, now,
                                from_replica=src.index,
                                why="wire_fault")
                rr._trace_enq_t = now
            return
        except (ReplicaCrashed, TimeoutError):
            # rr is still in src's assigned map — _declare_dead's
            # failover requeues it with the rest
            self._declare_dead(src, "crashed")
            return
        except ValueError:
            # the worker REFUSED the export (e.g. stale parked state
            # after a respawn): the stream no longer exists there —
            # requeue and let resume-from-tokens replay it
            del self._assigned[src.index][rr.id]
            rr._handle = None
            rr._replica = None
            rr._eligible_at = 0.0
            self._queue.appendleft(rr)
            self._stats["handoff_failures"] += 1
            self._event("handoff_failed", request=rr.id,
                        from_replica=src.index, to_replica=None)
            if self.trace is not None and rr.trace is not None:
                now = time.perf_counter()
                self.trace.span(rr.trace, "redispatch", now, now,
                                from_replica=src.index,
                                why="handoff_refused")
                rr._trace_enq_t = now
            return
        # export released the blocks on src: from here the ONLY copy of
        # the stream's KV is the payload, and the fallback is resume
        del self._assigned[src.index][rr.id]
        rr._handle = None
        rr._replica = None
        remaining = None
        if rr.deadline_s is not None:
            remaining = max(
                0.001,
                rr.deadline_s - (time.perf_counter() - rr.submit_time))

        def cb(_handle, tok, rr=rr, idx=tgt.index):
            self._on_token(rr, idx, tok)

        handle = None
        try:
            handle = tgt.import_kv(rr, payload, deadline_s=remaining,
                                   on_token=cb)
        except WireFault:
            # import reply lost/torn mid-transfer: treat as a refused
            # import (requeue below) and count the abort — the target's
            # protocol-fault sweep decides whether it stays in rotation
            self._stats["handoff_aborts"] += 1
            self._event("handoff_aborted", request=rr.id,
                        from_replica=src.index, to_replica=tgt.index,
                        phase="import")
            handle = None
        except (ReplicaCrashed, TimeoutError):
            self._declare_dead(tgt, "crashed")
            handle = None
        if handle is None:
            # the import was refused (pool pressure) or the target died
            # mid-import: requeue — resume-from-tokens replays losslessly
            rr._eligible_at = 0.0
            self._queue.appendleft(rr)
            self._stats["handoff_failures"] += 1
            self._event("handoff_failed", request=rr.id,
                        from_replica=src.index, to_replica=tgt.index)
            if self.trace is not None and rr.trace is not None:
                now = time.perf_counter()
                self.trace.span(rr.trace, "redispatch", now, now,
                                from_replica=src.index,
                                why="handoff_failed")
                rr._trace_enq_t = now
            return
        rr._handle = handle
        rr._replica = tgt.index
        rr.replicas.append(tgt.index)
        self._placements[tgt.index] += 1
        self._assigned[tgt.index][rr.id] = rr
        self._health[tgt.index]["free_slots"] = \
            self._health[tgt.index].get("free_slots", 1) - 1
        if isinstance(tgt, SubprocessReplica):
            # its cached snapshot refreshes on the next step reply;
            # debit it NOW so a same-sweep sibling handoff doesn't
            # over-commit the slot we just took
            tgt._health["free_slots"] = max(
                0, tgt._health.get("free_slots", 1) - 1)
        nbytes = payload.nbytes
        self._stats["handoffs"] += 1
        self._stats["kv_stream_bytes"] += nbytes
        self._event("handoff", request=rr.id, from_replica=src.index,
                    to_replica=tgt.index, blocks=payload.num_blocks,
                    bytes=nbytes)
        if self.trace is not None and rr.trace is not None:
            self.trace.span(rr.trace, "handoff", t_h0,
                            time.perf_counter(),
                            from_replica=src.index,
                            to_replica=tgt.index,
                            blocks=payload.num_blocks, bytes=nbytes)

    def _expire_queued_deadlines(self) -> None:
        now = time.perf_counter()
        overdue = [rr for rr in self._queue
                   if rr.deadline_s is not None
                   and now - rr.submit_time >= rr.deadline_s]
        for rr in overdue:
            self._queue.remove(rr)
            self._finish(rr, "deadline")

    def _finish(self, rr: RouterRequest, reason: str | None) -> None:
        if rr.done:
            return
        rr.done = True
        rr.finish_reason = reason or "unknown"
        rr.finish_time = time.perf_counter()
        rr._handle = None
        # "completed" counts streams that reached a SERVING conclusion
        # — shed/drained/failed refusals have their own counters and
        # must not inflate it (or the report would read 24/24 served
        # on a trace that shed 10)
        if reason in ("length", "stop", "deadline"):
            self._stats["completed"] += 1
            if rr._replica is not None:
                self._stats["served_by"][rr._replica] = \
                    self._stats["served_by"].get(rr._replica, 0) + 1
        if reason == "failed":
            self._stats["failed_requests"] += 1
        t = self._tenant_stats(rr.tenant)
        if reason in ("length", "stop", "deadline"):
            t["completed"] += 1
        elif reason == "shed":
            t["shed"] += 1
        elif reason == "failed":
            t["failed"] += 1
        if rr.ttft_s is not None:
            self._stats["ttft_s"].append(rr.ttft_s)
            t["ttft_s"].append(rr.ttft_s)
        if (self.trace is not None and rr.trace is not None
                and rr.submit_time is not None):
            # the ROOT span: every stage span parents to this one, so
            # connectivity in the merged trace is a single equality
            # check per span — and its window is what the critical-path
            # sweep tiles into queue/admission/prefill/handoff/decode/
            # stall
            self.trace.span(rr.trace, "request", rr.submit_time,
                            rr.finish_time, root=True, request=rr.id,
                            tenant=rr.tenant,
                            finish_reason=rr.finish_reason,
                            ttft_s=rr.ttft_s, retries=rr.retries)
            if reason in ("length", "stop", "deadline"):
                self.trace.note_finish(rr.tenant, rr.ttft_s)
        for rec in self._recovering:
            rec["pending"].discard(rr.id)
        self._gc_recovering()

    def _event(self, event: str, **row) -> None:
        if self.telemetry is not None:
            self.telemetry.event(event, tick=self._ticks, **row)

    # ------------------------------------------------------------------
    # lifecycle

    def warmup(self, prompt_lens=None) -> None:
        """Warm every replica (each engine compiles its tick + prefill
        buckets — in-process replicas over the same model share the jit
        cache, so N replicas compile once) and reset router stats.
        Resume-from-tokens redispatch reuses the SAME compiled prefill
        programs, so warming the buckets here is what makes a failover
        recompile-free on the survivors."""
        lens = prompt_lens or self.warmup_lens
        for r in self._replicas:
            try:
                r.warmup(lens)
            except WireFault as e:
                # a mangled (or dropped-then-timed-out) warmup reply is
                # a protocol fault, not a startup abort: the worker is
                # up and warmed — only the ACK died on the wire. Leave
                # the replica flagged; the health sweep quarantines it
                # and the clean-probe→canary path brings it back.
                self.telemetry.event("wire_fault_detected",
                                     replica=r.index, op="warmup",
                                     error=str(e))
        # subprocess workers report their engines' true context bound
        # at warmup — tighten submit validation to the real minimum
        reported = [getattr(r, "reported_max_seq_len", None)
                    for r in self._replicas]
        reported = [v for v in reported if v]
        if reported:
            self.max_seq_len = min([self.max_seq_len] + reported)
        self.reset_stats()

    def set_draft_params(self, params=None, *, checkpoint=None,
                         step=None) -> dict[int, dict]:
        """Broadcast a speculative-draft hot-swap to the whole fleet
        (ISSUE 16) — the serve half of the distill→swap loop: a
        DistillTrainer checkpoint becomes every replica's draft without
        dropping a stream (spec decode is lossless under ANY draft, so
        in-flight requests keep their token-for-token identity and their
        K/V; only the acceptance rate moves).

        In-process fleets accept a weight tree directly, or restore
        ``checkpoint`` ONCE and share the host copy; subprocess fleets
        require ``checkpoint`` — the PATH crosses the wire and each
        worker restores it through the same manifest-verified loader as
        its boot weights. Per-replica verification (tree structure +
        leaf shapes) happens in the engine either way.

        Returns {replica_index: {"draft_hash", "draft_swaps"}} for the
        replicas that accepted. A refusal (architecture mismatch) is
        counted, evented, and skipped — unless EVERY live replica
        refuses, which raises (the swap was simply wrong)."""
        if self._worker_specs is not None:
            if checkpoint is None:
                raise ValueError(
                    "a subprocess fleet takes set_draft_params("
                    "checkpoint=...) — weight trees do not cross the "
                    "wire")
            params = None   # the path is the payload
        elif params is None:
            if checkpoint is None:
                raise ValueError("pass params or checkpoint")
            from pytorchdistributed_tpu.training.checkpoint import (
                CheckpointManager,
            )

            # restore once, share the host copy fleet-wide
            with CheckpointManager(checkpoint) as mgr:
                params, _ = mgr.restore_params(step=step)
        results: dict[int, dict] = {}
        errors: list[str] = []
        for r in self._replicas:
            if self._status[r.index] in (DEAD, REMOVED):
                continue
            try:
                if params is not None:
                    info = r.set_draft_params(params)
                else:
                    info = r.set_draft_params(checkpoint=checkpoint,
                                              step=step)
            except (ReplicaCrashed, TimeoutError):
                self._declare_dead(r, "crashed")
                continue
            except ValueError as e:
                errors.append(f"replica {r.index}: {e}")
                self._event("draft_swap_failed", replica=r.index,
                            error=str(e)[:200])
                continue
            results[r.index] = info
            self._draft_info[r.index] = info
            self._stats["draft_swaps"] += 1
            self._event("draft_swap", replica=r.index,
                        hash=info.get("draft_hash"),
                        swaps=info.get("draft_swaps"),
                        checkpoint=(str(checkpoint) if checkpoint
                                    else None))
        if errors and not results:
            raise ValueError("draft swap refused fleet-wide: "
                             + "; ".join(errors[:3]))
        return results

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        while self._queue or any(self._assigned[r.index]
                                 for r in self._replicas):
            # quarantined replicas still count: the rejoin probes that
            # could restore them only run inside step(), and so do
            # respawns — only an all-DEAD fleet with no respawn budget
            # left is genuinely unrecoverable
            if self._fleet_unrecoverable():
                raise RuntimeError(
                    "every replica is dead with work outstanding")
            if max_steps <= 0:
                raise RuntimeError("router loop did not drain")
            self.step()
            max_steps -= 1

    def stream(self, rr: RouterRequest):
        """Iterator over one request's tokens, stepping the router —
        failover happens transparently underneath; the stream just
        keeps going."""
        sent = 0
        while True:
            while sent < len(rr.tokens):
                yield rr.tokens[sent]
                sent += 1
            if rr.done:
                return
            if self._fleet_unrecoverable():
                raise RuntimeError(
                    "every replica is dead; the stream cannot finish")
            self.step()

    def request_drain(self) -> None:
        """Signal-handler-safe drain request (the run.py SIGTERM
        forwarding contract) — the next step() performs the actual
        drain outside the signal frame."""
        self._draining = True

    def install_sigterm_drain(self) -> None:
        import signal

        signal.signal(signal.SIGTERM, lambda *_: self.request_drain())

    def drain(self, max_steps: int = 100_000) -> list[RouterRequest]:
        """Graceful drain: queued requests are shed with
        ``finish_reason="drained"`` (they never started streaming —
        refusing them cleanly beats a half-stream), RESIDENT streams
        run to completion on their replicas, then nothing new is
        admitted. Returns the requests finished by the drain."""
        self._draining = True
        out: list[RouterRequest] = []
        while self._queue:
            rr = self._queue.popleft()
            self._finish(rr, "drained")
            out.append(rr)
        while any(self._assigned[r.index] for r in self._replicas
                  if self._status[r.index] in (HEALTHY, DRAINING)) \
                and max_steps:
            for r in self._replicas:
                if self._status[r.index] not in (HEALTHY, DRAINING):
                    continue
                try:
                    r.step()
                except ReplicaCrashed:
                    self._declare_dead(r, "crashed")
            # parked prefill-role streams can only finish on a decode
            # home — keep the handoff sweep alive through the drain
            self._handoffs()
            self._reap()
            max_steps -= 1
        # streams stranded on dead replicas at drain time, plus any a
        # mid-drain crash FAILED OVER back onto the queue (nothing
        # dispatches during a drain): finished with what they have —
        # the drain contract is bounded shutdown, not infinite
        # redispatch
        for r in self._replicas:
            for rr in list(self._assigned[r.index].values()):
                self._finish(rr, "drained")
                out.append(rr)
            self._assigned[r.index].clear()
        while self._queue:
            rr = self._queue.popleft()
            self._finish(rr, "drained")
            out.append(rr)
        self._event("drained", finished=len(out))
        return out

    def close(self) -> None:
        """Drain, close every replica (engines assert their pool-leak
        invariant; subprocess workers get the SIGTERM→kill_group
        escalation — no orphans), stamp the telemetry summary."""
        self.drain()
        if self.session_store is not None:
            for r in self._replicas:
                if self._status[r.index] in (HEALTHY, DRAINING):
                    self._persist_replica_sessions(r)
            # the store flushes its DRAM tier to disk (restart
            # survival) but stays open — the caller owns its lifetime
            self.session_store.flush()
        subs = [r for r in self._replicas
                if isinstance(r, SubprocessReplica)
                and self._status[r.index] != REMOVED]
        for r in self._replicas:
            if r in subs or self._status[r.index] == REMOVED:
                continue   # tombstones already closed at removal
            try:
                r.close()
            except ReplicaCrashed:
                pass
        if subs:
            # group teardown: best-effort protocol close to each, then
            # ONE kill_group escalation over the whole fleet — N wedged
            # workers cost one grace window, not N
            from pytorchdistributed_tpu.run import kill_group

            for r in subs:
                if r.alive and r.proc.poll() is None:
                    try:
                        r._drain_wire(timeout=2.0)
                        r._send({"op": "close"})
                    except (ReplicaCrashed, TimeoutError):
                        pass
            kill_group([r.proc for r in subs], grace=10.0)
            for r in subs:
                r.alive = False
                for pipe in (r.proc.stdin, r.proc.stdout):
                    try:
                        pipe.close()
                    except OSError:
                        pass
        if self._hb_dir is not None:
            import shutil

            shutil.rmtree(self._hb_dir, ignore_errors=True)
            self._hb_dir = None
        if self.telemetry is not None:
            self.telemetry.summary(**self.summary())
            self.telemetry.close()
        if self.trace is not None:
            self.trace.close()

    # ------------------------------------------------------------------
    # stats

    def reset_stats(self) -> None:
        self._stats = dict(submitted=0, completed=0, shed_requests=0,
                           failed_requests=0, failovers=0,
                           redispatched_requests=0, quarantines=0,
                           rejoins=0, hangs_detected=0, replicas_lost=0,
                           respawns=0, respawn_failures=0,
                           handoffs=0, handoff_failures=0,
                           handoff_aborts=0, wire_faults=0,
                           faults_injected=0,
                           prefix_ships=0, kv_stream_bytes=0,
                           session_reattach={"hbm": 0, "dram": 0,
                                             "disk": 0},
                           session_fallbacks=0, session_ships=0,
                           session_demotes=0,
                           scale_ups=0, scale_downs=0,
                           draft_swaps=0,
                           preemptions=0, preempted_requeues=0,
                           tenants={},
                           served_by={}, ttft_s=[],
                           failover_recovery_ticks=[],
                           failover_recovery_s=[])
        self._occ_sum = [0.0 for _ in self._replicas]
        self._occ_n = [0 for _ in self._replicas]
        self._first_token_t = {}
        self._last_signal_counts = (0, 0)

    def _tenant_stats(self, name: str) -> dict:
        t = self._stats["tenants"].get(name)
        if t is None:
            t = self._stats["tenants"][name] = dict(
                submitted=0, completed=0, shed=0, failed=0, ttft_s=[])
        return t

    @property
    def first_token_times(self) -> dict[int, float]:
        """Wall-clock time each replica delivered its FIRST token since
        the last reset_stats — the far edge of the autoscaler's
        scale-up reaction measurement (decision wall time -> the new
        replica's entry appearing here)."""
        return dict(self._first_token_t)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def in_flight(self) -> int:
        return sum(len(a) for a in self._assigned)

    def health(self) -> list[dict]:
        """The latest per-replica snapshots, status included."""
        out = []
        for r in self._replicas:
            h = dict(self._health[r.index])
            h["replica"] = r.index
            h["status"] = self._status[r.index]
            out.append(h)
        return out

    def summary(self) -> dict:
        """Router-level aggregate (the bench's stamp source): request
        accounting, failover/shed/quarantine counters, per-replica
        occupancy balance and the recovery-time distribution."""
        st = self._stats
        occ = [round(self._occ_sum[i] / self._occ_n[i], 4)
               if self._occ_n[i] else None
               for i in range(len(self._replicas))]
        known = [o for o in occ if o is not None]
        ttfts = np.asarray(st["ttft_s"], np.float64)
        out = {
            "replicas": len(self._replicas),
            "healthy_replicas": sum(s == HEALTHY for s in self._status),
            "ticks": self._ticks,
            "submitted": st["submitted"],
            "completed": st["completed"],
            "shed_requests": st["shed_requests"],
            "failed_requests": st["failed_requests"],
            "failovers": st["failovers"],
            "redispatched_requests": st["redispatched_requests"],
            "quarantines": st["quarantines"],
            "rejoins": st["rejoins"],
            "hangs_detected": st["hangs_detected"],
            "replicas_lost": st["replicas_lost"],
            "respawns": st["respawns"],
            "respawn_failures": st["respawn_failures"],
            "scale_ups": st["scale_ups"],
            "scale_downs": st["scale_downs"],
            "draft_swaps": st["draft_swaps"],
            "preemptions": st["preemptions"],
            "preempted_requeues": st["preempted_requeues"],
            "statuses": list(self._status),
            "roles": list(self._roles),
            "handoffs": st["handoffs"],
            "handoff_failures": st["handoff_failures"],
            "handoff_aborts": st["handoff_aborts"],
            "wire_faults": st["wire_faults"],
            "faults_injected": st["faults_injected"],
            "prefix_ships": st["prefix_ships"],
            "kv_stream_bytes": st["kv_stream_bytes"],
            "cross_replica_hit_rate": (
                round(sum(h.get("remote_hit_tokens", 0)
                          for h in self._health)
                      / max(1, sum(h.get("admitted_tokens", 0)
                                   for h in self._health)), 4)),
            "served_by": dict(sorted(st["served_by"].items())),
            "replica_occupancy": occ,
            "occupancy_spread": (round(max(known) - min(known), 4)
                                 if known else None),
            "shed_rate": (round(st["shed_requests"]
                                / st["submitted"], 4)
                          if st["submitted"] else None),
            # recovery = failover declared -> every redispatched stream
            # delivering again. Ticks are the scheduler-step bound (the
            # chaos suite's unit); seconds are the wall-clock truth (an
            # idle router spins free ticks while the redispatch backoff
            # gate runs down, so ticks alone can over-read)
            "failover_recovery_ticks": (
                max(st["failover_recovery_ticks"])
                if st["failover_recovery_ticks"] else None),
            "failover_recovery_s": (
                max(st["failover_recovery_s"])
                if st["failover_recovery_s"] else None),
        }
        if ttfts.size:
            out["ttft_ms_p50"] = round(
                float(np.percentile(ttfts, 50)) * 1e3, 3)
            out["ttft_ms_p99"] = round(
                float(np.percentile(ttfts, 99)) * 1e3, 3)
        if self._draft_info:
            # per-replica draft identity (hash + lifetime swap count):
            # the report CLI's proof that the fleet converged on ONE
            # distilled draft after a broadcast
            out["draft"] = {
                i: dict(info)
                for i, info in sorted(self._draft_info.items())}
        if (self.session_store is not None
                or any(st["session_reattach"].values())
                or st["session_fallbacks"] or st["session_demotes"]):
            sess = {
                "reattach": dict(st["session_reattach"]),
                "fallbacks": st["session_fallbacks"],
                "ships": st["session_ships"],
                "demotes": st["session_demotes"],
                "resident": sum(h.get("sessions_resident", 0)
                                for h in self._health),
            }
            if self.session_store is not None:
                sess["store"] = self.session_store.stats()
            out["sessions"] = sess
        if st["tenants"]:
            adm = (self._admission.tenant_stats()
                   if self._admission is not None else {})
            tens = {}
            for name, t in sorted(st["tenants"].items()):
                row = {k: t[k] for k in ("submitted", "completed",
                                         "shed", "failed")}
                ts = np.asarray(t["ttft_s"], np.float64)
                if ts.size:
                    row["ttft_ms_p50"] = round(
                        float(np.percentile(ts, 50)) * 1e3, 3)
                    row["ttft_ms_p99"] = round(
                        float(np.percentile(ts, 99)) * 1e3, 3)
                if name in adm:
                    row["weight"] = adm[name]["weight"]
                    row["overage"] = adm[name]["overage"]
                tens[name] = row
            out["tenants"] = tens
        return out
