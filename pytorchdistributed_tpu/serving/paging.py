"""Host-side bookkeeping for the paged KV cache (ISSUE 7).

The device half of paging lives in models/transformer.py (block pool +
block-table gather inside the compiled tick) and serving/engine.py (the
jitted paged tick / chunked prefill). Everything here is pure-Python
state the scheduler mutates between compiled calls:

  * `BlockAllocator` — a refcounted free list over the physical pool.
    Block 0 is reserved as the TRASH block: retired slots' table entries
    (and pad positions of chunked prefills) point at it, so their garbage
    writes can never land in a block another request owns. A block frees
    when its last reference drops — a slot's table entry and a radix-
    cache node each hold one.
  * `RadixPrefixCache` — a block-granularity radix tree over prompt
    token ids (SGLang's RadixAttention at vLLM's block alignment): a
    node caches ONE full block (`block_size` tokens) of K/V under its
    parent's prefix. Admission walks the new prompt's full blocks down
    the tree; every hit is admitted by *reference* (the slot's table
    points at the cached physical block) instead of re-running prefill.
    Only whole blocks are ever shared, and a slot's writes always land
    in blocks it privately owns (its first unmatched block onward), so
    the copy-on-write discipline holds by construction — divergence
    within a block simply misses the cache and prefills a private copy.
    Eviction is LRU over leaf nodes whose block the cache is the sole
    owner of (evicting a block an active slot still reads would free
    nothing and lose reuse).

The leak invariant the engine asserts at teardown:
``free + resident == usable`` — every non-trash block is either on the
free list or accounted to at least one live reference.
"""

from __future__ import annotations

import hashlib
import itertools


def block_hashes(tokens, block_size: int) -> list[str]:
    """Chained per-block content digests of ``tokens``'s full blocks:
    ``h[i] = blake2b(h[i-1] || tokens_of_block_i)``. Each digest names a
    whole PREFIX (not just its last block), so two replicas hold the
    same cached prefix iff they hold the same digest — the fleet prefix
    index's matching unit. blake2b, not Python's ``hash()``: the
    builtin is per-process salted (PYTHONHASHSEED), and these digests
    must agree between the router and its subprocess workers."""
    out: list[str] = []
    prev = b""
    for i in range(len(tokens) // block_size):
        blk = ",".join(
            str(int(t))
            for t in tokens[i * block_size:(i + 1) * block_size])
        prev = hashlib.blake2b(prev + blk.encode(),
                               digest_size=16).digest()
        out.append(prev.hex())
    return out


class BlockAllocator:
    """Refcounted free-list allocator over ``num_blocks`` physical KV
    blocks of ``block_size`` tokens. Block 0 is the reserved trash block
    and is never handed out."""

    TRASH = 0

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks {num_blocks} must be >= 2 (block 0 is the "
                f"reserved trash block)")
        if block_size < 1:
            raise ValueError(f"block_size {block_size} must be >= 1")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # pop() hands out low block ids first (1, 2, ...)
        self._free = list(range(num_blocks - 1, 0, -1))
        self._refs: dict[int, int] = {}

    @property
    def usable(self) -> int:
        """Allocatable blocks (the pool minus the trash block)."""
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def resident(self) -> int:
        """Blocks currently referenced by at least one owner."""
        return len(self._refs)

    def refcount(self, block: int) -> int:
        return self._refs.get(block, 0)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """n fresh blocks at refcount 1, or None if the free list is
        short (the caller decides: evict prefix cache, preempt, or
        wait)."""
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._refs[b] = 1
        return blocks

    def incref(self, block: int) -> None:
        if block == self.TRASH:
            raise ValueError("cannot reference the trash block")
        if block not in self._refs:
            raise ValueError(f"block {block} is not allocated")
        self._refs[block] += 1

    def decref(self, block: int) -> bool:
        """Drop one reference; returns True when the block actually
        freed back to the pool."""
        rc = self._refs.get(block)
        if rc is None:
            raise ValueError(f"block {block} is not allocated")
        if rc > 1:
            self._refs[block] = rc - 1
            return False
        del self._refs[block]
        self._free.append(block)
        return True

    def check_leaks(self, expected_resident: int = 0) -> None:
        """The teardown invariant: free + resident == usable, and — once
        every owner has released (slots retired, radix cleared) —
        resident is exactly ``expected_resident``."""
        if self.free_count + self.resident != self.usable:
            raise AssertionError(
                f"KV block leak: free {self.free_count} + resident "
                f"{self.resident} != usable {self.usable} "
                f"(held: {sorted(self._refs)})")
        if self.resident != expected_resident:
            raise AssertionError(
                f"KV block leak: {self.resident} blocks still referenced "
                f"at teardown (expected {expected_resident}): "
                f"{sorted(self._refs)}")


class _RadixNode:
    __slots__ = ("children", "parent", "key", "block", "last_use",
                 "hash", "remote")

    def __init__(self, parent, key, block, hash="", remote=False):
        self.children: dict[tuple, _RadixNode] = {}
        self.parent = parent
        self.key = key
        self.block = block
        self.last_use = 0
        # the node's chained prefix digest (block_hashes) — what the
        # replica publishes in its health frontier
        self.hash = hash
        # True when the block's K/V arrived over the fleet KV stream
        # (import_prefix_blocks) instead of local prefill — hits through
        # it are STEERED hits, counted separately from local ones
        self.remote = remote


class RadixPrefixCache:
    """Block-granularity radix tree mapping full-block token prefixes to
    the physical pool blocks holding their K/V. Each node owns one
    allocator reference on its block, so cached prefixes survive the
    admitting request's retirement and free only on eviction."""

    def __init__(self, allocator: BlockAllocator):
        self.alloc = allocator
        self._root = _RadixNode(None, None, None)
        self._clock = itertools.count(1)
        self._nodes = 0
        # admission-level counters the engine folds into its summary.
        # hit_tokens counts LOCAL hits only; steered hits (through
        # remote-imported blocks) land in remote_hit_tokens — keeping
        # hit_rate/token_hit_rate comparable to the pre-fleet stamps
        self.lookups = 0
        self.hits = 0
        self.lookup_tokens = 0
        self.hit_tokens = 0
        self.remote_hits = 0
        self.remote_hit_tokens = 0
        self.evictions = 0

    @property
    def block_count(self) -> int:
        """Blocks the cache currently holds a reference on."""
        return self._nodes

    def _keys(self, tokens) -> list[tuple]:
        bs = self.alloc.block_size
        return [tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
                for i in range(len(tokens) // bs)]

    def match(self, tokens) -> list[int]:
        """Physical blocks backing the longest cached full-block prefix
        of ``tokens`` (possibly empty). Does NOT take references — the
        caller increfs the blocks it actually admits — and does NOT
        count toward the hit-rate stats (a pool-starved admission
        re-matches every retry; the engine records ONE
        ``record_admission`` when the admission actually lands).
        Touches the walked nodes' LRU clocks."""
        return [n.block for n in self.match_nodes(tokens)]

    def match_nodes(self, tokens) -> list:
        """Like match(), but returns the NODES — callers that need the
        remote flag (steered-hit accounting) or the prefix digests read
        them off the chain."""
        node, out = self._root, []
        for key in self._keys(tokens):
            child = node.children.get(key)
            if child is None:
                break
            child.last_use = next(self._clock)
            out.append(child)
            node = child
        return out

    def record_admission(self, matched_blocks: int, lookup_tokens: int,
                         remote_blocks: int = 0) -> None:
        """Fold one LANDED admission into the hit-rate counters.
        ``remote_blocks`` (of the matched) came from fleet-shipped
        prefix imports — they count as STEERED hits, kept out of the
        local hit_rate so it stays comparable across fleet topologies."""
        self.lookups += 1
        self.lookup_tokens += lookup_tokens
        local = matched_blocks - remote_blocks
        if local:
            self.hits += 1
            self.hit_tokens += local * self.alloc.block_size
        if remote_blocks:
            self.remote_hits += 1
            self.remote_hit_tokens += remote_blocks * self.alloc.block_size

    def insert(self, tokens, blocks, remote: bool = False) -> int:
        """Register ``blocks`` as the cache entries for the full-block
        prefix of ``tokens`` (``len(blocks)`` blocks' worth). Prefix
        nodes that already exist keep their block (the caller was
        admitted THROUGH them, so blocks[i] is the same physical id);
        new nodes take one allocator reference each and are stamped
        ``remote`` when their K/V arrived over the fleet KV stream.
        Returns how many new blocks were cached."""
        hashes = block_hashes(tokens, self.alloc.block_size)
        node, added = self._root, 0
        for key, block, hsh in zip(self._keys(tokens), blocks, hashes):
            child = node.children.get(key)
            if child is None:
                self.alloc.incref(block)
                child = _RadixNode(node, key, block, hash=hsh,
                                   remote=remote)
                node.children[key] = child
                self._nodes += 1
                added += 1
            child.last_use = next(self._clock)
            node = child
        return added

    def frontier(self, limit: int = 64) -> list[str]:
        """The most-recently-used ``limit`` cached prefix digests — what
        health() publishes for the router's FleetPrefixIndex. Every
        cached node's digest is a candidate (an internal node is a
        valid shorter match for a prompt that diverges below it);
        recency-bounded so a subprocess replica's health row stays a
        small JSON line, and hot prefixes (the ones worth steering
        toward) survive the bound."""
        nodes: list[_RadixNode] = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            nodes.append(n)
            stack.extend(n.children.values())
        nodes.sort(key=lambda n: n.last_use, reverse=True)
        return [n.hash for n in nodes[:limit]]

    def _evictable_leaves(self) -> list[_RadixNode]:
        out = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif self.alloc.refcount(n.block) == 1:
                # the cache is the sole owner: evicting actually frees
                out.append(n)
        return out

    def evictable_count(self) -> int:
        """How many blocks cascading leaf eviction could actually free:
        sole-owner nodes whose entire subtree is sole-owner too (a
        shared descendant pins its whole ancestor chain, since only
        leaves ever drop). Lets the engine check feasibility BEFORE
        destroying reusable prefixes on a reclaim that cannot cover the
        allocation anyway."""
        # iterative post-order (a full-length cached prompt is a chain
        # max_seq_len/block_size deep — don't lean on the recursion
        # limit): freeable(node) = all children freeable AND sole-owner
        order: list[_RadixNode] = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            order.append(n)
            stack.extend(n.children.values())
        total = 0
        freeable: dict[int, bool] = {}
        for n in reversed(order):  # children before parents
            ok = (all(freeable[id(c)] for c in n.children.values())
                  and self.alloc.refcount(n.block) == 1)
            freeable[id(n)] = ok
            total += ok
        return total

    def reclaim(self, n: int) -> int:
        """Evict LRU sole-owner leaves until ``n`` blocks have freed (or
        nothing evictable remains). Returns blocks actually freed."""
        freed = 0
        while freed < n:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            for leaf in sorted(leaves, key=lambda x: x.last_use):
                if freed >= n:
                    break
                self._drop(leaf)
                freed += 1
        return freed

    def _drop(self, node: _RadixNode) -> None:
        del node.parent.children[node.key]
        self.alloc.decref(node.block)
        self._nodes -= 1
        self.evictions += 1

    def clear(self) -> int:
        """Release every cached block (teardown / post-warmup flush)."""
        freed = 0
        stack = list(self._root.children.values())
        order = []
        while stack:
            n = stack.pop()
            order.append(n)
            stack.extend(n.children.values())
        for n in reversed(order):  # children before parents
            self._drop(n)
            freed += 1
        return freed

    def reset_stats(self) -> None:
        """Zero the hit-rate counters (post-warmup flush) — cached
        content and LRU state are untouched."""
        self.lookups = self.hits = 0
        self.lookup_tokens = self.hit_tokens = 0
        self.remote_hits = self.remote_hit_tokens = 0
        self.evictions = 0

    def stats(self) -> dict:
        return {
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_rate": (round(self.hits / self.lookups, 4)
                         if self.lookups else None),
            "hit_tokens": self.hit_tokens,
            "lookup_tokens": self.lookup_tokens,
            "token_hit_rate": (
                round(self.hit_tokens / self.lookup_tokens, 4)
                if self.lookup_tokens else None),
            # steered hits (fleet-shipped prefix blocks) — split out so
            # hit_rate above stays the LOCAL rate, comparable to the
            # per-engine stamps from before the fleet index existed
            "remote_hits": self.remote_hits,
            "remote_hit_tokens": self.remote_hit_tokens,
            "remote_token_hit_rate": (
                round(self.remote_hit_tokens / self.lookup_tokens, 4)
                if self.lookup_tokens else None),
            "cached_blocks": self._nodes,
            "evictions": self.evictions,
        }


class FleetPrefixIndex:
    """The router-owned fleet-wide view of every replica's radix
    frontier (the tentpole's cross-replica half): each replica publishes
    its cached prefix digests (``RadixPrefixCache.frontier()``) through
    ``health()`` snapshots; the dispatcher asks this index which replica
    holds the LONGEST cached prefix of an incoming prompt's digest chain
    (``block_hashes``) and steers the request there — or, when the owner
    can't take it, ships the matched blocks over the KV stream so a hot
    system prompt is prefilled once per fleet, not once per replica.
    Pure host state; refreshed (not accumulated) per snapshot, so a
    replica's evictions and deaths age out of the index naturally."""

    def __init__(self):
        self._frontiers: dict[int, set[str]] = {}

    def update(self, replica: int, hashes) -> None:
        """Replace ``replica``'s published frontier with this snapshot's."""
        self._frontiers[replica] = set(hashes or ())

    def add(self, replica: int, hashes) -> None:
        """Extend ``replica``'s frontier in place — the router's
        optimistic bookkeeping right after a prefix ship, so a burst of
        same-prefix arrivals doesn't re-ship the same blocks every
        dispatch until the next health snapshot replaces the set."""
        self._frontiers.setdefault(replica, set()).update(hashes or ())

    def remove(self, replica: int) -> None:
        self._frontiers.pop(replica, None)

    def match_depth(self, replica: int, hash_chain) -> int:
        """Longest prefix (in blocks) of ``hash_chain`` this replica
        published. Digests are chained, so membership of ``chain[i]``
        alone proves the whole i+1-block prefix is cached there."""
        have = self._frontiers.get(replica)
        if not have:
            return 0
        depth = 0
        for h in hash_chain:
            if h not in have:
                break
            depth += 1
        return depth

    def best_match(self, hash_chain, eligible=None) -> tuple[int | None,
                                                             int]:
        """(replica, depth) of the deepest published match — the
        steering target. ``eligible`` restricts candidates; ties break
        toward the lowest replica index (deterministic). (None, 0) when
        nobody holds any prefix of the chain."""
        best, best_depth = None, 0
        for rep in sorted(self._frontiers):
            if eligible is not None and rep not in eligible:
                continue
            d = self.match_depth(rep, hash_chain)
            if d > best_depth:
                best, best_depth = rep, d
        return best, best_depth

    def replicas(self) -> list[int]:
        return sorted(self._frontiers)


class FleetSessionIndex:
    """FleetPrefixIndex's sibling for persistent sessions (ISSUE 18):
    the router-owned map of which replica holds a session RESIDENT in
    its HBM tier (blocks parked after stream close). Replicas publish
    their resident session ids through ``health()`` snapshots
    (``session_frontier``); the dispatcher steers a reattaching
    ``submit(session_id=...)`` to the owner — a zero-copy radix
    re-seed there — before falling back to the router's host-DRAM/disk
    ``SessionStore`` tiers. Pure host state; refreshed (not
    accumulated) per snapshot, so demotions, evictions and replica
    deaths age out naturally."""

    def __init__(self):
        self._resident: dict[int, set[str]] = {}

    def update(self, replica: int, session_ids) -> None:
        """Replace ``replica``'s published resident set."""
        self._resident[replica] = set(session_ids or ())

    def add(self, replica: int, session_id: str) -> None:
        """Optimistic bookkeeping right after a steered reattach or a
        finished session stream — the owner answers for the session
        before the next health snapshot confirms it."""
        self._resident.setdefault(replica, set()).add(session_id)

    def discard(self, session_id: str) -> None:
        """Forget a session fleet-wide (demoted into the store, or
        dropped)."""
        for have in self._resident.values():
            have.discard(session_id)

    def remove(self, replica: int) -> None:
        self._resident.pop(replica, None)

    def owner(self, session_id: str, eligible=None) -> int | None:
        """The replica holding ``session_id`` resident, or None. Ties
        (stale overlapping snapshots) break toward the lowest index —
        deterministic steering, exactly like best_match."""
        for rep in sorted(self._resident):
            if eligible is not None and rep not in eligible:
                continue
            if session_id in self._resident[rep]:
                return rep
        return None

    def sessions(self, replica: int) -> set[str]:
        return set(self._resident.get(replica, ()))
