"""Host-side bookkeeping for the paged KV cache (ISSUE 7).

The device half of paging lives in models/transformer.py (block pool +
block-table gather inside the compiled tick) and serving/engine.py (the
jitted paged tick / chunked prefill). Everything here is pure-Python
state the scheduler mutates between compiled calls:

  * `BlockAllocator` — a refcounted free list over the physical pool.
    Block 0 is reserved as the TRASH block: retired slots' table entries
    (and pad positions of chunked prefills) point at it, so their garbage
    writes can never land in a block another request owns. A block frees
    when its last reference drops — a slot's table entry and a radix-
    cache node each hold one.
  * `RadixPrefixCache` — a block-granularity radix tree over prompt
    token ids (SGLang's RadixAttention at vLLM's block alignment): a
    node caches ONE full block (`block_size` tokens) of K/V under its
    parent's prefix. Admission walks the new prompt's full blocks down
    the tree; every hit is admitted by *reference* (the slot's table
    points at the cached physical block) instead of re-running prefill.
    Only whole blocks are ever shared, and a slot's writes always land
    in blocks it privately owns (its first unmatched block onward), so
    the copy-on-write discipline holds by construction — divergence
    within a block simply misses the cache and prefills a private copy.
    Eviction is LRU over leaf nodes whose block the cache is the sole
    owner of (evicting a block an active slot still reads would free
    nothing and lose reuse).

The leak invariant the engine asserts at teardown:
``free + resident == usable`` — every non-trash block is either on the
free list or accounted to at least one live reference.
"""

from __future__ import annotations

import itertools


class BlockAllocator:
    """Refcounted free-list allocator over ``num_blocks`` physical KV
    blocks of ``block_size`` tokens. Block 0 is the reserved trash block
    and is never handed out."""

    TRASH = 0

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 2:
            raise ValueError(
                f"num_blocks {num_blocks} must be >= 2 (block 0 is the "
                f"reserved trash block)")
        if block_size < 1:
            raise ValueError(f"block_size {block_size} must be >= 1")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # pop() hands out low block ids first (1, 2, ...)
        self._free = list(range(num_blocks - 1, 0, -1))
        self._refs: dict[int, int] = {}

    @property
    def usable(self) -> int:
        """Allocatable blocks (the pool minus the trash block)."""
        return self.num_blocks - 1

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def resident(self) -> int:
        """Blocks currently referenced by at least one owner."""
        return len(self._refs)

    def refcount(self, block: int) -> int:
        return self._refs.get(block, 0)

    def can_alloc(self, n: int) -> bool:
        return n <= len(self._free)

    def alloc(self, n: int) -> list[int] | None:
        """n fresh blocks at refcount 1, or None if the free list is
        short (the caller decides: evict prefix cache, preempt, or
        wait)."""
        if n > len(self._free):
            return None
        blocks = [self._free.pop() for _ in range(n)]
        for b in blocks:
            self._refs[b] = 1
        return blocks

    def incref(self, block: int) -> None:
        if block == self.TRASH:
            raise ValueError("cannot reference the trash block")
        if block not in self._refs:
            raise ValueError(f"block {block} is not allocated")
        self._refs[block] += 1

    def decref(self, block: int) -> bool:
        """Drop one reference; returns True when the block actually
        freed back to the pool."""
        rc = self._refs.get(block)
        if rc is None:
            raise ValueError(f"block {block} is not allocated")
        if rc > 1:
            self._refs[block] = rc - 1
            return False
        del self._refs[block]
        self._free.append(block)
        return True

    def check_leaks(self, expected_resident: int = 0) -> None:
        """The teardown invariant: free + resident == usable, and — once
        every owner has released (slots retired, radix cleared) —
        resident is exactly ``expected_resident``."""
        if self.free_count + self.resident != self.usable:
            raise AssertionError(
                f"KV block leak: free {self.free_count} + resident "
                f"{self.resident} != usable {self.usable} "
                f"(held: {sorted(self._refs)})")
        if self.resident != expected_resident:
            raise AssertionError(
                f"KV block leak: {self.resident} blocks still referenced "
                f"at teardown (expected {expected_resident}): "
                f"{sorted(self._refs)}")


class _RadixNode:
    __slots__ = ("children", "parent", "key", "block", "last_use")

    def __init__(self, parent, key, block):
        self.children: dict[tuple, _RadixNode] = {}
        self.parent = parent
        self.key = key
        self.block = block
        self.last_use = 0


class RadixPrefixCache:
    """Block-granularity radix tree mapping full-block token prefixes to
    the physical pool blocks holding their K/V. Each node owns one
    allocator reference on its block, so cached prefixes survive the
    admitting request's retirement and free only on eviction."""

    def __init__(self, allocator: BlockAllocator):
        self.alloc = allocator
        self._root = _RadixNode(None, None, None)
        self._clock = itertools.count(1)
        self._nodes = 0
        # admission-level counters the engine folds into its summary
        self.lookups = 0
        self.hits = 0
        self.lookup_tokens = 0
        self.hit_tokens = 0
        self.evictions = 0

    @property
    def block_count(self) -> int:
        """Blocks the cache currently holds a reference on."""
        return self._nodes

    def _keys(self, tokens) -> list[tuple]:
        bs = self.alloc.block_size
        return [tuple(int(t) for t in tokens[i * bs:(i + 1) * bs])
                for i in range(len(tokens) // bs)]

    def match(self, tokens) -> list[int]:
        """Physical blocks backing the longest cached full-block prefix
        of ``tokens`` (possibly empty). Does NOT take references — the
        caller increfs the blocks it actually admits — and does NOT
        count toward the hit-rate stats (a pool-starved admission
        re-matches every retry; the engine records ONE
        ``record_admission`` when the admission actually lands).
        Touches the walked nodes' LRU clocks."""
        node, out = self._root, []
        for key in self._keys(tokens):
            child = node.children.get(key)
            if child is None:
                break
            child.last_use = next(self._clock)
            out.append(child.block)
            node = child
        return out

    def record_admission(self, matched_blocks: int,
                         lookup_tokens: int) -> None:
        """Fold one LANDED admission into the hit-rate counters."""
        self.lookups += 1
        self.lookup_tokens += lookup_tokens
        if matched_blocks:
            self.hits += 1
            self.hit_tokens += matched_blocks * self.alloc.block_size

    def insert(self, tokens, blocks) -> int:
        """Register ``blocks`` as the cache entries for the full-block
        prefix of ``tokens`` (``len(blocks)`` blocks' worth). Prefix
        nodes that already exist keep their block (the caller was
        admitted THROUGH them, so blocks[i] is the same physical id);
        new nodes take one allocator reference each. Returns how many
        new blocks were cached."""
        node, added = self._root, 0
        for key, block in zip(self._keys(tokens), blocks):
            child = node.children.get(key)
            if child is None:
                self.alloc.incref(block)
                child = _RadixNode(node, key, block)
                node.children[key] = child
                self._nodes += 1
                added += 1
            child.last_use = next(self._clock)
            node = child
        return added

    def _evictable_leaves(self) -> list[_RadixNode]:
        out = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            if n.children:
                stack.extend(n.children.values())
            elif self.alloc.refcount(n.block) == 1:
                # the cache is the sole owner: evicting actually frees
                out.append(n)
        return out

    def evictable_count(self) -> int:
        """How many blocks cascading leaf eviction could actually free:
        sole-owner nodes whose entire subtree is sole-owner too (a
        shared descendant pins its whole ancestor chain, since only
        leaves ever drop). Lets the engine check feasibility BEFORE
        destroying reusable prefixes on a reclaim that cannot cover the
        allocation anyway."""
        # iterative post-order (a full-length cached prompt is a chain
        # max_seq_len/block_size deep — don't lean on the recursion
        # limit): freeable(node) = all children freeable AND sole-owner
        order: list[_RadixNode] = []
        stack = list(self._root.children.values())
        while stack:
            n = stack.pop()
            order.append(n)
            stack.extend(n.children.values())
        total = 0
        freeable: dict[int, bool] = {}
        for n in reversed(order):  # children before parents
            ok = (all(freeable[id(c)] for c in n.children.values())
                  and self.alloc.refcount(n.block) == 1)
            freeable[id(n)] = ok
            total += ok
        return total

    def reclaim(self, n: int) -> int:
        """Evict LRU sole-owner leaves until ``n`` blocks have freed (or
        nothing evictable remains). Returns blocks actually freed."""
        freed = 0
        while freed < n:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            for leaf in sorted(leaves, key=lambda x: x.last_use):
                if freed >= n:
                    break
                self._drop(leaf)
                freed += 1
        return freed

    def _drop(self, node: _RadixNode) -> None:
        del node.parent.children[node.key]
        self.alloc.decref(node.block)
        self._nodes -= 1
        self.evictions += 1

    def clear(self) -> int:
        """Release every cached block (teardown / post-warmup flush)."""
        freed = 0
        stack = list(self._root.children.values())
        order = []
        while stack:
            n = stack.pop()
            order.append(n)
            stack.extend(n.children.values())
        for n in reversed(order):  # children before parents
            self._drop(n)
            freed += 1
        return freed

    def reset_stats(self) -> None:
        """Zero the hit-rate counters (post-warmup flush) — cached
        content and LRU state are untouched."""
        self.lookups = self.hits = 0
        self.lookup_tokens = self.hit_tokens = 0
        self.evictions = 0

    def stats(self) -> dict:
        return {
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_rate": (round(self.hits / self.lookups, 4)
                         if self.lookups else None),
            "hit_tokens": self.hit_tokens,
            "lookup_tokens": self.lookup_tokens,
            "token_hit_rate": (
                round(self.hit_tokens / self.lookup_tokens, 4)
                if self.lookup_tokens else None),
            "cached_blocks": self._nodes,
            "evictions": self.evictions,
        }
