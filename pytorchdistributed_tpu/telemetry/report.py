"""The run report: one terminal answer to "how did that run go?".

    python -m pytorchdistributed_tpu.telemetry report <run-dir>

Merges everything a telemetry-enabled run (`Trainer(telemetry_dir=...)`
or `run.py --telemetry-dir`) leaves behind in one directory:

  * ``metrics_rank*.jsonl``  — per-rank step metrics (loss, samples/s,
    step time, tokens/s, MFU, comm-bytes/step at log cadence);
  * ``spans_rank*.trace.json`` — host-span traces (where host time went);
  * ``events_rank*.jsonl``   — anomaly tripwire events;
  * ``diagnostics_rank*.jsonl`` — in-graph model-health stream (ISSUE 6:
    grad-norm groups, update/param ratio, activation health, NaN
    provenance; per-layer tables at the configured cadence);
  * ``accounting.json``      — the StepAccounting compile-time facts;
  * a `jax.profiler` capture under the dir (``plugins/profile/...``), if
    the run pointed ``profile_dir`` into it — summarized via
    utils/trace.py with auto-detected step count.

Pure stdlib + the repo's own readers; no device work or backend init, so
the report runs on a machine that never touched the job (copy the run
dir home, read it there).
"""

from __future__ import annotations

import glob
import json
import os

from pytorchdistributed_tpu.telemetry.accounting import StepAccounting
from pytorchdistributed_tpu.telemetry.diagnostics import DIAG_GLOB
from pytorchdistributed_tpu.telemetry.events import (
    METRICS_GLOB,
    read_events,
)
from pytorchdistributed_tpu.telemetry.spans import SPAN_TRACE_GLOB

ACCOUNTING_FILE = "accounting.json"


def _fmt_bytes(n: float | int | None) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:.1f} GiB"


def _read_rank_rows(run_dir: str, glob_pat: str,
                    prefix: str) -> dict[int, list[dict]]:
    """{rank: JSONL rows} for any per-rank ``<prefix><R>.jsonl`` stream
    (metrics and diagnostics share the exact reader: rank parsed from the
    filename, torn final lines of a killed rank skipped)."""
    rows: dict[int, list[dict]] = {}
    for path in sorted(glob.glob(os.path.join(run_dir, glob_pat))):
        base = os.path.basename(path)
        try:
            rank = int(base[len(prefix):-len(".jsonl")])
        except ValueError:
            continue
        out = []
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        try:
                            out.append(json.loads(line))
                        except json.JSONDecodeError:
                            continue  # torn final line
        except OSError:
            continue
        rows[rank] = out
    return rows


def _read_metric_rows(run_dir: str) -> dict[int, list[dict]]:
    return _read_rank_rows(run_dir, METRICS_GLOB, "metrics_rank")


def _mean_of(rows: list[dict], key: str) -> float | None:
    vals = [float(r[key]) for r in rows if key in r
            and isinstance(r[key], (int, float))]
    vals = [v for v in vals if v == v]  # drop NaN
    return sum(vals) / len(vals) if vals else None


def _derive_step_time(rows: list[dict]) -> float | None:
    """Fallback when rows carry no step_time_s (no accounting): wall time
    between logged rows over the steps covered. Step numbers reset each
    epoch, so the count accumulates per consecutive pair — an epoch
    rollover contributes the new epoch's step offset (the unlogged tail
    of the previous epoch, at most log_every-1 steps, is approximated
    away rather than inflating the result)."""
    direct = _mean_of(rows, "step_time_s")
    if direct is not None:
        return direct
    pts = [(r["time"], r.get("epoch", 0), r["step"]) for r in rows
           if "time" in r and "step" in r]
    if len(pts) < 2:
        return None
    steps = 0
    for (_, e0, s0), (_, e1, s1) in zip(pts, pts[1:]):
        steps += (s1 - s0) if e1 == e0 else s1
    dt = pts[-1][0] - pts[0][0]
    return dt / steps if steps > 0 and dt > 0 else None


def _read_span_totals(run_dir: str) -> dict[int, dict[str, tuple[float, int]]]:
    """{rank: {span name: (total ms, count)}} from the dumped traces."""
    out: dict[int, dict[str, tuple[float, int]]] = {}
    for path in sorted(glob.glob(os.path.join(run_dir, SPAN_TRACE_GLOB))):
        base = os.path.basename(path)
        try:
            rank = int(base[len("spans_rank"):-len(".trace.json")])
        except ValueError:
            continue
        try:
            with open(path) as f:
                events = json.load(f).get("traceEvents", [])
        except (OSError, json.JSONDecodeError):
            continue
        totals: dict[str, list] = {}
        for e in events:
            if e.get("ph") != "X":
                continue
            r = totals.setdefault(e["name"], [0.0, 0])
            r[0] += e.get("dur", 0) / 1e3  # µs -> ms
            r[1] += 1
        out[rank] = {k: (v[0], v[1]) for k, v in totals.items()}
    return out


def _read_diag_rows(run_dir: str) -> dict[int, list[dict]]:
    """{rank: rows} from the per-rank diagnostics JSONL (ISSUE 6 —
    telemetry/diagnostics.py DIAG_FILE contract); empty streams are
    dropped so the layer-health section can index the last row."""
    return {rank: rows for rank, rows in _read_rank_rows(
        run_dir, DIAG_GLOB, "diagnostics_rank").items() if rows}


def _layer_health_section(run_dir: str) -> list[str]:
    """The layer-health table: the LAST per-layer table row each rank's
    diagnostics stream carries, rendered one line per layer, plus the
    freshest scalar health summary. Reads rank 0's stream (ranks run the
    same program; per-rank divergence shows up in the events table)."""
    rows_by_rank = _read_diag_rows(run_dir)
    if not rows_by_rank:
        return ["layer health: no diagnostics stream (run with "
                "Trainer(diagnostics='scalars'|'full[:N]') or "
                "PTD_DIAGNOSTICS)"]
    rank = min(rows_by_rank)
    rows = rows_by_rank[rank]
    last = rows[-1]
    lines = []
    scalars = {k: v for k, v in last.items()
               if k.startswith("diag/") and isinstance(v, (int, float))}
    lines.append(f"diagnostics (rank {rank}, step {last.get('step', '-')}, "
                 f"{len(rows)} rows):")
    if scalars:
        lines.append("  " + "  ".join(
            f"{k[len('diag/'):]}={v:.4g}" for k, v in sorted(
                scalars.items())))
    table_row = next((r for r in reversed(rows) if r.get("layers")), None)
    if table_row is None:
        lines.append("  per-layer tables: none written (scalar cadence — "
                     "use diagnostics='full[:N]')")
        return lines
    layers = table_row["layers"]
    cols = sorted(layers)
    n = max(len(v) for v in layers.values())
    lines.append(f"  layer health (step {table_row.get('step', '-')}):")
    lines.append("    " + f"{'layer':>5}  " + "  ".join(
        f"{c:>14}" for c in cols))
    for i in range(n):
        cells = []
        for c in cols:
            v = layers[c]
            cells.append(f"{v[i]:>14.6g}" if i < len(v) else f"{'-':>14}")
        marker = ""
        nf = layers.get("act_nonfinite")
        if nf and i < len(nf) and nf[i] > 0:
            marker = "  <- non-finite"
        lines.append("    " + f"{i:>5}  " + "  ".join(cells) + marker)
    return lines


def _serving_section(run_dir: str) -> list[str]:
    """The serving / prefix-cache table (ISSUE 7): aggregate the
    ``serve_metrics_rank*.jsonl`` streams a ServingEngine leaves behind —
    per-request TTFT/hit rows plus the paged pool summary row close()
    stamps. Silent (empty) when the run never served."""
    from pytorchdistributed_tpu.serving.telemetry import SERVE_METRICS_GLOB

    rows_by_rank = _read_rank_rows(run_dir, SERVE_METRICS_GLOB,
                                   "serve_metrics_rank")
    if not rows_by_rank:
        return []
    lines = ["serving (per rank: requests / TTFT / prefix cache / "
             "speculation):"]
    lines.append(f"  {'rank':>4}  {'reqs':>5}  {'ttft p50':>9}  "
                 f"{'hit tok':>8}  {'hit rate':>8}  {'chunks':>6}  "
                 f"{'preempt':>7}  {'acc rate':>8}  {'cached blk':>10}  "
                 f"{'kv hbm':>9}  {'kv resident':>11}")
    for rank, rows in sorted(rows_by_rank.items()):
        reqs = [r for r in rows if r.get("kind") == "request"]
        pool = next((r for r in reversed(rows)
                     if r.get("kind") == "pool"), None)
        ttfts = sorted(r["ttft_ms"] for r in reqs
                       if r.get("ttft_ms") is not None)
        p50 = (f"{ttfts[len(ttfts) // 2]:.1f} ms" if ttfts else "-")
        hit_tok = sum(r.get("prefix_hit_tokens") or 0 for r in reqs)
        # rate against ADMITTED tokens (the pool row counts every
        # admission, preempt-resumes included — per-request prompt_len
        # is counted once, so hit tokens accumulated across a request's
        # re-admissions would read as > 100% sharing against it)
        denom = (pool.get("admitted_tokens") if pool else None) or sum(
            r.get("prompt_len") or 0 for r in reqs)
        rate = f"{hit_tok / denom:.2%}" if denom else "-"
        chunks = sum(r.get("prefill_chunks") or 0 for r in reqs)
        preempt = sum(r.get("preemptions") or 0 for r in reqs)
        # speculative-decoding health (ISSUE 8): accepted / proposed
        # draft tokens across the rank's requests — "-" when spec is off
        drafted = sum(r.get("draft_tokens") or 0 for r in reqs)
        accepted = sum(r.get("accepted_tokens") or 0 for r in reqs)
        acc = f"{accepted / drafted:.2%}" if drafted else "-"
        cached = pool.get("cached_blocks", "-") if pool else "-"
        hbm = _fmt_bytes(pool.get("kv_hbm_bytes")) if pool else "-"
        # KV compression (ISSUE 13): high-water bytes actually resident
        # in KV blocks (scale planes included) — against "kv hbm" (the
        # allocated pool) this reads as the compression/occupancy win
        resident = (_fmt_bytes(pool.get("kv_bytes_resident"))
                    if pool and pool.get("kv_bytes_resident") is not None
                    else "-")
        lines.append(f"  {rank:>4}  {len(reqs):>5}  {p50:>9}  "
                     f"{hit_tok:>8}  {rate:>8}  {chunks:>6}  "
                     f"{preempt:>7}  {acc:>8}  {cached!s:>10}  {hbm:>9}  "
                     f"{resident:>11}")
    pools = [r for rows in rows_by_rank.values() for r in rows
             if r.get("kind") == "pool"]
    if pools:
        # pool geometry is per-engine (take any row); the cache counters
        # sum across ranks so the line reads as the fleet's behavior
        p = pools[-1]
        hits = sum(r.get("hits") or 0 for r in pools)
        lookups = sum(r.get("lookups") or 0 for r in pools)
        evictions = sum(r.get("evictions") or 0 for r in pools)
        # effective capacity: tokens the pool can hold at its storage
        # dtype — the same HBM backs ~1.9x the tokens at int8
        cap = p.get("kv_tokens_capacity")
        eff = (f", capacity {cap} tokens @ {p.get('kv_dtype', 'bf16')}"
               if cap else "")
        retired = sum(r.get("retired_blocks") or 0 for r in pools)
        ret = f", {retired} blocks window-retired" if retired else ""
        lines.append(
            f"  pool: {p.get('num_blocks', '-')} x "
            f"{p.get('block_size', '-')}-token blocks, "
            f"cache {hits}/{lookups} lookups hit, "
            f"{evictions} evictions{eff}{ret}")
        if any(r.get("spec_k") for r in pools):
            drafted = sum(r.get("draft_tokens") or 0 for r in pools)
            accepted = sum(r.get("accepted_tokens") or 0 for r in pools)
            # learned-drafting identity (ISSUE 16): which draft served
            # this engine (params fingerprint + hot-swap count, proposal
            # heads), and — adaptive-k runs — the close-time acceptance
            # EMA and the effective depth it settled at
            heads = p.get("spec_heads")
            extra = f", {heads} proposal heads" if heads else ""
            if p.get("draft_params_hash"):
                extra += f", draft {p['draft_params_hash']}"
            swaps = sum(r.get("draft_swaps") or 0 for r in pools)
            if swaps:
                extra += f" ({swaps} hot-swaps)"
            if p.get("accept_ema") is not None:
                extra += (f", accept ema {p['accept_ema']:.2f}"
                          f" -> k_eff {p.get('effective_k', '-')}")
            lines.append(
                f"  speculation: k={p.get('spec_k')}, "
                f"{accepted}/{drafted} draft tokens accepted{extra}")
    return lines


def _trace_section(run_dir: str, top: int) -> list[str]:
    """The distributed request-trace table (ISSUE 17): the per-request
    critical-path breakdown + per-tenant SLO-debt attribution merged
    from the ``trace_rank*.jsonl`` files a traced fleet leaves behind.
    Silent when the run never traced."""
    import glob as _glob

    from pytorchdistributed_tpu.telemetry.tracing import (
        TRACE_GLOB,
        render_trace,
    )

    if not _glob.glob(os.path.join(run_dir, TRACE_GLOB)):
        return []
    return render_trace(run_dir, top=top).splitlines()


def _router_section(run_dir: str) -> list[str]:
    """The replica-router table (ISSUE 9): aggregate the
    ``router_metrics_rank*.jsonl`` streams a ReplicaRouter leaves behind
    — the close-time summary row plus per-replica status/occupancy and
    the failover/quarantine event trail. Silent when no router ran."""
    from pytorchdistributed_tpu.serving.telemetry import (
        ROUTER_METRICS_GLOB,
    )

    rows_by_rank = _read_rank_rows(run_dir, ROUTER_METRICS_GLOB,
                                   "router_metrics_rank")
    if not rows_by_rank:
        return []
    lines = []
    for rank, rows in sorted(rows_by_rank.items()):
        summary = next((r for r in reversed(rows)
                        if r.get("kind") == "router"), None)
        events = [r for r in rows if r.get("kind") == "event"]
        samples = [r for r in rows if r.get("kind") == "replica"]
        lines.append(f"replica router (rank {rank}):")
        if summary is not None:
            shed = summary.get("shed_rate")
            rec = summary.get("failover_recovery_ticks")
            lines.append(
                f"  submitted {summary.get('submitted', 0)}  "
                f"completed {summary.get('completed', 0)}  "
                f"shed {summary.get('shed_requests', 0)}"
                + (f" ({shed:.1%})" if shed is not None else "")
                + f"  failovers {summary.get('failovers', 0)}  "
                f"redispatched {summary.get('redispatched_requests', 0)}  "
                f"quarantines {summary.get('quarantines', 0)}  "
                f"rejoins {summary.get('rejoins', 0)}  "
                f"respawns {summary.get('respawns', 0)}"
                + (f"  recovery {rec} ticks" if rec is not None else "")
                + (f"  draft_swaps {summary.get('draft_swaps')}"
                   if summary.get("draft_swaps") else ""))
            if (summary.get("handoffs") or summary.get("prefix_ships")
                    or summary.get("cross_replica_hit_rate")):
                # the disaggregation line (ISSUE 12): KV handoff +
                # fleet-prefix traffic — absent on a colocated fleet
                xr = summary.get("cross_replica_hit_rate")
                lines.append(
                    f"  handoffs {summary.get('handoffs', 0)}  "
                    f"handoff_failures "
                    f"{summary.get('handoff_failures', 0)}  "
                    f"prefix_ships {summary.get('prefix_ships', 0)}  "
                    f"kv_stream "
                    f"{summary.get('kv_stream_bytes', 0) / 1e6:.2f} MB"
                    + (f"  cross_replica_hit_rate {xr:.1%}"
                       if xr is not None else ""))
        n_replicas = (summary.get("replicas") if summary
                      else 1 + max((s.get("replica", 0)
                                    for s in samples), default=0))
        occ = (summary or {}).get("replica_occupancy") or []
        served = {int(k): v for k, v in
                  ((summary or {}).get("served_by") or {}).items()}
        roles = (summary or {}).get("roles") or []
        # per-replica draft identity (ISSUE 16): the summary's ``draft``
        # map is close-time truth (params fingerprint + lifetime swap
        # count); the draft_swap event trail backs it when a replica
        # died (its map entry is popped) after absorbing a swap
        draft_map = {int(k): v for k, v in
                     ((summary or {}).get("draft") or {}).items()}
        draft_on = bool(draft_map) or any(
            e.get("event", "").startswith("draft_swap") for e in events)
        draft_hdr = (f"  {'draft':>8}  {'swaps':>5}" if draft_on else "")
        lines.append(f"  {'replica':>7}  {'role':>7}  {'status':>11}  "
                     f"{'served':>6}  "
                     f"{'occupancy':>9}  {'failovers':>9}  "
                     f"{'quarantines':>11}  {'rejoins':>7}  "
                     f"{'respawns':>8}  {'handoffs':>8}{draft_hdr}")
        for i in range(n_replicas or 0):
            status = next((s.get("status", "-") for s in reversed(samples)
                           if s.get("replica") == i), "-")
            role = roles[i] if i < len(roles) else "both"
            lost = sum(1 for e in events
                       if e.get("event") == "replica_dead"
                       and e.get("replica") == i)
            quar = sum(1 for e in events
                       if e.get("event") == "quarantine"
                       and e.get("replica") == i)
            rej = sum(1 for e in events
                      if e.get("event") == "rejoin"
                      and e.get("replica") == i)
            resp = sum(1 for e in events
                       if e.get("event") == "respawn"
                       and e.get("replica") == i)
            # a handoff touches two replicas: count both directions
            hoff = sum(1 for e in events
                       if e.get("event") == "handoff"
                       and i in (e.get("from_replica"),
                                 e.get("to_replica")))
            o = occ[i] if i < len(occ) and occ[i] is not None else None
            if draft_on:
                d = draft_map.get(i)
                if d is None:
                    # dead/swapped-out replica: fall back to its last
                    # draft_swap event so the trail stays readable
                    last = next((e for e in reversed(events)
                                 if e.get("event") == "draft_swap"
                                 and e.get("replica") == i), None)
                    d = (dict(draft_hash=last.get("hash"),
                              draft_swaps=last.get("swaps"))
                         if last else {})
                draft_col = (f"  {d.get('draft_hash') or '-':>8}  "
                             f"{d.get('draft_swaps', 0) or 0:>5}")
            else:
                draft_col = ""
            lines.append(
                f"  {i:>7}  {role:>7}  {status:>11}  {served.get(i, 0):>6}  "
                f"{(f'{o:.2%}' if o is not None else '-'):>9}  "
                f"{lost:>9}  {quar:>11}  {rej:>7}  {resp:>8}  {hoff:>8}"
                f"{draft_col}")
        tens = (summary or {}).get("tenants") or {}
        if tens:
            # the multi-tenant admission table (ISSUE 15): per-tenant
            # request accounting plus the WDRR weight and the signed
            # token overage the scheduler held it to (positive = served
            # beyond its weighted fair share; sheds land there first)
            lines.append(
                f"  {'tenant':>10}  {'submitted':>9}  {'completed':>9}  "
                f"{'shed':>5}  {'ttft_p99':>10}  {'weight':>6}  "
                f"{'overage':>8}")
            for name, t in sorted(tens.items()):
                p99 = t.get("ttft_ms_p99")
                wt, ov = t.get("weight"), t.get("overage")
                p99_s = f"{p99:.1f} ms" if p99 is not None else "-"
                wt_s = f"{wt:g}" if wt is not None else "-"
                ov_s = f"{ov:+.2f}" if ov is not None else "-"
                lines.append(
                    f"  {name:>10}  {t.get('submitted', 0):>9}  "
                    f"{t.get('completed', 0):>9}  {t.get('shed', 0):>5}  "
                    f"{p99_s:>10}  {wt_s:>6}  {ov_s:>8}")
        sess = (summary or {}).get("sessions") or {}
        if sess:
            # the persistent-session tier table (ISSUE 18): where
            # reattaching turns found their KV — resident HBM, the
            # store's host-DRAM tier, or disk — vs the loud lossless
            # re-prefill fallbacks
            store = sess.get("store") or {}
            rea = sess.get("reattach") or {}
            lines.append(
                f"  {'tier':>6}  {'sessions':>8}  {'bytes':>12}  "
                f"{'reattach':>8}")
            tiers = (
                ("hbm", sess.get("resident", 0), None),
                ("dram", store.get("dram_sessions"),
                 store.get("dram_bytes")),
                ("disk", store.get("disk_sessions"),
                 store.get("disk_bytes")),
            )
            for tier, n, nbytes in tiers:
                n_s = "-" if n is None else f"{n:g}"
                b_s = ("-" if nbytes is None
                       else f"{nbytes / 1e6:.2f} MB")
                lines.append(f"  {tier:>6}  {n_s:>8}  {b_s:>12}  "
                             f"{rea.get(tier, 0):>8}")
            extras = (f"  session_fallbacks {sess.get('fallbacks', 0)}"
                      f"  ships {sess.get('ships', 0)}"
                      f"  demotes {sess.get('demotes', 0)}")
            if store.get("quarantined") or store.get("torn"):
                extras += (f"  quarantined {store.get('quarantined', 0)}"
                           f"  torn {store.get('torn', 0)}")
            lines.append(extras)
        # the scaling timeline (ISSUE 15): autoscale_* rows are the
        # control loop's decisions (stamped with the breach that
        # justified them), scale_* the router acting on them (or an
        # operator's manual add/remove) — relative seconds from the
        # first event, so a flash crowd reads as a burst
        scaling = [e for e in events
                   if e.get("event") in ("autoscale_up", "autoscale_down",
                                         "scale_up", "scale_down")]
        if scaling:
            t0 = scaling[0].get("time", 0.0)
            lines.append("  scaling timeline:")
            for e in scaling:
                why = e.get("why")
                q = e.get("queue_depth")
                detail = f"  why={why}" if why else ""
                if q is not None:
                    detail += f"  queue={q:g}"
                if e.get("mode"):
                    detail += f"  mode={e['mode']}"
                lines.append(
                    f"    +{e.get('time', t0) - t0:6.2f}s  "
                    f"{e.get('event', '-'):<14}  "
                    f"replica {e.get('replica', '-')}{detail}")
        # the chaos recovery table (ISSUE 19): per fault class, how
        # many injections the schedule fired, how many the router
        # noticed (dead/quarantine/wire events), how many fully healed
        # (rejoin), and the injection→recovery MTTR distribution
        if any(e.get("event") in ("fault_injected", "wire_fault")
               for e in events):
            from pytorchdistributed_tpu.faults.chaos import (
                recovery_table,
            )

            rec_table = recovery_table(events)
            if rec_table:
                lines.append("  fault recovery (per class):")
                lines.append(
                    f"    {'fault':>14}  {'injected':>8}  "
                    f"{'detected':>8}  {'recovered':>9}  "
                    f"{'mttr_p50':>9}  {'mttr_p95':>9}  {'max':>8}")
                for kind, row in sorted(rec_table.items()):
                    def _s(v):
                        return f"{v:.2f}s" if v is not None else "-"
                    lines.append(
                        f"    {kind:>14}  {row['injected']:>8}  "
                        f"{row['detected']:>8}  {row['recovered']:>9}  "
                        f"{_s(row['mttr_p50_s']):>9}  "
                        f"{_s(row['mttr_p95_s']):>9}  "
                        f"{_s(row['mttr_max_s']):>8}")
    return lines


def _device_trace_section(run_dir: str, top: int) -> list[str]:
    if not glob.glob(os.path.join(run_dir, "**", "*.trace.json.gz"),
                     recursive=True):
        return ["device trace: none found (point Trainer(profile_dir=...) "
                "into the run dir to include one)"]
    # imported lazily: summarize is the one reader that may pull heavier
    # deps, and most run dirs carry no capture
    from pytorchdistributed_tpu.utils.trace import summarize

    try:
        return ["device trace summary (utils/trace.py):",
                summarize(run_dir, steps=None, top=top)]
    except Exception as e:
        return [f"device trace: unreadable ({e})"]


def render(run_dir: str | os.PathLike, *, top: int = 10) -> str:
    """The merged cross-rank run report as one printable string."""
    run_dir = str(run_dir)
    rows_by_rank = _read_metric_rows(run_dir)
    events = read_events(run_dir)
    span_totals = _read_span_totals(run_dir)
    acct = None
    acct_path = os.path.join(run_dir, ACCOUNTING_FILE)
    if os.path.exists(acct_path):
        try:
            acct = StepAccounting.load(acct_path)
        except Exception:
            pass

    lines = [f"telemetry run report: {run_dir}"]
    ranks = sorted(set(rows_by_rank) | set(span_totals)
                   | {e.rank for e in events})
    lines.append(f"ranks: {', '.join(map(str, ranks)) if ranks else 'none'}")
    lines.append("")

    # -- step accounting (compile-time facts) ------------------------------
    if acct is not None:
        sim = " (sim fallback)" if acct.peak_source == "cpu-sim-nominal" \
            else f" ({acct.peak_source})"
        lines.append("step accounting (per device, from the compiled step):")
        lines.append(f"  model flops/step: {acct.model_flops_per_step:.4g}")
        lines.append(f"  comm bytes/step:  "
                     f"{_fmt_bytes(acct.comm_bytes_per_step)}  "
                     + " ".join(f"{k}={_fmt_bytes(v)}"
                                for k, v in acct.comm_bytes_by_op.items()
                                if v))
        peak = (f"{acct.peak_flops_per_device:.4g}"
                if acct.peak_flops_per_device else "unknown")
        lines.append(f"  peak flops/device: {peak}{sim}  |  "
                     f"devices: {acct.n_devices}  |  global tokens/step: "
                     f"{acct.tokens_per_step}")
    else:
        lines.append("step accounting: no accounting.json "
                     "(run with Trainer(telemetry_dir=...))")
    lines.append("")

    # -- per-rank merged metrics -------------------------------------------
    lines.append(f"{'rank':>4}  {'steps':>5}  {'last':>5}  "
                 f"{'step time':>10}  {'tokens/s':>10}  {'mfu':>7}  "
                 f"{'comm/step':>10}  {'loss(last)':>10}  {'events':>6}")
    n_events_by_rank = {r: sum(1 for e in events if e.rank == r)
                        for r in ranks}
    for rank in ranks:
        rows = rows_by_rank.get(rank, [])
        step_time = _derive_step_time(rows)
        tokens_s = _mean_of(rows, "tokens_per_s")
        tokens_note = ""
        if tokens_s is None and acct is not None and step_time:
            tokens_s = acct.tokens_per_s(step_time)
        if tokens_s is None:
            # last resort is SAMPLES/s (no accounting to convert with) —
            # label it, or an LM run would misread by a factor of seq_len
            tokens_s = _mean_of(rows, "samples_per_s")
            if tokens_s is not None:
                tokens_note = " smp"
        mfu = _mean_of(rows, "mfu")
        if mfu is None and acct is not None and step_time:
            mfu = acct.mfu(step_time)
        comm = _mean_of(rows, "comm_bytes_per_step")
        if comm is None and acct is not None:
            comm = acct.comm_bytes_per_step
        last_loss = next((float(r["loss"]) for r in reversed(rows)
                          if "loss" in r), None)
        mfu_s = f"{mfu:.4f}" if mfu is not None else "-"
        if mfu is not None and acct is not None \
                and acct.peak_source == "cpu-sim-nominal":
            mfu_s += "*"
        step_s = f"{step_time * 1e3:.1f} ms" if step_time else "-"
        tok_s = (f"{tokens_s:.1f}{tokens_note}"
                 if tokens_s is not None else "-")
        loss_s = f"{last_loss:.4g}" if last_loss is not None else "-"
        lines.append(
            f"{rank:>4}  {len(rows):>5}  "
            f"{(rows[-1]['step'] if rows else '-'):>5}  "
            f"{step_s:>10}  {tok_s:>10}  "
            f"{mfu_s:>7}  {_fmt_bytes(comm):>10}  "
            f"{loss_s:>10}  "
            f"{n_events_by_rank.get(rank, 0):>6}")
    if acct is not None and acct.peak_source == "cpu-sim-nominal":
        lines.append("  (* MFU against the CPU-sim NOMINAL peak — not a "
                     "hardware utilization number)")
    lines.append("")

    # -- tripwire events ----------------------------------------------------
    if events:
        lines.append(f"tripwire events ({len(events)}):")
        for e in events[:50]:
            lines.append(f"  {e.describe()}")
        if len(events) > 50:
            lines.append(f"  ... and {len(events) - 50} more")
    else:
        lines.append("tripwire events: none")
    lines.append("")

    # -- layer health (in-graph diagnostics) --------------------------------
    lines.extend(_layer_health_section(run_dir))
    lines.append("")

    # -- serving / prefix cache ---------------------------------------------
    serving = _serving_section(run_dir)
    if serving:
        lines.extend(serving)
        lines.append("")

    # -- replica router -------------------------------------------------------
    router = _router_section(run_dir)
    if router:
        lines.extend(router)
        lines.append("")

    # -- request traces (ISSUE 17) --------------------------------------------
    traces = _trace_section(run_dir, top)
    if traces:
        lines.extend(traces)
        lines.append("")

    # -- host spans ----------------------------------------------------------
    if span_totals:
        lines.append("host spans (total ms / count):")
        for rank in sorted(span_totals):
            totals = span_totals[rank]
            ordered = sorted(totals.items(), key=lambda kv: -kv[1][0])[:top]
            lines.append(f"  rank {rank}: " + "  ".join(
                f"{name} {ms:.1f}/{n}" for name, (ms, n) in ordered))
    else:
        lines.append("host spans: none recorded")
    lines.append("")

    lines.extend(_device_trace_section(run_dir, top))
    return "\n".join(lines)
