"""Derived step metrics: turn wall-clock into MFU / tokens-per-s /
comm-bytes — the join of compile-time facts with runtime timing.

PR 1's HLO censuses (`utils/hlo.py`) already extract per-device flops and
the collective mix from a compiled train step, and the Trainer times
steps — but nobody joined the two, so the repo had no MFU or per-step
communication-volume number outside a hand-run profiler session.
`StepAccounting` is that join, built ONCE per (config, mesh, batch
shape) from the AOT-compiled step (`Trainer.lower_step(...).compile()`):

  * ``model_flops_per_step`` — XLA cost analysis, per device,
    post-partitioning (the same number the compiled-invariant tripwires
    pin, so an MFU-math regression trips in CI);
  * ``comm_bytes_per_step`` — `utils.hlo.collective_bytes` over the
    optimized HLO (collectives exist only post-SPMD-partitioning);
  * ``peak_flops_per_device`` — per-TPU-generation bf16 peak, with a
    NOMINAL CPU-sim fallback so the full metrics path runs (and is
    testable) without a chip; ``peak_source`` labels which was used so a
    sim MFU can never be mistaken for a hardware one.

Everything downstream is arithmetic on a measured sec/step: `mfu()`,
`tokens_per_s()`. The object is JSON-(de)serializable so rank 0 stamps
it into the telemetry run dir and the report CLI re-derives the numbers
offline.
"""

from __future__ import annotations

import dataclasses
import json
import os

from pytorchdistributed_tpu.utils.hlo import collective_bytes

# Peak bf16 matmul throughput per chip, by jax device_kind — the MFU
# denominator (shared with bench.py; previously its private table).
PEAK_BF16_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}

# The CPU-sim stand-in peak: a NOMINAL 1 TFLOP/s so MFU is computable
# (and deterministic in tests) on the 8-device simulator. The absolute
# value is meaningless by construction — peak_source carries the label
# so no report can pass a sim MFU off as utilization of real hardware.
CPU_SIM_NOMINAL_PEAK_FLOPS = 1e12

# Nominal aggregate ICI bandwidth per chip (bytes/s, all links), by jax
# device_kind — the comm_stall_frac denominator. These are public
# per-chip interconnect aggregates (v4 ≈ 2.4 Tb/s, v5e ≈ 1.6 Tb/s,
# v5p ≈ 4.8 Tb/s, v6e ≈ 3.6 Tb/s), NOT an achievable-bandwidth model:
# comm_stall_frac is an order-of-magnitude stall estimator and says so
# via ici_source, the same labeling discipline as the MFU peak table.
ICI_BYTES_PER_S = {
    "TPU v4": 3.0e11,
    "TPU v5 lite": 2.0e11,
    "TPU v5e": 2.0e11,
    "TPU v5": 6.0e11,
    "TPU v5p": 6.0e11,
    "TPU v6 lite": 4.5e11,
    "TPU v6e": 4.5e11,
}

# CPU-sim stand-in ICI (nominal 10 GB/s): meaningless absolutely, but it
# makes comm_stall_frac computable and DETERMINISTIC from the compiled
# artifact alone — which is what lets the structural compiled-invariant
# tier pin it (tests/test_compiled_invariants.py).
CPU_SIM_NOMINAL_ICI_BYTES_PER_S = 1e10


def ici_bytes_per_s_for(device_kind: str,
                        platform: str | None = None,
                        ) -> tuple[float | None, str]:
    """(per-chip nominal ICI bytes/s, source label) — comm_stall_frac's
    denominator, labeled like peak_flops_for so a sim estimate can never
    read as a hardware one."""
    bw = ICI_BYTES_PER_S.get(device_kind)
    if bw is not None:
        return bw, device_kind
    if platform == "cpu" or device_kind == "cpu":
        return CPU_SIM_NOMINAL_ICI_BYTES_PER_S, "cpu-sim-nominal"
    return None, f"unknown:{device_kind}"


def peak_flops_for(device_kind: str,
                   platform: str | None = None) -> tuple[float | None, str]:
    """(per-device peak bf16 flops, source label). Unknown TPU kinds get
    (None, "unknown:<kind>") — better to omit MFU than to invent a
    denominator for a chip generation this table predates."""
    peak = PEAK_BF16_FLOPS.get(device_kind)
    if peak is not None:
        return peak, device_kind
    if platform == "cpu" or device_kind == "cpu":
        return CPU_SIM_NOMINAL_PEAK_FLOPS, "cpu-sim-nominal"
    return None, f"unknown:{device_kind}"


def device_memory_highwater() -> int | None:
    """Max per-device HBM high-water (bytes) over the local devices, via
    ``device.memory_stats()`` — None where the backend has none (the CPU
    sim reports no stats). A host-side read of allocator counters: no
    device sync, cheap enough for log cadence."""
    import jax

    peak = None
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            return None
        if not stats:
            continue
        v = stats.get("peak_bytes_in_use", stats.get("bytes_in_use"))
        if v is not None:
            peak = max(peak or 0, int(v))
    return peak


@dataclasses.dataclass(frozen=True)
class StepAccounting:
    """Compile-time facts of one train step, ready to join with wall-clock.

    ``model_flops_per_step`` and ``comm_bytes_per_step`` are PER DEVICE
    (post-partitioning, matching the compiled-invariant convention);
    ``tokens_per_step`` / ``samples_per_step`` are GLOBAL (the batch the
    step consumes), so ``tokens_per_s`` reports global throughput."""

    model_flops_per_step: float
    comm_bytes_per_step: int
    comm_bytes_by_op: dict[str, int]
    tokens_per_step: int
    samples_per_step: int
    peak_flops_per_device: float | None
    peak_source: str
    n_devices: int
    # ICI denominator for comm_stall_frac. Defaults keep accounting.json
    # files written before ISSUE 5 loading (from_json passes only the
    # recorded keys).
    ici_bytes_per_s: float | None = None
    ici_source: str = ""

    @classmethod
    def from_compiled(cls, compiled, *, batch, n_devices: int | None = None,
                      ) -> "StepAccounting":
        """Build from a `jax.stages.Compiled` train step (the output of
        `Trainer.lower_step(batch).compile()`) plus the batch that shaped
        it. ``batch`` may be arrays or ShapeDtypeStructs — only shapes
        are read."""
        import jax

        cost = compiled.cost_analysis() or {}
        if isinstance(cost, (list, tuple)):  # older jax wraps in a list
            cost = cost[0] if cost else {}
        by_op = collective_bytes(compiled.as_text())
        tokens, samples = _batch_tokens_samples(batch)
        dev = jax.devices()[0]
        peak, source = peak_flops_for(dev.device_kind, dev.platform)
        ici, ici_source = ici_bytes_per_s_for(dev.device_kind, dev.platform)
        return cls(
            model_flops_per_step=float(cost.get("flops", 0.0)),
            comm_bytes_per_step=int(sum(by_op.values())),
            comm_bytes_by_op=by_op,
            tokens_per_step=tokens,
            samples_per_step=samples,
            peak_flops_per_device=peak,
            peak_source=source,
            n_devices=(n_devices if n_devices is not None
                       else jax.device_count()),
            ici_bytes_per_s=ici,
            ici_source=ici_source,
        )

    # -- derived metrics ---------------------------------------------------

    def mfu(self, sec_per_step: float) -> float | None:
        """Model-flops utilization of ONE device: cost-analysis flops are
        already per-device, so no world-size factor enters."""
        if (self.peak_flops_per_device is None or sec_per_step <= 0
                or self.model_flops_per_step <= 0):
            return None
        return round(self.model_flops_per_step / sec_per_step
                     / self.peak_flops_per_device, 4)

    def tokens_per_s(self, sec_per_step: float) -> float | None:
        if sec_per_step <= 0:
            return None
        return round(self.tokens_per_step / sec_per_step, 1)

    def comm_bytes_per_s(self, sec_per_step: float) -> float | None:
        if sec_per_step <= 0:
            return None
        return round(self.comm_bytes_per_step / sec_per_step, 1)

    @property
    def a2a_bytes_per_step(self) -> int:
        """Per-device all-to-all bytes (plain + ragged) — the
        expert-parallel MoE dispatch/combine volume (ISSUE 14), already
        inside ``comm_bytes_per_step`` but surfaced on its own because
        it's the term the capacity factor, int8 payloads and chunked
        overlap all act on (bench --mode moe stamps it per A/B leg)."""
        return int(sum(self.comm_bytes_by_op.get(k, 0)
                       for k in ("all-to-all", "ragged-all-to-all")))

    def comm_stall_frac(self, sec_per_step: float | None = None,
                        ) -> float | None:
        """Estimated fraction of the step stalled on collectives — the
        zero-overlap UPPER BOUND (ISSUE 5c): the time the step's
        per-device collective bytes would take at the chip's nominal ICI
        bandwidth, as a fraction of the step. With a measured
        ``sec_per_step`` (the Trainer/bench path) the denominator is the
        real step; without one (the structural compiled-invariant pins)
        it is the estimated serial compute + comm time at nominal peaks,
        so the number is a deterministic function of the compiled
        artifact. A step whose measured comm_stall_frac sits well below
        the structural estimate is one whose collectives the scheduler
        actually hid — read it next to utils.hlo.overlap_census, which
        says how (async pairs, ops inside the windows). ``ici_source``
        labels the denominator; cpu-sim-nominal estimates are for
        regression-pinning, not performance claims."""
        if self.ici_bytes_per_s is None:
            return None
        comm_s = self.comm_bytes_per_step / self.ici_bytes_per_s
        if sec_per_step is not None:
            if sec_per_step <= 0:
                return None
            return round(min(1.0, comm_s / sec_per_step), 4)
        if self.peak_flops_per_device is None or self.model_flops_per_step <= 0:
            return None
        compute_s = self.model_flops_per_step / self.peak_flops_per_device
        if comm_s + compute_s <= 0:
            return None
        return round(comm_s / (comm_s + compute_s), 4)

    # -- (de)serialization -------------------------------------------------

    def to_json(self) -> str:
        return json.dumps({"format": 1, **dataclasses.asdict(self)})

    @classmethod
    def from_json(cls, text: str) -> "StepAccounting":
        d = json.loads(text)
        d.pop("format", None)
        return cls(**d)

    def save(self, path: str | os.PathLike) -> None:
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            f.write(self.to_json())
        os.replace(tmp, path)

    @classmethod
    def load(cls, path: str | os.PathLike) -> "StepAccounting":
        with open(path) as f:
            return cls.from_json(f.read())


def _batch_tokens_samples(batch) -> tuple[int, int]:
    """(global tokens, global samples) from batch leaf shapes. LM batches
    carry a 2-D "tokens" leaf → tokens = B·S; everything else counts the
    leading dim (one "token" per sample, matching how samples/s and
    tokens/s coincide for vision workloads)."""
    shapes = {k: tuple(getattr(v, "shape", ()))
              for k, v in dict(batch).items()}
    samples = next((s[0] for s in shapes.values() if s), 0)
    tok = shapes.get("tokens")
    if tok is not None and len(tok) >= 2:
        n = 1
        for d in tok:
            n *= int(d)
        return n, int(samples)
    return int(samples), int(samples)
