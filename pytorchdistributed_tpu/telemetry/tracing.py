"""Fleet-wide distributed request tracing (ISSUE 17).

`spans.py` answers "where did this RANK's host time go?"; nothing
answered "where did this REQUEST's 400 ms go?" once the fleet became
role-aware and self-scaling (PRs 11-16): one stream now crosses router
submit -> WDRR admission -> a prefill replica -> a parked-KV handoff ->
a decode replica, surviving failover and preemption on the way. This
module is the Dapper-style request-scoped half:

  * `TraceContext` — trace_id + root span id, minted once at
    `ReplicaRouter.submit` and carried by value across every process
    boundary (the line-JSON wire's submit op, the KV handoff payload),
    so a request's spans form ONE connected trace no matter how many
    replicas served it.
  * `RequestTracer` — the per-process writer: each completed stage
    lands as one JSONL row in ``trace_rank{rank}.jsonl`` (the same
    writer-FILE/reader-GLOB contract as serve_metrics). Rows carry
    unix-epoch microsecond timestamps via a once-per-process anchor
    (the spans.py convention), so independently-written ranks merge
    onto one timeline. Host-only by construction: recording a span is
    a dict + one line-buffered write, nothing touches the device or
    the jit cache.
  * readers — `read_trace` / `critical_path` / `chrome_trace` /
    `slo_debt`: the report CLI's fleet-wide merge. `critical_path`
    clips a trace's stage spans into a timeline PARTITION of the root
    interval (latest-starting span owns an overlapped instant;
    uncovered time is ``stall``), so per-stage sums tile
    [submit, finish] exactly — the breakdown always adds up to the
    request's terminal latency.

Stage taxonomy (one request's life, router clock unless noted):

  queue      router submit -> WDRR dequeue (admission.popleft stamps)
  admission  dequeue -> accepted by a replica's engine
  prefill    engine submit -> first token / parked   (engine-side)
  handoff    parked-KV export -> import on the decode replica
  decode     first token (or import) -> retired      (engine-side)
  stall      anything the stages above did not cover (requeue backoff,
             parked-waiting-for-a-decode-slot, reap latency)

plus marker spans (``redispatch``) for failover/preemption requeues and
the root ``request`` span the whole trace parents to.

Off means off: every hook sits behind ``if tracer is not None`` — no
per-tick host work, no files, event/metric streams unchanged
(tests/test_tracing.py pins it, TRACE_COUNTS included).
"""

from __future__ import annotations

import glob
import itertools
import json
import os
import time
import uuid

from pytorchdistributed_tpu.telemetry.events import (
    TELEMETRY_DIR_ENV,
    JsonlWriter,
)

# writer filename / reader glob pair (rename together — report.py, the
# trace CLI and the tests all read through TRACE_GLOB)
TRACE_FILE = "trace_rank{rank}.jsonl"
TRACE_GLOB = "trace_rank*.jsonl"

#: request tracing master switch (default OFF): subprocess workers and
#: the bench legs read it; the router's ``trace="auto"`` honors it too.
TRACE_ENV = "PTD_TRACE"

#: the attributable stages, in sweep priority order (when two spans
#: cover the same instant the LATER-STARTING one owns it — a handoff
#: inside a long decode window attributes to the handoff)
STAGES = ("queue", "admission", "prefill", "handoff", "decode")

#: default per-request TTFT budget for SLO-debt attribution — matches
#: serving/autoscale.py's SLOConfig.ttft_target_ms default.
DEFAULT_SLO_TTFT_S = 0.5

# One-time wall-clock anchor (the spans.py convention): all repo
# timestamps are time.perf_counter() readings; the anchor maps them to
# unix-epoch so spans written by different processes merge. Every
# tracer in one process shares this module-level anchor, so durations
# and boundaries are EXACT within a process.
_ANCHOR_S = time.time() - time.perf_counter()


def to_unix(t_pc: float) -> float:
    """Map a time.perf_counter() reading to unix-epoch seconds."""
    return t_pc + _ANCHOR_S


def from_unix(t_unix: float) -> float:
    """Map unix-epoch seconds onto this process's perf_counter clock."""
    return t_unix - _ANCHOR_S


class TraceContext:
    """The by-value trace identity a request carries everywhere:
    ``trace_id`` names the trace, ``root`` the root span every stage
    span parents to (a FLAT chain on purpose: connectivity is a single
    equality check, and a late-joining emitter — the decode replica a
    handoff lands on — needs no span-stack handshake)."""

    __slots__ = ("trace_id", "root")

    def __init__(self, trace_id: str, root: str):
        self.trace_id = str(trace_id)
        self.root = str(root)

    def to_wire(self) -> dict:
        return {"trace_id": self.trace_id, "root": self.root}

    @classmethod
    def from_wire(cls, d) -> "TraceContext | None":
        if not d:
            return None
        return cls(d["trace_id"], d["root"])

    def __repr__(self):
        return f"TraceContext({self.trace_id}, root={self.root})"


class RequestTracer:
    """Per-process request-span writer + the live SLO-debt ledger the
    autoscaler reads. One instance per emitting process (the router
    owns one and shares it with its in-process engines; a subprocess
    worker builds its own from the env contract)."""

    def __init__(self, run_dir: str | os.PathLike,
                 rank: int | None = None, *,
                 slo_ttft_s: float = DEFAULT_SLO_TTFT_S):
        self.run_dir = str(run_dir)
        self.rank = (rank if rank is not None
                     else int(os.environ.get("RANK", "0")))
        self.slo_ttft_s = float(slo_ttft_s)
        # block-buffered, not line-buffered: a span is a memcpy, not a
        # write syscall (the < 1% overhead bar is measured against a
        # test-size model where a request completes in ~10 ms); rows
        # land on close()/flush(), and read_trace tolerates a torn tail
        self._w = JsonlWriter(os.path.join(
            self.run_dir, TRACE_FILE.format(rank=self.rank)),
            buffering=-1)
        self._seq = itertools.count()
        # {tenant: {"requests", "breaches", "debt_s"}} — updated at
        # router _finish time; Autoscaler._read folds the totals into
        # its decision snapshot
        self.slo_debt: dict[str, dict] = {}

    @classmethod
    def from_env(cls, rank: int | None = None) -> "RequestTracer | None":
        """The subprocess worker's constructor: PTD_TRACE=1 plus the
        launcher's telemetry-dir contract, else None (off means off)."""
        if os.environ.get(TRACE_ENV, "0").lower() not in ("1", "true",
                                                          "yes", "on"):
            return None
        d = os.environ.get(TELEMETRY_DIR_ENV)
        return cls(d, rank=rank) if d else None

    def new_trace(self) -> TraceContext:
        tid = uuid.uuid4().hex[:16]
        return TraceContext(tid, f"{tid}/0")

    def span(self, ctx: TraceContext | None, stage: str,
             t0: float, t1: float, *, root: bool = False,
             **attrs) -> None:
        """Record one COMPLETED stage: t0/t1 are perf_counter readings
        (mapped to unix µs here). Emitters call this at stage
        completion — no context-manager nesting to thread through the
        engine's callback-driven lifecycle."""
        if ctx is None:
            return
        sid = ctx.root if root else f"{self.rank}/{next(self._seq) + 1}"
        row = {"trace": ctx.trace_id, "span": sid,
               "parent": None if root else ctx.root,
               "stage": stage, "rank": self.rank,
               "t0_us": round(to_unix(t0) * 1e6, 1),
               "t1_us": round(to_unix(t1) * 1e6, 1)}
        row.update(attrs)
        self._w.write(row)

    def note_finish(self, tenant: str, ttft_s: float | None) -> None:
        """Accumulate the tenant's SLO debt (TTFT seconds beyond the
        budget) — the live aggregate the autoscaler stamps into its
        decision snapshots."""
        rec = self.slo_debt.setdefault(
            tenant, {"requests": 0, "breaches": 0, "debt_s": 0.0})
        rec["requests"] += 1
        if ttft_s is None:
            return
        debt = ttft_s - self.slo_ttft_s
        if debt > 0:
            rec["breaches"] += 1
            rec["debt_s"] += debt

    def debt_totals(self) -> dict:
        """{"slo_debt_s": total, "slo_debt_tenant": worst} — flat keys
        shaped for the autoscaler's metric snapshot."""
        if not self.slo_debt:
            return {}
        worst = max(self.slo_debt, key=lambda t: self.slo_debt[t]["debt_s"])
        return {"slo_debt_s": round(sum(
            r["debt_s"] for r in self.slo_debt.values()), 4),
            "slo_debt_tenant": worst}

    def close(self) -> None:
        self._w.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# readers — the fleet-wide merge the report CLI and tests consume


def read_trace(run_dir: str | os.PathLike) -> list[dict]:
    """Every span row under ``run_dir`` (all ranks merged; torn final
    lines of a killed process skipped)."""
    rows: list[dict] = []
    for path in sorted(glob.glob(os.path.join(str(run_dir), TRACE_GLOB))):
        try:
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rows.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except OSError:
            continue
    return rows


def spans_by_trace(rows: list[dict]) -> dict[str, list[dict]]:
    out: dict[str, list[dict]] = {}
    for r in rows:
        out.setdefault(r.get("trace", "?"), []).append(r)
    return out


def critical_path(spans: list[dict]) -> dict | None:
    """One trace's per-stage breakdown. Sweeps the elementary intervals
    of the root window: each instant belongs to the latest-starting
    stage span covering it, or to ``stall`` when none does — so
    ``queue_s + admission_s + prefill_s + handoff_s + decode_s +
    stall_s == total_s`` EXACTLY (the acceptance invariant). Also
    computes the same partition clipped to the TTFT window
    (``ttft_<stage>_s``) — which stage ate the TTFT budget."""
    root = next((s for s in spans if s.get("parent") is None), None)
    if root is None:
        return None
    t0, t1 = float(root["t0_us"]), float(root["t1_us"])
    stage_spans = [s for s in spans
                   if s is not root and s.get("stage") in STAGES]
    connected = all(s.get("parent") == root["span"]
                    for s in spans if s is not root)
    cuts = {t0, t1}
    for s in stage_spans:
        cuts.add(min(max(float(s["t0_us"]), t0), t1))
        cuts.add(min(max(float(s["t1_us"]), t0), t1))
    ttft_s = root.get("ttft_s")
    ttft_edge = t0 + ttft_s * 1e6 if ttft_s is not None else None
    if ttft_edge is not None:
        cuts.add(min(max(ttft_edge, t0), t1))
    edges = sorted(cuts)
    sums = dict.fromkeys(STAGES, 0.0)
    ttft_sums = dict.fromkeys(STAGES, 0.0)
    stall = ttft_stall = 0.0
    for a, b in zip(edges, edges[1:]):
        if b <= a:
            continue
        owner = None
        for s in stage_spans:
            if float(s["t0_us"]) <= a and float(s["t1_us"]) >= b:
                if owner is None or float(s["t0_us"]) >= float(
                        owner["t0_us"]):
                    owner = s
        dur = b - a
        in_ttft = ttft_edge is not None and b <= ttft_edge + 1e-9
        if owner is not None:
            sums[owner["stage"]] += dur
            if in_ttft:
                ttft_sums[owner["stage"]] += dur
        else:
            stall += dur
            if in_ttft:
                ttft_stall += dur
    out = {"trace": root.get("trace"), "request": root.get("request"),
           "tenant": root.get("tenant", "default"),
           "finish_reason": root.get("finish_reason"),
           "ttft_s": ttft_s, "retries": root.get("retries", 0),
           "total_s": (t1 - t0) / 1e6, "stall_s": stall / 1e6,
           "spans": len(spans), "connected": connected}
    for st in STAGES:
        out[f"{st}_s"] = sums[st] / 1e6
        out[f"ttft_{st}_s"] = ttft_sums[st] / 1e6
    out["ttft_stall_s"] = ttft_stall / 1e6
    return out


def critical_paths(rows: list[dict]) -> list[dict]:
    """Per-request breakdowns for every trace with a root span."""
    out = []
    for spans in spans_by_trace(rows).values():
        cp = critical_path(spans)
        if cp is not None:
            out.append(cp)
    return out


def slo_debt(paths: list[dict],
             slo_ttft_s: float = DEFAULT_SLO_TTFT_S) -> dict[str, dict]:
    """Per-tenant SLO-debt attribution from the merged critical paths:
    total debt seconds (TTFT beyond budget), breach count, and — over
    the BREACHING requests only — which stage their TTFT window spent
    its time in. The report table and ROADMAP item 4's per-tenant
    scaling signals read the same shape."""
    out: dict[str, dict] = {}
    for p in paths:
        rec = out.setdefault(p["tenant"], {
            "requests": 0, "breaches": 0, "debt_s": 0.0,
            **{f"ttft_{st}_s": 0.0 for st in STAGES},
            "ttft_stall_s": 0.0})
        rec["requests"] += 1
        if p["ttft_s"] is None:
            continue
        debt = p["ttft_s"] - slo_ttft_s
        if debt <= 0:
            continue
        rec["breaches"] += 1
        rec["debt_s"] += debt
        for st in STAGES:
            rec[f"ttft_{st}_s"] += p.get(f"ttft_{st}_s", 0.0)
        rec["ttft_stall_s"] += p.get("ttft_stall_s", 0.0)
    return out


def chrome_trace(rows: list[dict]) -> dict:
    """Trace Event JSON with ONE LANE PER REQUEST (pid = request lane,
    tid = emitting rank), so a handed-off stream reads as one lane
    crossing replica rows — open in ui.perfetto.dev."""
    events: list[dict] = []
    lanes: dict[str, int] = {}
    for r in rows:
        tid = r.get("replica", r.get("rank", 0))
        if not isinstance(tid, int):
            tid = -1   # the router's rank is the string "router"
        lane = lanes.get(r.get("trace", "?"))
        if lane is None:
            lane = lanes[r.get("trace", "?")] = len(lanes)
            root = r.get("parent") is None
            name = (f"req {r.get('request', '?')} "
                    f"({r.get('tenant', 'default')})"
                    if root else f"trace {r.get('trace', '?')}")
            events.append({"ph": "M", "name": "process_name",
                           "pid": lane, "args": {"name": name}})
        attrs = {k: v for k, v in r.items()
                 if k not in ("trace", "span", "parent", "stage",
                              "t0_us", "t1_us")}
        events.append({
            "ph": "X", "name": r.get("stage", "?"), "pid": lane,
            "tid": tid, "cat": "request",
            "ts": round(float(r["t0_us"]), 3),
            "dur": round(max(0.0, float(r["t1_us"])
                             - float(r["t0_us"])), 3),
            "args": attrs,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------------
# rendering — shared by the `trace` CLI subcommand and report.py


def render_trace(run_dir: str | os.PathLike, *, top: int = 10,
                 tenant: str | None = None, stage: str | None = None,
                 slo_ttft_s: float = DEFAULT_SLO_TTFT_S) -> str:
    """The terminal answer: top-N slowest requests (by ``stage`` when
    given, else by total latency) + the per-tenant SLO-debt table."""
    rows = read_trace(str(run_dir))
    paths = critical_paths(rows)
    if tenant is not None:
        paths = [p for p in paths if p["tenant"] == tenant]
    if not paths:
        return ("request traces: none found (run with tracing on — "
                "ReplicaRouter(trace=True) or PTD_TRACE=1 — and a "
                "telemetry dir)")
    key = f"{stage}_s" if stage else "total_s"
    ranked = sorted(paths, key=lambda p: -p.get(key, 0.0))
    n_conn = sum(p["connected"] for p in paths)
    lines = [f"request traces: {len(paths)} requests, "
             f"{sum(p['spans'] for p in paths)} spans, "
             f"{n_conn}/{len(paths)} connected"
             + (f"  (tenant {tenant})" if tenant else "")]
    hdr = (f"  {'request':>7}  {'tenant':>10}  {'total':>8}  "
           f"{'queue':>7}  {'admit':>7}  {'prefill':>7}  {'handoff':>7}  "
           f"{'decode':>8}  {'stall':>7}  {'ttft':>7}  {'finish':>8}")
    lines.append(f"  slowest by {stage or 'total latency'}:")
    lines.append(hdr)

    def ms(v):
        return f"{v * 1e3:.1f}" if v is not None else "-"

    for p in ranked[:top]:
        lines.append(
            f"  {p['request'] if p['request'] is not None else '-':>7}  "
            f"{p['tenant']:>10}  {ms(p['total_s']):>8}  "
            f"{ms(p['queue_s']):>7}  {ms(p['admission_s']):>7}  "
            f"{ms(p['prefill_s']):>7}  {ms(p['handoff_s']):>7}  "
            f"{ms(p['decode_s']):>8}  {ms(p['stall_s']):>7}  "
            f"{ms(p['ttft_s']):>7}  {p['finish_reason'] or '-':>8}")
    debt = slo_debt(paths, slo_ttft_s)
    lines.append(f"  per-tenant SLO debt (ttft budget "
                 f"{slo_ttft_s * 1e3:.0f} ms; breach-window ms by stage):")
    lines.append(f"  {'tenant':>10}  {'reqs':>5}  {'breaches':>8}  "
                 f"{'debt':>9}  {'queue':>7}  {'admit':>7}  "
                 f"{'prefill':>7}  {'handoff':>7}  {'decode':>7}  "
                 f"{'stall':>7}")
    for name, r in sorted(debt.items()):
        lines.append(
            f"  {name:>10}  {r['requests']:>5}  {r['breaches']:>8}  "
            f"{r['debt_s'] * 1e3:>7.1f}ms  "
            f"{r['ttft_queue_s'] * 1e3:>7.1f}  "
            f"{r['ttft_admission_s'] * 1e3:>7.1f}  "
            f"{r['ttft_prefill_s'] * 1e3:>7.1f}  "
            f"{r['ttft_handoff_s'] * 1e3:>7.1f}  "
            f"{r['ttft_decode_s'] * 1e3:>7.1f}  "
            f"{r['ttft_stall_s'] * 1e3:>7.1f}")
    return "\n".join(lines)
