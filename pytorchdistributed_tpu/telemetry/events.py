"""Anomaly tripwires + structured telemetry events.

The NaN watchdog (utils/guards.py) RAISES on a non-finite metric — right
for halting, useless for post-mortem: the exception dies with the rank
and nothing durable says which step, which metric, what the loss was
doing beforehand. Tripwires here are the recording half: evaluated at
log cadence (piggybacking on the device sync the Trainer already pays
for — no extra blocking), they emit `TelemetryEvent` JSONL records,
one file per rank, that survive the process. The launcher
(`pytorchdistributed_tpu.run --telemetry-dir`) aggregates them per
incarnation next to its heartbeat state, and the report CLI folds them
into the run report.

Detectors:
  * non-finite: any logged metric (loss, grad_norm, ...) NaN/Inf —
    stamped with the in-graph NaN-provenance layer index
    (``diag/first_bad_layer``, telemetry/diagnostics.py) when the
    diagnostics subsystem supplies one;
  * metric spike: per-key EMA z-score — an independent EMA
    mean/variance per watched key (the loss, ``grad_norm``, and every
    ``diag/*`` scalar by default; PTD_ANOMALY_KEYS pins the set,
    PTD_ANOMALY_Z the threshold), an event when a new value sits more
    than ``z_threshold`` deviations above the mean (one-sided: dropping
    fast is not an anomaly). The EMA warmup suppresses the first noisy
    observations. The loss key keeps its original ``loss_spike`` event
    shape; other keys emit ``metric_spike``.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import math
import os
import time

TELEMETRY_DIR_ENV = "PTD_TELEMETRY_DIR"

# The run-dir file contract, shared by writer (Trainer) and readers
# (report CLI, the run.py agent) — rename in ONE place or readers
# silently find nothing.
EVENTS_FILE = "events_rank{rank}.jsonl"
EVENTS_GLOB = "events_rank*.jsonl"
METRICS_FILE = "metrics_rank{rank}.jsonl"
METRICS_GLOB = "metrics_rank*.jsonl"

# Canonical event kinds shared by the emitters (faults/, checkpoint,
# Trainer) and the readers (report CLI, run.py's per-incarnation
# summaries) — string constants so a typo'd kind is an import error at
# the call site, not a silently-unmatched row in the post-mortem.
EVENT_FAULT = "fault_injected"          # faults/inject.py hooks
EVENT_RETRY = "io_retry"                # faults/retry.py backoff
EVENT_PREEMPTED = "preempted"           # Trainer SIGTERM graceful exit
EVENT_CKPT_QUARANTINED = "ckpt_quarantined"  # integrity verify failed
EVENT_CKPT_FALLBACK = "ckpt_fallback"   # restore walked back a step
EVENT_COMPILE_CACHE = "compile_cache"   # runtime/compile_cache.py hit/miss/
#                                         store/quarantine lifecycle
EVENT_REPLICA_RESTORE = "replica_restore"  # worker loaded a verified ckpt
EVENT_REPLICA_RESTORE_FALLBACK = "replica_restore_fallback"  # ckpt absent/
#                                         bad: worker fell back to init_seed


class JsonlWriter:
    """Append-only JSONL sink. Lazy (re)open in append mode — safe to
    ``close()`` at every epoch teardown and keep writing next epoch —
    and line-buffered, so each row is durable even if the process dies
    mid-epoch and the file is never left open or truncated. Zero-dep on
    purpose: the one durability implementation behind both the Trainer's
    metric sinks (training/logging.py re-exports it) and EventLog."""

    def __init__(self, path: str | os.PathLike, buffering: int = 1):
        # buffering=1 (default) = line-buffered: one write syscall per
        # row, durable through a crash. High-rate sinks whose readers
        # tolerate a torn tail (request tracing) pass -1 for block
        # buffering — a row becomes a memcpy, flushed on close().
        self.path = str(path)
        self._buffering = buffering
        self._f = None

    def write(self, obj: dict) -> None:
        self.write_line(json.dumps(obj))

    def write_line(self, line: str) -> None:
        if self._f is None:
            parent = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(parent, exist_ok=True)
            self._f = open(self.path, "a", buffering=self._buffering)
        self._f.write(line + "\n")

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


@dataclasses.dataclass(frozen=True)
class TelemetryEvent:
    """One structured anomaly/lifecycle record (a JSONL row)."""

    kind: str
    step: int
    rank: int
    time: float
    data: dict

    def to_json(self) -> str:
        return json.dumps({"kind": self.kind, "step": self.step,
                           "rank": self.rank, "time": self.time,
                           **self.data})

    @classmethod
    def from_json(cls, line: str) -> "TelemetryEvent":
        d = json.loads(line)
        return cls(kind=d.pop("kind"), step=int(d.pop("step", -1)),
                   rank=int(d.pop("rank", 0)), time=float(d.pop("time", 0.0)),
                   data=d)

    def describe(self) -> str:
        extras = " ".join(f"{k}={v}" for k, v in self.data.items())
        return f"rank {self.rank} step {self.step} {self.kind} {extras}"


class EventLog(JsonlWriter):
    """Per-rank TelemetryEvent sink: a JsonlWriter that stamps
    rank/time and returns the structured event from emit()."""

    def __init__(self, path: str | os.PathLike, rank: int = 0):
        super().__init__(path)
        self.rank = rank

    @classmethod
    def from_env(cls, rank: int) -> "EventLog | None":
        d = os.environ.get(TELEMETRY_DIR_ENV)
        if not d:
            return None
        os.makedirs(d, exist_ok=True)
        return cls(os.path.join(d, EVENTS_FILE.format(rank=rank)),
                   rank=rank)

    def emit(self, kind: str, *, step: int, **data) -> TelemetryEvent:
        ev = TelemetryEvent(kind=kind, step=step, rank=self.rank,
                            time=round(time.time(), 3), data=data)
        self.write_line(ev.to_json())
        return ev


# env knobs for the spike tripwires (ISSUE 6a): PTD_ANOMALY_Z overrides
# the z threshold, PTD_ANOMALY_KEYS (comma list) pins the watched-key set
# — unset, the detector watches the loss key, grad_norm, and every
# diag/* scalar the diagnostics subsystem emits.
ANOMALY_Z_ENV = "PTD_ANOMALY_Z"
ANOMALY_KEYS_ENV = "PTD_ANOMALY_KEYS"

#: diag scalars that are INDICES/counters, not magnitudes — z-scoring the
#: provenance layer index jumping -1 → L would only duplicate the
#: non_finite event that always accompanies it
_AUTO_WATCH_EXCLUDE = ("diag/first_bad_layer",)

#: metric key whose value (>= 0) names the first non-finite layer — the
#: in-graph NaN provenance (telemetry/diagnostics.py) the non-finite
#: events carry so a blowup is pinpointed to its origin layer
PROVENANCE_KEY = "diag/first_bad_layer"


class AnomalyDetector:
    """The tripwire logic, pure host arithmetic on already-synced metric
    floats — `check` adds no device work. Returns (kind, payload) pairs;
    the caller (Trainer) turns them into EventLog records.

    Per-key EMA state (ISSUE 6a): beyond ``loss_key`` the detector keeps
    an independent EMA mean/variance for every watched key —
    ``grad_norm`` and any ``diag/*`` scalar by default, or exactly the
    ``keys``/PTD_ANOMALY_KEYS set when given. Event shapes are
    backward-compatible: the loss key still emits ``loss_spike`` with the
    original payload; other keys emit ``metric_spike`` with the same
    fields plus ``metric``. Non-finite events additionally carry
    ``first_bad_layer`` whenever the in-graph provenance scalar is
    present and a layer is implicated."""

    def __init__(self, *, loss_key: str = "loss",
                 z_threshold: float | None = None,
                 ema: float = 0.98, warmup: int = 5,
                 min_rel_std: float = 0.05,
                 keys: tuple[str, ...] | None = None):
        self.loss_key = loss_key
        if z_threshold is None:
            env = os.environ.get(ANOMALY_Z_ENV, "").strip()
            z_threshold = float(env) if env else 6.0
        self.z_threshold = z_threshold
        self.ema = ema
        self.warmup = warmup
        # std floor as a fraction of the EMA mean: a smoothly-converging
        # loss drives the EMA variance toward zero, where any drift would
        # z-score as a "spike" — only excursions that are also material
        # relative to the loss level should trip
        self.min_rel_std = min_rel_std
        if keys is None:
            env = os.environ.get(ANOMALY_KEYS_ENV, "").strip()
            keys = tuple(k.strip() for k in env.split(",")
                         if k.strip()) if env else None
        self._keys = keys  # None = auto (loss + grad_norm + diag/*)
        # per-key EMA state: key -> [mean, var, seen]
        self._state: dict[str, list] = {}

    def _watched(self, metrics: dict) -> list[str]:
        if self._keys is not None:
            return [k for k in self._keys if k in metrics]
        return [k for k in metrics
                if (k == self.loss_key or k == "grad_norm"
                    or k.startswith("diag/"))
                and k not in _AUTO_WATCH_EXCLUDE]

    def check(self, metrics: dict[str, float],
              step: int) -> list[tuple[str, dict]]:
        out: list[tuple[str, dict]] = []
        prov = metrics.get(PROVENANCE_KEY)
        prov = (int(prov) if prov is not None and math.isfinite(float(prov))
                and float(prov) >= 0 else None)
        for k, v in metrics.items():
            v = float(v)
            if not math.isfinite(v):
                payload = {"metric": k, "value": str(v)}
                if prov is not None:
                    payload["first_bad_layer"] = prov
                out.append(("non_finite_metric", payload))
        for key in self._watched(metrics):
            v = metrics.get(key)
            if v is None or not math.isfinite(float(v)):
                continue
            v = float(v)
            mean, var, seen = self._state.get(key, (0.0, 0.0, 0))
            if seen >= self.warmup:
                std = max(math.sqrt(max(var, 0.0)),
                          self.min_rel_std * abs(mean), 1e-8)
                z = (v - mean) / std
                if z > self.z_threshold:
                    payload = {"value": round(v, 6),
                               "ema_mean": round(mean, 6),
                               "ema_std": round(std, 6), "z": round(z, 2)}
                    if key == self.loss_key:
                        out.append(("loss_spike", payload))
                    else:
                        out.append(("metric_spike",
                                    {"metric": key, **payload}))
            # fold AFTER judging: the spike itself must not pre-inflate
            # the variance it is measured against
            m = self.ema if seen else 0.0
            delta = v - mean
            mean += (1 - m) * delta
            var = m * (var + (1 - m) * delta * delta)
            self._state[key] = [mean, var, seen + 1]
        return out


def read_events(run_dir: str | os.PathLike) -> list[TelemetryEvent]:
    """Every TelemetryEvent under ``run_dir`` (all ranks, sorted by
    time) — the report CLI's reader."""
    events: list[TelemetryEvent] = []
    for path in sorted(glob.glob(os.path.join(str(run_dir), EVENTS_GLOB))):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    try:
                        events.append(TelemetryEvent.from_json(line))
                    except (json.JSONDecodeError, KeyError):
                        continue  # torn final line of a killed rank
    return sorted(events, key=lambda e: e.time)


def summarize_new_events(run_dir: str | os.PathLike,
                         offsets: dict[str, int]) -> str | None:
    """Agent-side per-incarnation aggregation: counts of event kinds per
    rank appended past ``offsets`` (byte offsets per file, updated in
    place — call once per incarnation teardown). None when nothing new."""
    counts: dict[tuple[int, str], int] = {}
    for path in sorted(glob.glob(os.path.join(str(run_dir), EVENTS_GLOB))):
        start = offsets.get(path, 0)
        try:
            with open(path) as f:
                f.seek(start)
                chunk = f.read()
                offsets[path] = f.tell()
        except OSError:
            continue
        for line in chunk.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                ev = TelemetryEvent.from_json(line)
            except (json.JSONDecodeError, KeyError):
                continue
            counts[(ev.rank, ev.kind)] = counts.get((ev.rank, ev.kind), 0) + 1
    if not counts:
        return None
    parts = [f"rank {r} {kind} x{n}"
             for (r, kind), n in sorted(counts.items())]
    return f"{sum(counts.values())} event(s): " + ", ".join(parts)
