"""In-graph training diagnostics (ISSUE 6): per-layer model health as
extra jitted outputs, not extra dispatches.

The telemetry subsystem (PR 2) and the anomaly tripwires (PR 4) watch the
training loop from the HOST: a non-finite loss fires an event, but
nothing durable says *which layer* went non-finite, whether grad norms
were already drifting ten steps earlier, or whether int8 quantization
(ops/quant.py) is saturating. This module is the in-graph half:

  * **activation health** — every TransformerBlock sows RMS / absmax /
    non-finite-count of its output into the "diagnostics" flax
    collection (models/transformer.py), gated entirely on the collection
    being *mutable* in the apply — with diagnostics off the stats are
    never traced and the compiled HLO is byte-identical
    (tests/test_compiled_invariants.py pins that literally);
  * **optimizer health** — the train step folds global and
    per-param-group grad norms, the per-layer grad-norm table of the
    scanned block stack, and the update/param RMS ratio into the same
    metrics pytree (training/trainer.py), so steady-state dispatch count
    is unchanged;
  * **NaN provenance** — ``diag/first_bad_layer``: the first layer index
    whose finite-flag drops, computed in-graph from the per-layer
    non-finite counts; the AnomalyDetector (telemetry/events.py)
    attaches it to every ``non_finite_metric`` event, so a
    ``PTD_FAULTS "nan@step=S,layer=L"`` injection (faults/inject.py)
    is pinpointed end-to-end;
  * **int8 saturation** — with ``quant != "none"`` the blocks also sow
    the clip fraction of the activations entering their quantized
    matmuls (ops/quant.py ``saturation_fraction``).

Key namespace contract (consumed by the Trainer's metric routing):
``diag/*`` are scalars — they ride the normal log-cadence device sync,
feed the AnomalyDetector's per-key EMAs, and land in the per-rank
diagnostics JSONL; ``diag_tbl/*`` are per-layer ``[L]`` arrays — the
Trainer pops them off the metrics dict on the host (no sync) and writes
them at the configured table cadence.

Cadence: everything is computed in-graph every step (the stats are a
handful of reductions — the point of in-graph diagnostics is that the
cadence knob governs host *emission*, never device work). ``scalars``
writes scalar rows only; ``full:N`` adds the per-layer tables every ~N
steps (evaluated at the log-cadence syncs the Trainer already pays for,
so a table row can be up to ``log_every - 1`` steps later than the
nominal tick — no extra device blocking is ever added).
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Any, Mapping

import jax
import jax.numpy as jnp

DIAGNOSTICS_ENV = "PTD_DIAGNOSTICS"

# The run-dir file contract (same discipline as events.py's EVENTS_FILE):
# one diagnostics JSONL per rank, next to the metric log.
DIAG_FILE = "diagnostics_rank{rank}.jsonl"
DIAG_GLOB = "diagnostics_rank*.jsonl"

#: flax collection name the model-side sow sites use. Everything is
#: gated on this collection being mutable in the apply, so the knob
#: lives entirely at apply time — no model-config flag, no rebuild.
DIAG_COLLECTION = "diagnostics"

#: metric-key namespaces (see module docstring)
SCALAR_PREFIX = "diag/"
TABLE_PREFIX = "diag_tbl/"

_DEFAULT_TABLE_EVERY = 50


@dataclasses.dataclass(frozen=True)
class DiagnosticsConfig:
    """Parsed diagnostics mode. ``table_every == 0`` means scalar rows
    only (the per-layer tables are still computed in-graph — provenance
    needs them — just never written)."""

    table_every: int = 0

    @property
    def spec(self) -> str:
        if self.table_every:
            return f"full:{self.table_every}"
        return "scalars"

    @classmethod
    def parse(cls, spec: str) -> "DiagnosticsConfig | None":
        """``off`` → None; ``scalars`` → scalar rows only; ``full`` /
        ``full:N`` → per-layer tables every ~N steps (default 50)."""
        s = str(spec).strip().lower()
        if s in ("", "off", "none", "0", "false"):
            return None
        if s in ("scalars", "on", "1", "true"):
            return cls(table_every=0)
        m = re.fullmatch(r"full(?::(\d+))?", s)
        if m:
            n = int(m.group(1)) if m.group(1) else _DEFAULT_TABLE_EVERY
            if n < 1:
                raise ValueError(
                    f"diagnostics table cadence must be >= 1, got {spec!r}")
            return cls(table_every=n)
        raise ValueError(
            f"unknown diagnostics mode {spec!r}; one of off | scalars | "
            f"full[:N] (N = per-layer table cadence in steps)")

    @classmethod
    def resolve(cls, arg) -> "DiagnosticsConfig | None":
        """The Trainer-knob resolution order: explicit arg (a spec string
        or an already-built config) wins, then the PTD_DIAGNOSTICS env
        contract, then off."""
        if isinstance(arg, cls):
            return arg
        if arg is not None:
            return cls.parse(arg)
        return cls.parse(os.environ.get(DIAGNOSTICS_ENV, "off"))


# ---------------------------------------------------------------------------
# in-graph stats (called from the model's sow sites and the train step)
# ---------------------------------------------------------------------------

#: layout of the per-block sown stat vector (models/transformer.py)
ACT_STAT_NAMES = ("act_rms", "act_absmax", "act_nonfinite")


def activation_stat_vec(x) -> jax.Array:
    """The ``[3]`` fp32 stat vector one block sows for its output
    activation: RMS, absmax, and the count of non-finite elements.
    Non-finite inputs must not poison the first two (NaN absorbs
    everything): the moments are computed over the finite elements only,
    so ``act_rms`` stays readable right up to — and after — a blowup
    while ``act_nonfinite`` carries the event itself."""
    xf = x.astype(jnp.float32)
    finite = jnp.isfinite(xf)
    # count the NON-finite side directly in integer dtype: a float32 sum
    # of ~2^28 ones rounds (spacing 16 past 2^24) and would erase a
    # 2-element NaN count on production-size activations — exactly when
    # provenance matters most
    nonfinite = jnp.sum(~finite, dtype=jnp.int32)
    safe = jnp.where(finite, xf, 0.0)
    denom = jnp.maximum(jnp.int32(x.size) - nonfinite, 1).astype(
        jnp.float32)
    rms = jnp.sqrt(jnp.sum(safe * safe) / denom)
    absmax = jnp.max(jnp.abs(safe))
    return jnp.stack([rms, absmax, nonfinite.astype(jnp.float32)])


def _natural_key(s: str):
    """Sort 'block_10' after 'block_2' (unrolled stacks name blocks
    block_0..block_N; lexicographic order would interleave layers)."""
    return [int(t) if t.isdigit() else t for t in re.split(r"(\d+)", s)]


def collect_activation_tables(coll: Mapping[str, Any]) -> dict[str, Any]:
    """Sown "diagnostics" collection → ``{stat name: [L] array}``.

    Handles both stacked layouts: under ``nn.scan`` a sow site appears
    once with a leading layer axis (``out_stats`` → ``[L, 3]``); in an
    unrolled stack each ``block_i`` sows its own ``[3]`` vector and the
    layers are reassembled in natural path order. Returns {} when the
    model sowed nothing (non-transformer models)."""
    by_name: dict[str, list[tuple[str, Any]]] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(dict(coll))[0]:
        keys = [getattr(k, "key", getattr(k, "idx", k)) for k in path]
        name = next((str(k) for k in reversed(keys)
                     if isinstance(k, str)), None)
        if name is None:
            continue
        by_name.setdefault(name, []).append(
            ("/".join(str(k) for k in keys), leaf))

    out: dict[str, Any] = {}

    def stacked(entries):
        entries.sort(key=lambda kv: _natural_key(kv[0]))
        leaves = [v for _, v in entries]
        if len(leaves) == 1:
            return leaves[0]
        return jnp.stack(leaves)

    if "out_stats" in by_name:
        stats = stacked(by_name["out_stats"])  # [L, 3] (or [3] for L=1)
        if stats.ndim == 1:
            stats = stats[None]
        for i, name in enumerate(ACT_STAT_NAMES):
            out[name] = stats[:, i]
    if "int8_sat" in by_name:
        sat = stacked(by_name["int8_sat"])
        out["int8_sat"] = sat.reshape(-1)
    if "moe_overflow" in by_name:
        ovf = stacked(by_name["moe_overflow"])
        out["moe_overflow"] = ovf.reshape(-1)
    if "moe_frac" in by_name:
        # per-expert first-choice routing fractions: [L, e] — the one 2-D
        # table (the JSONL writer ravels rows, so e columns per layer)
        frac = stacked(by_name["moe_frac"])
        out["moe_frac"] = frac.reshape(-1, frac.shape[-1])
    return out


def first_bad_layer(act_nonfinite) -> jax.Array:
    """NaN provenance: the first layer index whose non-finite count is
    positive, ``-1`` when every layer is clean. Works on the
    micro-batch-averaged table too (a mean of counts is > 0 iff any
    micro-batch saw a non-finite element)."""
    bad = act_nonfinite > 0
    idx = jnp.argmax(bad)  # first True (argmax of bool picks it)
    return jnp.where(jnp.any(bad), idx, -1).astype(jnp.float32)


def _sumsq_and_size(tree) -> tuple[jax.Array, float]:
    leaves = [l for l in jax.tree.leaves(tree)
              if hasattr(l, "dtype")
              and jnp.issubdtype(l.dtype, jnp.floating)]
    if not leaves:
        return jnp.float32(0.0), 0.0
    ss = sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    return ss, float(sum(l.size for l in leaves))


def tree_norm(tree) -> jax.Array:
    """Global L2 norm over every floating leaf (optax.global_norm without
    the integer-leaf trip hazard)."""
    ss, _ = _sumsq_and_size(tree)
    return jnp.sqrt(ss)


def tree_rms(tree) -> jax.Array:
    ss, n = _sumsq_and_size(tree)
    return jnp.sqrt(ss / max(n, 1.0))


def _param_groups(tree) -> dict[str, Any]:
    """Top-level param groups for the per-group norms: unwrap the
    "params" collection wrapper when present so groups read as the
    model's own top-level modules (embed / h / ln_f / ...)."""
    if isinstance(tree, Mapping):
        inner = tree.get("params", tree)
        if isinstance(inner, Mapping):
            return dict(inner)
    return {}


def per_layer_grad_norms(group_tree, num_layers: int) -> jax.Array | None:
    """``[L]`` per-layer grad norms for a group whose every leaf carries
    the scanned layer axis in front (the ``nn.scan`` block stack's
    ``[L, ...]`` leaves). None when the group isn't layer-stacked."""
    leaves = [l for l in jax.tree.leaves(group_tree)
              if hasattr(l, "ndim")]
    if not leaves or num_layers < 2:
        return None
    if not all(l.ndim >= 1 and l.shape[0] == num_layers for l in leaves):
        return None
    ss = sum(
        jnp.sum(jnp.square(l.astype(jnp.float32)),
                axis=tuple(range(1, l.ndim)))
        for l in leaves)
    return jnp.sqrt(ss)


def _sanitize(name: str) -> str:
    return re.sub(r"[^A-Za-z0-9_.]+", "_", name)


def diagnostics_metrics(*, acts, grads, params, updates,
                        num_layers: int | None) -> dict[str, Any]:
    """The full in-graph diagnostics dict for one train step, keyed by
    the ``diag/`` (scalar) and ``diag_tbl/`` ([L] table) namespaces.
    Called INSIDE the jitted step — everything here is traced arithmetic
    on values the step already holds, so it adds zero dispatches.

    ``acts`` is the sown collection (or None when the loss/model doesn't
    surface one — grad/update health still reports), ``grads``/``params``
    /``updates`` are the step's trees, ``num_layers`` the transformer
    depth (None for non-transformer models — disables the per-layer
    grad table)."""
    out: dict[str, Any] = {}

    # -- optimizer health --------------------------------------------------
    out[SCALAR_PREFIX + "grad_norm"] = tree_norm(grads)
    groups = _param_groups(grads)
    for name in sorted(groups):
        out[SCALAR_PREFIX + f"gnorm_{_sanitize(name)}"] = tree_norm(
            groups[name])
        if num_layers:
            layered = per_layer_grad_norms(groups[name], num_layers)
            if layered is not None:
                out[TABLE_PREFIX + f"gnorm_{_sanitize(name)}"] = layered
    # update/param RMS ratio: the effective relative step size — the
    # quantity LR-schedule debugging actually wants (≈ lr·adam_ratio)
    out[SCALAR_PREFIX + "update_ratio"] = tree_rms(updates) / jnp.maximum(
        tree_rms(params), 1e-20)

    # -- activation health -------------------------------------------------
    if acts:
        tables = collect_activation_tables(acts)
        for name, tbl in tables.items():
            out[TABLE_PREFIX + name] = tbl
        if "act_rms" in tables:
            out[SCALAR_PREFIX + "act_rms_mean"] = tables["act_rms"].mean()
        if "act_absmax" in tables:
            out[SCALAR_PREFIX + "act_absmax"] = tables["act_absmax"].max()
        if "act_nonfinite" in tables:
            out[SCALAR_PREFIX + "act_nonfinite"] = (
                tables["act_nonfinite"].sum())
            out[SCALAR_PREFIX + "first_bad_layer"] = first_bad_layer(
                tables["act_nonfinite"])
        if "int8_sat" in tables:
            out[SCALAR_PREFIX + "int8_sat"] = tables["int8_sat"].mean()
        if "moe_overflow" in tables:
            # mean over MoE layers: the headline "how much routed traffic
            # rode the residual" number the capacity factor is tuned by
            out[SCALAR_PREFIX + "moe_overflow"] = (
                tables["moe_overflow"].mean())
        if "moe_frac" in tables:
            # worst per-expert routing share (uniform = 1/e; → 1.0 as the
            # router collapses onto one expert)
            out[SCALAR_PREFIX + "moe_frac_max"] = tables["moe_frac"].max()
    return out


def split_scalars_tables(metrics: Mapping[str, Any]):
    """(scalars, tables) views of a metrics dict by the diag namespaces —
    the Trainer's host-side router (pure dict work, no device sync)."""
    scalars = {k: v for k, v in metrics.items()
               if k.startswith(SCALAR_PREFIX)}
    tables = {k: v for k, v in metrics.items()
              if k.startswith(TABLE_PREFIX)}
    return scalars, tables
