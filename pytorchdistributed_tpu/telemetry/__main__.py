"""CLI entry: ``python -m pytorchdistributed_tpu.telemetry <cmd>``.

  report <run-dir>        merged cross-rank run report (see report.py)
  merge-trace <run-dir>   merge every rank's host-span trace into one
                          Chrome-trace JSON (open in ui.perfetto.dev;
                          overlay the jax.profiler device capture by
                          opening both)
"""

from __future__ import annotations

import argparse
import json
import sys

from pytorchdistributed_tpu.telemetry.report import render
from pytorchdistributed_tpu.telemetry.spans import merge_chrome_traces


def main(argv=None) -> int:
    p = argparse.ArgumentParser("pytorchdistributed_tpu.telemetry")
    sub = p.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("report", help="merged cross-rank run report")
    rp.add_argument("run_dir")
    rp.add_argument("--top", type=int, default=10,
                    help="rows per top-N table")
    mp = sub.add_parser("merge-trace",
                        help="merge per-rank host-span traces")
    mp.add_argument("run_dir")
    mp.add_argument("-o", "--output", default=None,
                    help="output path (default <run-dir>/merged.trace.json)")
    args = p.parse_args(argv)
    if args.cmd == "report":
        print(render(args.run_dir, top=args.top))
        return 0
    out = args.output or f"{args.run_dir.rstrip('/')}/merged.trace.json"
    merged = merge_chrome_traces(args.run_dir)
    with open(out, "w") as f:
        json.dump(merged, f)
    print(f"merged {len(merged['traceEvents'])} events into {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
