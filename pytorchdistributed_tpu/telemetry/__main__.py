"""CLI entry: ``python -m pytorchdistributed_tpu.telemetry <cmd>``.

  report <run-dir>        merged cross-rank run report (see report.py)
  merge-trace <run-dir>   merge every rank's host-span trace into one
                          Chrome-trace JSON (open in ui.perfetto.dev;
                          overlay the jax.profiler device capture by
                          opening both)
  trace <run-dir>         fleet-wide REQUEST traces (ISSUE 17): top-N
                          slowest requests with their per-stage
                          critical-path breakdown + the per-tenant
                          SLO-debt table; ``--chrome`` additionally
                          writes a one-lane-per-request Chrome trace
"""

from __future__ import annotations

import argparse
import json
import sys

from pytorchdistributed_tpu.telemetry.report import render
from pytorchdistributed_tpu.telemetry.spans import merge_chrome_traces
from pytorchdistributed_tpu.telemetry.tracing import (
    DEFAULT_SLO_TTFT_S,
    STAGES,
    chrome_trace,
    read_trace,
    render_trace,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser("pytorchdistributed_tpu.telemetry")
    sub = p.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser("report", help="merged cross-rank run report")
    rp.add_argument("run_dir")
    rp.add_argument("--top", type=int, default=10,
                    help="rows per top-N table")
    mp = sub.add_parser("merge-trace",
                        help="merge per-rank host-span traces")
    mp.add_argument("run_dir")
    mp.add_argument("-o", "--output", default=None,
                    help="output path (default <run-dir>/merged.trace.json)")
    tp = sub.add_parser("trace",
                        help="merged request traces: slowest requests "
                             "by stage + per-tenant SLO debt")
    tp.add_argument("run_dir")
    tp.add_argument("--top", type=int, default=10,
                    help="slowest-request rows to show")
    tp.add_argument("--tenant", default=None,
                    help="only this tenant's requests")
    tp.add_argument("--stage", default=None, choices=list(STAGES),
                    help="rank by this stage's time instead of total")
    tp.add_argument("--slo-ttft-ms", type=float,
                    default=DEFAULT_SLO_TTFT_S * 1e3,
                    help="TTFT budget for the SLO-debt table (ms)")
    tp.add_argument("--chrome", default=None, metavar="OUT",
                    help="also write a one-lane-per-request Chrome "
                         "trace JSON here")
    args = p.parse_args(argv)
    if args.cmd == "report":
        print(render(args.run_dir, top=args.top))
        return 0
    if args.cmd == "trace":
        print(render_trace(args.run_dir, top=args.top,
                           tenant=args.tenant, stage=args.stage,
                           slo_ttft_s=args.slo_ttft_ms / 1e3))
        if args.chrome:
            ct = chrome_trace(read_trace(args.run_dir))
            with open(args.chrome, "w") as f:
                json.dump(ct, f)
            print(f"wrote {len(ct['traceEvents'])} request-trace "
                  f"events to {args.chrome}")
        return 0
    out = args.output or f"{args.run_dir.rstrip('/')}/merged.trace.json"
    merged = merge_chrome_traces(args.run_dir)
    with open(out, "w") as f:
        json.dump(merged, f)
    print(f"merged {len(merged['traceEvents'])} events into {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
