"""Host-span tracing — the host half of "where did the step go?".

`jax.profiler` answers for the *device* (utils/trace.py summarizes its
captures); nothing answered for the *host*: data loading, H2D sharding,
dispatch, the blocking metric sync, checkpoint saves. `SpanTracer` is a
zero-dependency ring-buffer recorder the Trainer wraps around exactly
those regions. Design constraints, in order:

  * **Overhead**: entering+exiting a span is two `perf_counter_ns` calls
    and one deque append (~1-2 µs measured — tests/test_telemetry.py pins
    the budget). Cheap enough to leave on for a whole run; the ring
    buffer (`capacity` spans, oldest evicted) bounds memory for
    arbitrarily long jobs.
  * **Chrome-trace output**: `dump()` writes the Trace Event JSON format,
    one file per rank, `pid` = rank — openable directly in
    ui.perfetto.dev / chrome://tracing, and mergeable across ranks
    (`merge_chrome_traces`). Timestamps are unix-epoch microseconds
    (wall-clock anchored once at tracer construction, monotonic within
    the trace), so independently-dumped ranks land on one timeline.
  * **Zero deps**: no jax import — the tracer must be constructible
    before any backend init and usable from launcher-side code.
"""

from __future__ import annotations

import collections
import glob
import json
import os
import time


class _Span:
    """One `with tracer.span(name):` region. Allocation-light on purpose:
    the hot loop enters several of these per step."""

    __slots__ = ("_buf", "_name", "_t0")

    def __init__(self, buf, name):
        self._buf = buf
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, *exc):
        self._buf.append((self._name, self._t0, time.perf_counter_ns()))
        return False


class SpanTracer:
    """Ring-buffer host-span recorder; one instance per process/rank.

    ``rank`` stamps the Chrome-trace pid (defaults to the launcher env
    contract's RANK, 0 outside one); ``capacity`` bounds memory — at 6
    spans/step the default holds ~10k steps of history.
    """

    def __init__(self, capacity: int = 65536, rank: int | None = None):
        self.rank = (rank if rank is not None
                     else int(os.environ.get("RANK", "0")))
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        # One-time wall-clock anchor: spans record monotonic perf_counter
        # times; the anchor maps them onto unix-epoch µs so traces dumped
        # by different ranks (different processes, same or different
        # hosts) merge onto a shared timeline.
        self._epoch_us = time.time() * 1e6 - time.perf_counter_ns() / 1e3

    def span(self, name: str) -> _Span:
        return _Span(self._buf, name)

    def __len__(self) -> int:
        return len(self._buf)

    def totals(self) -> dict[str, tuple[float, int]]:
        """{span name: (total ms, count)} over the buffered spans."""
        out: dict[str, list] = {}
        for name, t0, t1 in self._buf:
            r = out.setdefault(name, [0.0, 0])
            r[0] += (t1 - t0) / 1e6
            r[1] += 1
        return {k: (v[0], v[1]) for k, v in out.items()}

    def to_chrome_trace(self) -> dict:
        """Trace Event JSON dict: complete ("X") events, ts/dur in µs."""
        pid = self.rank
        events: list[dict] = [
            {"ph": "M", "name": "process_name", "pid": pid,
             "args": {"name": f"host rank {self.rank}"}},
            {"ph": "M", "name": "thread_name", "pid": pid, "tid": 0,
             "args": {"name": "host spans"}},
        ]
        for name, t0, t1 in self._buf:
            events.append({
                "ph": "X", "name": name, "pid": pid, "tid": 0,
                "ts": round(self._epoch_us + t0 / 1e3, 3),
                "dur": round((t1 - t0) / 1e3, 3),
                "cat": "host",
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump(self, path: str | os.PathLike) -> None:
        """Write the Chrome-trace JSON (atomic rename: a reader — the
        report CLI, a mid-run Perfetto open — never sees a torn file)."""
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        os.replace(tmp, path)


# writer filename / reader glob pair — rename together (report.py and
# the Trainer both import these; see the matching contract in events.py)
SPAN_TRACE_FILE = "spans_rank{rank}.trace.json"
SPAN_TRACE_GLOB = "spans_rank*.trace.json"


def merge_chrome_traces(run_dir: str | os.PathLike,
                        extra_events: list[dict] | None = None) -> dict:
    """Merge every rank's span trace under ``run_dir`` into one
    Chrome-trace dict (each file already carries a distinct pid = rank).
    ``extra_events`` lets a caller overlay another trace's events — e.g.
    the device events of a `jax.profiler` capture — on the same timeline."""
    events: list[dict] = []
    for path in sorted(glob.glob(os.path.join(str(run_dir),
                                              SPAN_TRACE_GLOB))):
        with open(path) as f:
            events.extend(json.load(f).get("traceEvents", []))
    if extra_events:
        events.extend(extra_events)
    return {"traceEvents": events, "displayTimeUnit": "ms"}
