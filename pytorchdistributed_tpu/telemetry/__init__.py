"""Unified telemetry subsystem (SURVEY.md §5, grown into a layer):

  * spans.py       — host-span tracer (ring buffer → Chrome-trace JSON)
  * accounting.py  — StepAccounting: MFU / tokens-per-s / comm-bytes from
                     the compiled step joined with wall-clock
  * events.py      — anomaly tripwires → per-rank TelemetryEvent JSONL
  * diagnostics.py — in-graph model health (ISSUE 6): per-layer
                     activation stats, grad/update health, NaN
                     provenance — extra jitted outputs, zero overhead
                     when off (``Trainer(diagnostics=...)`` /
                     PTD_DIAGNOSTICS)
  * tracing.py     — fleet-wide request tracing (ISSUE 17): one
                     TraceContext per router submit, propagated across
                     the wire; per-rank ``trace_rank*.jsonl`` spans
                     merged into critical-path / SLO-debt tables
                     (``... telemetry trace <dir>``)
  * report.py      — the cross-rank run report CLI
                     (``python -m pytorchdistributed_tpu.telemetry report``)

The Trainer enables all of it with one knob (``telemetry_dir=...`` or the
launcher's ``--telemetry-dir`` / PTD_TELEMETRY_DIR env).
"""

from pytorchdistributed_tpu.telemetry.accounting import (  # noqa: F401
    CPU_SIM_NOMINAL_ICI_BYTES_PER_S,
    CPU_SIM_NOMINAL_PEAK_FLOPS,
    ICI_BYTES_PER_S,
    PEAK_BF16_FLOPS,
    StepAccounting,
    device_memory_highwater,
    ici_bytes_per_s_for,
    peak_flops_for,
)
from pytorchdistributed_tpu.telemetry.diagnostics import (  # noqa: F401
    DIAGNOSTICS_ENV,
    DiagnosticsConfig,
)
from pytorchdistributed_tpu.telemetry.events import (  # noqa: F401
    TELEMETRY_DIR_ENV,
    AnomalyDetector,
    EventLog,
    TelemetryEvent,
    read_events,
    summarize_new_events,
)
from pytorchdistributed_tpu.telemetry.spans import (  # noqa: F401
    SpanTracer,
    merge_chrome_traces,
)
from pytorchdistributed_tpu.telemetry.tracing import (  # noqa: F401
    TRACE_ENV,
    RequestTracer,
    TraceContext,
    critical_paths,
    read_trace,
)
