"""ctypes loader for the native host-data-path library (csrc/ptd_host.cc).

`gather(src, indices)` is the loader's hot loop (one call per batch);
the native path is a multi-threaded row memcpy that releases the GIL
(ctypes calls drop it), so host batch assembly overlaps device compute.
Falls back to numpy fancy indexing when the library isn't built — the
framework never hard-requires the C++ toolchain. Build with:

    make -C csrc
"""

from __future__ import annotations

import ctypes
import pathlib
import subprocess

import numpy as np

_LIB_PATH = pathlib.Path(__file__).parent / "libptd_host.so"
_CSRC = pathlib.Path(__file__).parent.parent.parent / "csrc"
_lib = None
_load_attempted = False


def _try_load() -> ctypes.CDLL | None:
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True  # one build/load attempt per process, pass or fail
    if not _LIB_PATH.exists() and (_CSRC / "Makefile").exists():
        # best-effort one-shot build; stays silent on missing toolchain
        try:
            subprocess.run(["make", "-C", str(_CSRC)], capture_output=True,
                           timeout=120, check=True)
        except Exception:
            return None
    if not _LIB_PATH.exists():
        return None
    try:
        lib = ctypes.CDLL(str(_LIB_PATH))
        if lib.ptd_version() != 1:
            return None
        lib.ptd_gather.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int32,
        ]
        lib.ptd_gather.restype = None
        _lib = lib
    except OSError:
        return None
    return _lib


def native_available() -> bool:
    return _try_load() is not None


def gather(src: np.ndarray, indices: np.ndarray) -> np.ndarray:
    """out[i] = src[indices[i]] — native multithreaded when built, numpy
    otherwise. Bounds are checked here (the C side trusts its caller)."""
    lib = _try_load()
    indices = np.ascontiguousarray(indices, dtype=np.int64)
    # Bounds check BEFORE choosing a path so semantics don't depend on
    # build state: numpy fancy indexing would silently wrap negative
    # indices that the native path rejects.
    if indices.size and (indices.min() < 0 or indices.max() >= len(src)):
        raise IndexError(
            f"indices out of range [0, {len(src)}) for gather")
    if lib is None or not src.flags.c_contiguous or src.nbytes == 0:
        return src[indices]
    out = np.empty((len(indices),) + src.shape[1:], dtype=src.dtype)
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    lib.ptd_gather(
        src.ctypes.data, len(src), row_bytes,
        indices.ctypes.data, len(indices), out.ctypes.data, 0)
    return out
