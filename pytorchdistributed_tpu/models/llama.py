"""Llama-family causal LM — the working TPU-native replacement for the
reference's failed ``LlamaForCausalLM.from_pretrained("decanlp/llama-7b-hf",
device_map="auto")`` demo (reference 03_model_parallel.ipynb:86-89, cell 1;
it never ran for lack of network). Here the model is defined natively on the
shared TransformerStack with the Llama dialect knobs flipped (RMSNorm,
SwiGLU, RoPE, grouped-query attention, no biases, untied LM head), so every
parallel strategy — DDP/FSDP/TP/PP/SP and ``--strategy auto``, the
device_map analog (parallel/auto.py) — applies unmodified.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from pytorchdistributed_tpu.models.transformer import (
    Embedder,
    LMHead,
    TransformerConfig,
    TransformerStack,
    _layer_norm,
    check_pipeline_decomposition,
    gather_free_ce,
    make_stage_apply,
    stack_to_stages,
    stages_to_stack,
)


class Llama(nn.Module):
    cfg: TransformerConfig

    def setup(self):
        cfg = self.cfg
        self.embed = Embedder(cfg)
        self.h = TransformerStack(cfg)
        self.ln_f = _layer_norm(cfg, None)
        self.lm_head = LMHead(cfg)

    def _backbone(self, tokens, deterministic):
        x = self.embed(tokens)
        x = self.h(x, deterministic=deterministic)
        return self.ln_f(x)

    def __call__(self, tokens, *, deterministic: bool = True):
        x = self._backbone(tokens, deterministic)
        return self.lm_head(x).astype(jnp.float32)

    def loss_per_position(self, tokens, targets, *,
                          deterministic: bool = True):
        """Fused chunked-CE head (see GPT2.loss_per_position)."""
        from pytorchdistributed_tpu.ops.fused_ce import chunked_softmax_ce
        from pytorchdistributed_tpu.models.transformer import _cfg_dot_general

        cfg = self.cfg
        x = self._backbone(tokens, deterministic)
        return chunked_softmax_ce(
            x.astype(cfg.dtype), self.lm_head.kernel.astype(cfg.dtype),
            targets, chunk=cfg.ce_chunk, transpose_w=False,
            dot_general=_cfg_dot_general(cfg))

    @nn.nowrap
    def pipeline_parts(self):
        """1F1B decomposition (see GPT2.pipeline_parts): pre = token embed,
        stages = layer groups, head = ln_f + untied lm_head + CE. No tied
        embedding, so grads merge without summing contributions."""
        from pytorchdistributed_tpu.parallel.pipeline import PipelineParts

        cfg = self.cfg
        check_pipeline_decomposition(cfg)

        def split(params):
            pp = params["params"]
            stage = stack_to_stages(pp["h"]["block"], cfg)
            head = {"ln_f": pp["ln_f"], "proj": pp["lm_head"]["kernel"]}
            return pp["embed"], stage, head

        def pre_apply(pre, tokens):
            return Embedder(cfg).apply({"params": pre}, tokens)

        def head_loss(head, h, targets):
            x = _layer_norm(cfg, None).apply({"params": head["ln_f"]}, h)
            logits = x.astype(cfg.dtype) @ head["proj"].astype(cfg.dtype)
            return gather_free_ce(logits, targets).mean()

        def merge_grads(pre_g, stage_g, head_g):
            blocks = stages_to_stack(stage_g, cfg)
            return {"params": {
                "embed": pre_g, "h": {"block": blocks},
                "ln_f": head_g["ln_f"],
                "lm_head": {"kernel": head_g["proj"]},
            }}

        return PipelineParts(
            split, pre_apply, make_stage_apply(cfg), head_loss, merge_grads,
            stage_apply_aux=(make_stage_apply(cfg, aux=True)
                             if cfg.moe_experts > 0 else None))


def llama_config(size: str = "7b", **overrides) -> TransformerConfig:
    """Llama-2/3-style sizes. mlp_dim follows the released models (the
    2/3·4·d multiple-of-256 rule baked in as literals)."""
    presets = {
        "test": dict(num_layers=2, embed_dim=64, num_heads=4, num_kv_heads=2,
                     mlp_dim=128, vocab_size=128, max_seq_len=128),
        "1b": dict(num_layers=16, embed_dim=2048, num_heads=32,
                   num_kv_heads=8, mlp_dim=8192),
        "7b": dict(num_layers=32, embed_dim=4096, num_heads=32,
                   num_kv_heads=32, mlp_dim=11008),
        "8b": dict(num_layers=32, embed_dim=4096, num_heads=32,
                   num_kv_heads=8, mlp_dim=14336, rope_theta=500000.0),
        "13b": dict(num_layers=40, embed_dim=5120, num_heads=40,
                    num_kv_heads=40, mlp_dim=13824),
        "70b": dict(num_layers=80, embed_dim=8192, num_heads=64,
                    num_kv_heads=8, mlp_dim=28672),
    }
    kw = dict(vocab_size=32000, max_seq_len=4096, causal=True,
              norm="rmsnorm", activation="swiglu", rope=True,
              num_kv_heads=None, use_bias=False, tie_embeddings=False,
              # Llama-2/3's released rms_norm_eps. Llama-1 and HF's
              # LlamaConfig default use 1e-6 — override norm_eps to match
              # the checkpoint when importing (torch_import validates via
              # its rms_norm_eps kwarg).
              norm_eps=1e-5)
    kw.update(presets[size])
    kw.update(overrides)
    return TransformerConfig(**kw)
