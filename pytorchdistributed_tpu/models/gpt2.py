"""GPT-2 causal LM (BASELINE config[3]: "GPT-2-medium FSDP + activation
checkpointing").

The reference's only LLM contact is the failed LLaMA auto-shard cell
(reference 03_model_parallel.ipynb:86-89); this is the working TPU-native
replacement, built on the shared TransformerStack so every parallel strategy
(DP/FSDP/TP/ring-attention SP) applies unmodified.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from pytorchdistributed_tpu.models.transformer import (
    Embedder,
    LMHead,
    ProposalHeads,
    TransformerConfig,
    TransformerStack,
    _layer_norm,
    check_pipeline_decomposition,
    make_stage_apply,
    stack_to_stages,
    stages_to_stack,
)


class GPT2(nn.Module):
    cfg: TransformerConfig

    def setup(self):
        cfg = self.cfg
        self.embed = Embedder(cfg)
        self.h = TransformerStack(cfg)
        self.ln_f = _layer_norm(cfg, None)
        if not cfg.tie_embeddings:
            self.lm_head = LMHead(cfg)
        if cfg.spec_heads:
            self.heads = ProposalHeads(cfg)

    def _backbone(self, tokens, deterministic):
        x = self.embed(tokens)
        x = self.h(x, deterministic=deterministic)
        return self.ln_f(x)

    def __call__(self, tokens, *, deterministic: bool = True):
        x = self._backbone(tokens, deterministic)
        if self.cfg.spec_heads and self.is_initializing():
            # materialize the (compact) proposal-head params at init —
            # __call__ is every init path's trace, but only spec_logits /
            # head_logits ever runs the heads
            self.heads(x)
        if self.cfg.tie_embeddings:
            logits = self.embed.attend(x)
        else:
            logits = self.lm_head(x)
        return logits.astype(jnp.float32)

    # -- multi-token proposal heads (ISSUE 16; cfg.spec_heads > 0) -------

    def hidden_states(self, tokens, *, deterministic: bool = True):
        """Backbone + final norm only — the draft decode entry when this
        model carries proposal heads: the caller selects the one live
        position per row, then runs logits_from_hidden/head_logits on the
        selection instead of projecting every chunk position through the
        vocab matrix. Cache-mutating exactly like __call__."""
        return self._backbone(tokens, deterministic)

    def logits_from_hidden(self, x):
        """The base next-token logits for already-normed hidden states
        (the second half of __call__; no cache touched)."""
        if self.cfg.tie_embeddings:
            return self.embed.attend(x).astype(jnp.float32)
        return self.lm_head(x).astype(jnp.float32)

    def head_logits(self, x):
        """Proposal-head logits ``[..., spec_heads, vocab]`` (fp32) for
        final hidden states x — head j predicts the token j+2 ahead,
        through the SAME tied/untied projection as the base head."""
        h = self.heads(x)
        if self.cfg.tie_embeddings:
            return self.embed.attend(h).astype(jnp.float32)
        return self.lm_head(h).astype(jnp.float32)

    def spec_logits(self, tokens, *, deterministic: bool = True):
        """``[b, s, spec_heads + 1, vocab]`` fp32 — index 0 the base
        next-token logits, index j+1 head j's (the token j+2 ahead).
        The distillation training target shape (training/distill.py):
        every position trains the base head AND each proposal head on
        its own shifted offset in one forward."""
        x = self._backbone(tokens, deterministic)
        base = self.logits_from_hidden(x)
        return jnp.concatenate([base[..., None, :], self.head_logits(x)],
                               axis=-2)

    def loss_per_position(self, tokens, targets, *,
                          deterministic: bool = True):
        """Per-position CE without ever materializing [b, s, vocab] logits:
        the LM head runs through ops/fused_ce.chunked_softmax_ce (Megatron's
        fused CE shape). The fp32 logits tensor it avoids is ~31% of
        GPT-2-small's per-step HBM traffic; use via
        training.losses.fused_token_cross_entropy_loss. DP/FSDP path — the
        TP/pipeline paths keep the gather-free CE (transformer.py)."""
        from pytorchdistributed_tpu.ops.fused_ce import chunked_softmax_ce
        from pytorchdistributed_tpu.models.transformer import _cfg_dot_general

        cfg = self.cfg
        x = self._backbone(tokens, deterministic)
        if cfg.tie_embeddings:
            w, transpose = self.embed.tok.embedding, True
        else:
            w, transpose = self.lm_head.kernel, False
        return chunked_softmax_ce(x.astype(cfg.dtype), w.astype(cfg.dtype),
                                  targets, chunk=cfg.ce_chunk,
                                  transpose_w=transpose,
                                  dot_general=_cfg_dot_general(cfg))

    @nn.nowrap
    def pipeline_parts(self):
        """Decomposition for the 1F1B fused train step
        (parallel/pipeline.py `one_f_one_b`; reference schedule spec
        03_model_parallel.ipynb:668-697): pre = Embedder, stages = layer
        groups of the scanned stack, head = ln_f + (tied) logit projection +
        token cross-entropy. The tied embedding appears in both pre and head;
        `merge_grads` sums the two contributions."""
        from pytorchdistributed_tpu.parallel.pipeline import PipelineParts

        cfg = self.cfg
        check_pipeline_decomposition(cfg)

        def split(params):
            pp = params["params"]
            stage = stack_to_stages(pp["h"]["block"], cfg)
            head = {"ln_f": pp["ln_f"]}
            head["proj"] = (pp["embed"]["tok"]["embedding"]
                            if cfg.tie_embeddings
                            else pp["lm_head"]["kernel"])
            return pp["embed"], stage, head

        def pre_apply(pre, tokens):
            return Embedder(cfg).apply({"params": pre}, tokens)

        def head_loss(head, h, targets):
            from pytorchdistributed_tpu.models.transformer import (
                gather_free_ce,
            )

            x = _layer_norm(cfg, None).apply({"params": head["ln_f"]}, h)
            proj = head["proj"].astype(cfg.dtype)
            logits = (x.astype(cfg.dtype) @ proj.T if cfg.tie_embeddings
                      else x.astype(cfg.dtype) @ proj)
            return gather_free_ce(logits, targets).mean()

        def merge_grads(pre_g, stage_g, head_g):
            blocks = stages_to_stack(stage_g, cfg)
            tree = {"embed": pre_g, "h": {"block": blocks},
                    "ln_f": head_g["ln_f"]}
            if cfg.tie_embeddings:
                tok = tree["embed"]["tok"]
                tree["embed"] = dict(tree["embed"])
                tree["embed"]["tok"] = {
                    "embedding": tok["embedding"] + head_g["proj"]}
            else:
                tree["lm_head"] = {"kernel": head_g["proj"]}
            return {"params": tree}

        return PipelineParts(
            split, pre_apply, make_stage_apply(cfg), head_loss, merge_grads,
            stage_apply_aux=(make_stage_apply(cfg, aux=True)
                             if cfg.moe_experts > 0 else None))


def gpt2_config(size: str = "small", **overrides) -> TransformerConfig:
    """Standard GPT-2 family sizes (124M/355M/774M/1.5B)."""
    presets = {
        "test": dict(num_layers=2, embed_dim=64, num_heads=4, vocab_size=128,
                     max_seq_len=128),
        "small": dict(num_layers=12, embed_dim=768, num_heads=12),
        "medium": dict(num_layers=24, embed_dim=1024, num_heads=16),
        "large": dict(num_layers=36, embed_dim=1280, num_heads=20),
        "xl": dict(num_layers=48, embed_dim=1600, num_heads=25),
    }
    kw = dict(vocab_size=50257, max_seq_len=1024, causal=True,
              norm_eps=1e-5)  # GPT-2's released layer_norm_epsilon
    kw.update(presets[size])
    kw.update(overrides)
    return TransformerConfig(**kw)
