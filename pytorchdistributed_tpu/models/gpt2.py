"""GPT-2 causal LM (BASELINE config[3]: "GPT-2-medium FSDP + activation
checkpointing").

The reference's only LLM contact is the failed LLaMA auto-shard cell
(reference 03_model_parallel.ipynb:86-89); this is the working TPU-native
replacement, built on the shared TransformerStack so every parallel strategy
(DP/FSDP/TP/ring-attention SP) applies unmodified.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from pytorchdistributed_tpu.models.transformer import (
    Embedder,
    TransformerConfig,
    TransformerStack,
    _layer_norm,
)
from pytorchdistributed_tpu.parallel.tp import Logical


class GPT2(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, *, deterministic: bool = True):
        cfg = self.cfg
        emb = Embedder(cfg, name="embed")
        x = emb(tokens)
        x = TransformerStack(cfg, name="h")(x, deterministic=deterministic)
        x = _layer_norm(cfg, "ln_f")(x)
        if cfg.tie_embeddings:
            logits = emb.attend(x)
        else:
            logits = nn.Dense(
                cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                kernel_init=nn.with_logical_partitioning(
                    nn.initializers.normal(stddev=0.02),
                    (Logical.EMBED, Logical.VOCAB)),
                name="lm_head",
            )(x)
        return logits.astype(jnp.float32)


def gpt2_config(size: str = "small", **overrides) -> TransformerConfig:
    """Standard GPT-2 family sizes (124M/355M/774M/1.5B)."""
    presets = {
        "test": dict(num_layers=2, embed_dim=64, num_heads=4, vocab_size=128,
                     max_seq_len=128),
        "small": dict(num_layers=12, embed_dim=768, num_heads=12),
        "medium": dict(num_layers=24, embed_dim=1024, num_heads=16),
        "large": dict(num_layers=36, embed_dim=1280, num_heads=20),
        "xl": dict(num_layers=48, embed_dim=1600, num_heads=25),
    }
    kw = dict(vocab_size=50257, max_seq_len=1024, causal=True)
    kw.update(presets[size])
    kw.update(overrides)
    return TransformerConfig(**kw)
