"""BERT-base masked-LM encoder (BASELINE config[2]: "BERT-base MLM, bf16").

Bidirectional TransformerStack (causal=False) + the standard MLM head
(dense → gelu → LN → tied-embedding decode). Batches follow
data/datasets.py's MLM shape: {tokens, targets, loss_mask}.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from pytorchdistributed_tpu.models.transformer import (
    Embedder,
    TransformerConfig,
    TransformerStack,
    _dense_general,
    _layer_norm,
)
from pytorchdistributed_tpu.parallel.tp import Logical


class BertMLM(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, *, deterministic: bool = True):
        cfg = self.cfg
        emb = Embedder(cfg, name="embed")
        x = emb(tokens)
        x = _layer_norm(cfg, "ln_embed")(x).astype(cfg.dtype)
        x = TransformerStack(cfg, name="encoder")(
            x, deterministic=deterministic)
        # MLM transform head (BERT's cls/predictions/transform). Output dim
        # logically "mlp" so TP shards it column-wise (a duplicate "embed"
        # pair would map to an invalid duplicate mesh axis).
        x = _dense_general(cfg.embed_dim, (Logical.EMBED, Logical.MLP), cfg,
                           "mlm_dense")(x)
        x = nn.gelu(x)
        x = _layer_norm(cfg, "mlm_ln")(x)
        logits = emb.attend(x)
        return logits.astype(jnp.float32)


def bert_config(size: str = "base", **overrides) -> TransformerConfig:
    presets = {
        "test": dict(num_layers=2, embed_dim=64, num_heads=4,
                     vocab_size=128, max_seq_len=128),
        "base": dict(num_layers=12, embed_dim=768, num_heads=12),
        "large": dict(num_layers=24, embed_dim=1024, num_heads=16),
    }
    kw = dict(vocab_size=30522, max_seq_len=512, causal=False)
    kw.update(presets[size])
    kw.update(overrides)
    return TransformerConfig(**kw)
