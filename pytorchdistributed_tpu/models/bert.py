"""BERT-base masked-LM encoder (BASELINE config[2]: "BERT-base MLM, bf16").

Bidirectional TransformerStack (causal=False) + the standard MLM head
(dense → gelu → LN → tied-embedding decode). Batches follow
data/datasets.py's MLM shape: {tokens, targets, loss_mask}.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from pytorchdistributed_tpu.models.transformer import (
    Embedder,
    TransformerConfig,
    TransformerStack,
    _dense_general,
    _layer_norm,
    check_pipeline_decomposition,
    gather_free_ce,
    make_stage_apply,
    stack_to_stages,
    stages_to_stack,
)
from pytorchdistributed_tpu.parallel.tp import Logical


class BertMLM(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, *, deterministic: bool = True):
        cfg = self.cfg
        emb = Embedder(cfg, name="embed")
        x = emb(tokens)
        x = _layer_norm(cfg, "ln_embed")(x).astype(cfg.dtype)
        x = TransformerStack(cfg, name="encoder")(
            x, deterministic=deterministic)
        # MLM transform head (BERT's cls/predictions/transform). Output dim
        # logically "mlp" so TP shards it column-wise (a duplicate "embed"
        # pair would map to an invalid duplicate mesh axis).
        x = _dense_general(cfg.embed_dim, (Logical.EMBED, Logical.MLP), cfg,
                           "mlm_dense")(x)
        x = nn.gelu(x, approximate=cfg.gelu_approximate)
        x = _layer_norm(cfg, "mlm_ln")(x)
        logits = emb.attend(x)
        # BERT's cls.predictions decoder bias: tied weights + a free [V]
        # bias (torch_import maps it directly)
        bias = self.param(
            "mlm_bias",
            nn.with_logical_partitioning(nn.initializers.zeros_init(),
                                         (Logical.VOCAB,)),
            (cfg.vocab_size,), cfg.param_dtype)
        return (logits + bias).astype(jnp.float32)

    @nn.nowrap
    def pipeline_parts(self):
        """1F1B decomposition (see GPT2.pipeline_parts): pre = embed +
        ln_embed, stages = encoder layer groups, head = MLM transform +
        tied decode + weighted CE. The masked-LM loss normalizes by the
        GLOBAL mask count, so ``targets_of`` precomputes per-position
        weights w = mask/Σmask; each micro-batch's head_loss is then
        M·Σ(ce·w), making (1/M)·Σ losses equal the full-batch masked mean
        exactly regardless of how masked tokens fall across micro-batches."""
        from pytorchdistributed_tpu.parallel.pipeline import PipelineParts

        cfg = self.cfg
        m = cfg.pipeline_microbatches
        check_pipeline_decomposition(cfg)

        def split(params):
            pp = params["params"]
            stage = stack_to_stages(pp["encoder"]["block"], cfg)
            head = {"mlm_dense": pp["mlm_dense"], "mlm_ln": pp["mlm_ln"],
                    "proj": pp["embed"]["tok"]["embedding"],
                    "mlm_bias": pp["mlm_bias"]}
            pre = {"embed": pp["embed"], "ln_embed": pp["ln_embed"]}
            return pre, stage, head

        def pre_apply(pre, tokens):
            x = Embedder(cfg).apply({"params": pre["embed"]}, tokens)
            return _layer_norm(cfg, None).apply(
                {"params": pre["ln_embed"]}, x).astype(cfg.dtype)

        def targets_of(batch):
            targets = batch["targets"]
            mask = batch.get("loss_mask")
            if mask is None:
                mask = jnp.ones(targets.shape, jnp.float32)
            w = mask.astype(jnp.float32) / jnp.maximum(mask.sum(), 1)
            return {"targets": targets, "w": w}

        def head_loss(head, h, t):
            x = _dense_general(
                cfg.embed_dim, (Logical.EMBED, Logical.MLP), cfg,
                None).apply({"params": head["mlm_dense"]}, h)
            x = nn.gelu(x, approximate=cfg.gelu_approximate)
            x = _layer_norm(cfg, None).apply({"params": head["mlm_ln"]}, x)
            logits = (x.astype(cfg.dtype) @ head["proj"].astype(cfg.dtype).T
                      + head["mlm_bias"].astype(cfg.dtype))
            ce = gather_free_ce(logits, t["targets"])
            # x M: the schedule averages micro-batch losses; the global
            # weights w already carry the 1/Σmask normalization
            return (ce * t["w"]).sum() * m

        def merge_grads(pre_g, stage_g, head_g):
            blocks = stages_to_stack(stage_g, cfg)
            embed_g = dict(pre_g["embed"])
            tok = embed_g["tok"]
            embed_g["tok"] = {"embedding": tok["embedding"] + head_g["proj"]}
            return {"params": {
                "embed": embed_g, "ln_embed": pre_g["ln_embed"],
                "encoder": {"block": blocks},
                "mlm_dense": head_g["mlm_dense"],
                "mlm_ln": head_g["mlm_ln"],
                "mlm_bias": head_g["mlm_bias"],
            }}

        return PipelineParts(
            split, pre_apply, make_stage_apply(cfg), head_loss, merge_grads,
            targets_of,
            stage_apply_aux=(make_stage_apply(cfg, aux=True)
                             if cfg.moe_experts > 0 else None))


def bert_config(size: str = "base", **overrides) -> TransformerConfig:
    presets = {
        "test": dict(num_layers=2, embed_dim=64, num_heads=4,
                     vocab_size=128, max_seq_len=128),
        "base": dict(num_layers=12, embed_dim=768, num_heads=12),
        "large": dict(num_layers=24, embed_dim=1024, num_heads=16),
    }
    # Released-BERT fidelity (torch_import): post-LN residual order, exact
    # erf GELU, layer_norm_eps 1e-12.
    kw = dict(vocab_size=30522, max_seq_len=512, causal=False,
              norm_eps=1e-12, norm_position="post", gelu_approximate=False)
    kw.update(presets[size])
    kw.update(overrides)
    return TransformerConfig(**kw)
