from pytorchdistributed_tpu.models.mlp import MLP, LinearRegression  # noqa: F401
