from pytorchdistributed_tpu.models.mlp import MLP, LinearRegression  # noqa: F401
from pytorchdistributed_tpu.models.transformer import (  # noqa: F401
    TransformerConfig,
    TransformerBlock,
    TransformerStack,
)
from pytorchdistributed_tpu.models.gpt2 import GPT2, gpt2_config  # noqa: F401
from pytorchdistributed_tpu.models.llama import Llama, llama_config  # noqa: F401
from pytorchdistributed_tpu.models.moe import SwitchMoE  # noqa: F401
from pytorchdistributed_tpu.models.bert import BertMLM, bert_config  # noqa: F401
from pytorchdistributed_tpu.models.vit import ViT, ViTConfig, vit_config  # noqa: F401
from pytorchdistributed_tpu.models.resnet import (  # noqa: F401
    ResNet,
    ResNetConfig,
    resnet18,
    resnet50,
)
