"""Shared Transformer core for the model zoo (GPT-2, BERT, ViT).

The reference never ships a transformer (its LLaMA demo,
03_model_parallel.ipynb:86, failed to run), but the BASELINE configs demand
BERT-base MLM, GPT-2-medium FSDP and ViT-L/16 — so one TPU-first core serves
all three. Design decisions (SURVEY.md §7 stance — strategies are sharding
choices, not model rewrites):

  * every parameter carries *logical* axis names via
    `nn.with_logical_partitioning`; parallel/tp.py's rule tables map them to
    mesh axes, so DDP/FSDP/TP/2D reuse this exact module;
  * layers can be stacked with `nn.scan` (one compiled block body instead of
    N inlined copies — faster XLA compiles, and the scanned "stage" axis is
    what pipeline parallelism shards);
  * `remat` wraps the block in `jax.checkpoint` (GPipe's activation
    recomputation, reference 03_model_parallel.ipynb:637-643);
  * attention backend is pluggable: "dense" | "pallas" (flash kernel) |
    "ring" (context parallel over the seq axis) | "ulysses" (all-to-all);
  * compute dtype bf16-by-default for the MXU; LayerNorm/softmax accumulate
    fp32.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable

import flax.linen as nn
import jax
import jax.ad_checkpoint
import jax.numpy as jnp

from pytorchdistributed_tpu.ops.attention import (
    dense_attention,
    paged_gather,
)
from pytorchdistributed_tpu.parallel.tp import Logical

Dtype = Any


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    num_layers: int = 12
    embed_dim: int = 768
    num_heads: int = 12
    mlp_dim: int | None = None          # default 4*embed_dim
    max_seq_len: int = 1024
    causal: bool = True                 # GPT-style; False for BERT/ViT
    dropout_rate: float = 0.0
    dtype: Dtype = jnp.bfloat16         # compute dtype (MXU)
    param_dtype: Dtype = jnp.float32
    attention: str = "dense"            # dense | pallas | ring | ulysses
    # Architecture dialect knobs (GPT-2/BERT/ViT use the defaults; the Llama
    # family — models/llama.py, the working replacement for the reference's
    # failed llama-7b auto-shard cell, 03_model_parallel.ipynb:86-89 — flips
    # all four). One shared core: every strategy applies to every dialect.
    norm: str = "layernorm"             # layernorm | rmsnorm
    # Normalization epsilon. Family presets pin the released models'
    # values (GPT-2/Llama 1e-5, BERT 1e-12) so torch-trained checkpoints
    # import bit-faithfully (models/torch_import.py).
    norm_eps: float = 1e-6
    # "pre" (GPT-2/Llama/ViT: x + Attn(LN(x))) or "post" (original
    # BERT: LN(x + Attn(x))) — released BERT checkpoints are post-LN, so
    # bert_config flips this for architectural fidelity.
    norm_position: str = "pre"          # pre | post
    # GELU flavor: tanh approximation (GPT-2's "gelu_new", the flax
    # default) vs exact erf (BERT's "gelu").
    gelu_approximate: bool = True
    # Fused custom_vjp norm backward (ops/norms.py). A/B'd on the chip
    # (r5, BASELINE.md): wins only on post-LN BERT (+4.3% — twice the
    # LayerNorm sites per block); gpt2s wash, gpt2m/vit/llama small
    # losses. Default stays off; bert's bench config flips it on.
    fused_norms: bool = False
    # Fused chunked-CE head (ops/fused_ce.py) row-chunk size: rows of
    # fp32 logits alive at once (chunk x vocab x 4 B — 2048 x 32000 is
    # ~262 MB on Llama). Smaller chunks trade a little head throughput
    # for HBM headroom that can buy a bigger batch (the r5 llama bs-10
    # probe missed fitting by 32 MB at chunk 2048).
    ce_chunk: int = 2048
    # Flash/ring/ulysses kernel block size (block_q = block_k). None =
    # each kernel's own default — flash and ulysses 1024 (measured
    # fastest for the committed LM configs, BASELINE.md r3/r5), ring 512
    # (blocks tile the PER-SHARD sequence there). A per-config override
    # re-opens the block-size A/B without code edits.
    attn_block: int | None = None
    # Int8 quantized-training matmuls (ops/quant.py — AQT-style dynamic
    # per-channel scaling). "none" = bf16 dots (the committed baselines);
    # "int8_fwd" quantizes the forward weight matmuls (QKV/out, MLP, LM
    # head / fused-CE logits) and keeps the backward in bf16 — the
    # convergence-safe default for the MXU's ~2x int8 rate; "int8" also
    # quantizes both backward contractions with stochastic rounding on the
    # gradient operand. Sharding annotations are untouched: the injectable
    # dot_general is plain HLO, so TP's column/row splits, FSDP gathers and
    # the pipeline stage axis apply to the int8 operands unmodified.
    quant: str = "none"                 # none | int8_fwd | int8
    # Collective-latency hiding for the TP hot path (ops/overlap.py +
    # parallel/overlap.py — ISSUE 5). "xla": monolithic collectives, XLA's
    # latency-hiding scheduler does the overlap (the Trainer wires the
    # scheduler flags); "ring": route the QKV/out/MLP projections through
    # hand-decomposed collective-matmul rings (all-gather→matmul and
    # matmul→reduce-scatter as ppermute chains interleaved with the
    # chunks) whenever the ambient mesh has a tensor axis > 1 — the
    # ASPLOS'23 decomposition, wins at small tp axes / ICI-bound shapes;
    # "off": monolithic collectives AND no scheduler flags (the measured
    # baseline). Composes with quant: the ring gathers int8 shards
    # (comm bytes ÷4). Decode and pipeline stage bodies always take the
    # monolithic path (s=1 can't ring; stages already run inside a
    # manual region).
    overlap: str = "xla"                # ring | xla | off
    activation: str = "gelu"            # gelu | swiglu
    rope: bool = False                  # rotary position embedding (no
    #                                     learned pos table when True)
    rope_theta: float = 10000.0
    num_kv_heads: int | None = None     # < num_heads = grouped-query attn
    use_bias: bool = True               # Llama: no biases anywhere
    # Autoregressive decode mode (inference.generate): attention keeps a
    # [b, max_seq_len, kv_heads, head_dim] K/V cache in the flax "cache"
    # collection and attends over it with a position mask; the embedder
    # tracks its own position counter. Same params as decode=False.
    decode: bool = False
    # Decode-time attention window: score only cache[:, :decode_attend_len]
    # instead of all max_seq_len slots. inference.generate sets it to the
    # (128-rounded) prompt+new total, so per-tick attention cost tracks the
    # sequence actually being generated, not the model's context limit —
    # at 8k context with a 1k generation that is an 8x score-work cut.
    # None = full max_seq_len. Caller contract: positions >= the window are
    # never live (generate guarantees total <= decode_attend_len).
    decode_attend_len: int | None = None
    # Slot-based decode (serving/ — the continuous-batching engine): > 0
    # turns every cache position counter ("index" per attention layer,
    # "pos_index" in the embedder) into a per-row [decode_slots] vector and
    # the cache writes into per-row dynamic_update_slices, so each batch
    # row ("slot") sits at its OWN sequence position — requests of
    # different lengths decode in one compiled step. Requires decode=True
    # and batch == decode_slots; 0 keeps the scalar counters generate()
    # uses (all rows advance together). Chunks of ANY length s decode
    # per-row (positions idx[row] + [0, s): within-chunk causality from
    # the position mask, writes land at [idx, idx+s)) and are
    # BITWISE-equal to s sequential single-token ticks — the multi-token
    # verify contract speculative decoding (ISSUE 8) builds on: a k+1
    # chunk whose suffix is later rejected needs no rollback, because the
    # next chunk's writes start at the accepted length and cover it.
    decode_slots: int = 0
    # Paged KV cache (serving/ — ISSUE 7, vLLM's PagedAttention realized
    # TPU-natively): kv_block_size > 0 replaces each attention layer's
    # dense [slots, max_seq_len, kv_heads, head_dim] cache with ONE pool
    # of kv_blocks fixed-size blocks ([kv_blocks, kv_block_size, kv_heads,
    # head_dim]) plus a per-slot block table ([decode_slots,
    # max_seq_len/kv_block_size] int32 physical-block ids, a "cache"
    # variable the serving engine overrides from host state every call).
    # Writes scatter each slot's token into table[slot, pos//bs] at offset
    # pos%bs; reads gather the slot's blocks back into position order, so
    # the masked attention math — and therefore the emitted tokens — stay
    # BITWISE-equal to the dense path while HBM is bounded by actual
    # resident tokens instead of slots x max_seq_len. Requires decode=True,
    # decode_slots >= 1 and max_seq_len % kv_block_size == 0 (block-padded
    # gathers then cover exactly the dense attend window, keeping the
    # softmax reduction shapes — hence the bits — identical). kv_blocks
    # sizes the pool (block 0 is the engine's reserved trash block).
    kv_block_size: int = 0
    kv_blocks: int = 0
    # KV compression (ISSUE 13). "bf16" stores pool blocks in cfg.dtype
    # (the exact-bitwise default); "int8" stores int8 codes plus fp32
    # per-(token, head) scale planes (`cached_key_scale` /
    # `cached_value_scale`, [kv_blocks, kv_block_size, kv_heads]) in the
    # same cache collection — absmax-over-head_dim quantization at block
    # write time (ops/quant.kv_quantize), dequantized at read. Per-row
    # scales mean the one-token-per-tick decode write never requantizes
    # block neighbours. ~1.9x resident tokens at equal pool HBM
    # (2 bytes/elem + 0 scale vs 1 byte/elem + 4/head_dim). Paged only.
    kv_dtype: str = "bf16"              # bf16 | int8
    # Sliding-window + attention-sink masking (StreamingLLM shape): when
    # kv_window_tokens > 0, query at position p attends position j iff
    # j < kv_sink_tokens or j > p - kv_window_tokens (the first sink
    # tokens plus the trailing window, p itself included). Both are
    # STATIC block multiples so the serving engine can retire
    # fully-dead middle blocks back to the allocator mid-stream without
    # retracing; masking lives in the compiled program, retirement is
    # pure host bookkeeping. 0 = full attention (the default).
    kv_sink_tokens: int = 0
    kv_window_tokens: int = 0
    # Decode-tick attention implementation for the paged pool: "gather"
    # reassembles each slot's blocks into position order and runs the
    # masked dense tail (bitwise-equal to the dense cache — the exact
    # contract); "pallas" runs the scalar-prefetch paged flash kernel
    # (ops/pallas_attention.paged_flash_attention) straight over the
    # block pool on single-token ticks — no gather materialization, the
    # serving default on TPU (tolerance-pinned vs gather, not bitwise:
    # online softmax reassociates the reduction). Multi-token chunks
    # (prefill, speculative verify) always take the gather path.
    paged_attn: str = "gather"          # gather | pallas
    # Per-slot sink/window overrides (ISSUE 15): the slot-batch decode
    # models read sink/window from per-slot ``kv_sinks``/``kv_windows``
    # cache leaves (host-stamped by the serving engine) instead of the
    # static cfg values — what lets one request decode under a tighter
    # window than the pool's. Gather path only (the Pallas kernel takes
    # sink/window as STATIC parameters); off by default so the static
    # mask — and every pinned HLO — is byte-identical.
    per_slot_kv_limits: bool = False
    # Multi-token proposal heads (ISSUE 16, the Medusa recipe — Cai et
    # al. 2024) for a speculative DRAFT model: > 0 adds that many extra
    # decoding heads, each a zero-init SiLU residual block on the final
    # hidden state feeding the SHARED logit projection, so head j
    # predicts the token j+2 positions ahead and at init reproduces the
    # base head's distribution exactly. ONE draft forward then proposes
    # spec_heads+1 tokens instead of rolling the draft autoregressively —
    # inference.draft_and_verify collapses its k+1-step scan to a single
    # head-parallel forward when the draft carries heads. Never on the
    # TARGET model: the verify forward and the rejection kernel are
    # untouched, so losslessness does not depend on this knob.
    spec_heads: int = 0
    scan_layers: bool = True
    remat: bool = False
    # What the checkpoint keeps when remat=True. "full" recomputes the whole
    # block in backward (minimum memory, ~1/3 extra FLOPs). "dots" keeps the
    # outputs of weight matmuls (dot_generals with no batch dims — the
    # q/k/v/o projections and both MLP matmuls) and recomputes only
    # elementwise ops and attention internals: nearly the memory win at a
    # few percent recompute cost, the MFU-friendly default. "dots_norms"
    # additionally keeps the bf16 post-norm activations (see
    # checkpoint_policy).
    remat_policy: str = "dots"    # full | dots | dots_all | dots_norms
    tie_embeddings: bool = True
    # Pipeline parallelism (parallel/pipeline.py): >1 runs the stack as a
    # pipeline over the "pipe" mesh axis with this many stages.
    pipeline_stages: int = 1
    pipeline_microbatches: int = 1
    # "gpipe": forward pipeline, backward by AD — O(M) in-flight residuals.
    # "1f1b": fused train-step schedule (PipeDream-flush) — residuals bounded
    # by stage count; training only, selected by the Trainer's step builder
    # (the pure forward path always pipelines GPipe-style — schedules only
    # differ in where the backward interleaves).
    pp_schedule: str = "gpipe"
    # Mixture-of-Experts (models/moe.py): >0 replaces block MLPs with a
    # top-k routed expert FFN bank, sharded over the "expert" mesh axis.
    # Use losses that add the sown load-balance/z-loss terms
    # (training.losses.moe_token_cross_entropy_loss).
    moe_experts: int = 0
    moe_capacity_factor: float = 1.25
    # 1 = Switch top-1 (raw top-prob gate); 2 = GShard-style top-2 with
    # gates renormalized over the chosen pair. First choices always beat
    # second choices in the capacity race (k-major cumsum ordering).
    moe_top_k: int = 1
    # An MoE FFN every Nth block ((i+1) % N == 0), dense MLP elsewhere.
    # N > 1 requires scan_layers=False: the scanned stack folds every
    # block into ONE body, so blocks cannot differ structurally.
    moe_every: int = 1
    # Routing groups G (per-group capacity ceil(cf · (tokens/G)/e)).
    # 0 = auto: one group per data×fsdp×expert shard when the expert
    # axis is > 1 — the layout whose dispatch is a pure permutation (a
    # literal all_to_all) — else 1, the original global-capacity
    # numerics. decode always routes per-token (capacity never binds →
    # serving output independent of slot neighbours, the bitwise
    # contract). Explicit values let single-device parity runs pin the
    # sharded grouping.
    moe_groups: int = 0
    # "auto" routes dispatch/combine through the explicit all_to_all
    # shard_map path (ops/overlap.expert_a2a_ffn) whenever mesh/shapes
    # tile; "a2a" documents intent (still falls back rather than error);
    # "dense" keeps the einsum path — the bench overlap-A/B knob.
    moe_dispatch: str = "auto"   # auto | a2a | dense
    # > 1 chunks the capacity dim so chunk i's combine a2a overlaps
    # chunk i+1's expert matmuls (the rings' latency-hiding recipe on
    # a2a). Non-dividing chunk counts degrade to monolithic.
    moe_chunks: int = 1

    @property
    def head_dim(self) -> int:
        return self.embed_dim // self.num_heads

    def __post_init__(self):
        if self.quant not in ("none", "int8_fwd", "int8"):
            raise ValueError(f"unknown quant {self.quant!r}; "
                             f"one of ('none', 'int8_fwd', 'int8')")
        from pytorchdistributed_tpu.parallel.overlap import validate_overlap

        validate_overlap(self.overlap)
        kv = self.kv_heads
        if kv <= 0 or self.num_heads % kv:
            raise ValueError(
                f"num_kv_heads {kv} must be a positive divisor of "
                f"num_heads {self.num_heads}")
        if self.decode and self.pipeline_stages > 1:
            raise ValueError("decode mode does not compose with pipeline "
                             "parallelism (generate on a dp/tp mesh instead)")
        if self.decode_slots < 0:
            raise ValueError(f"decode_slots {self.decode_slots} must be >= 0")
        if self.moe_dispatch not in ("auto", "a2a", "dense"):
            raise ValueError(f"unknown moe_dispatch {self.moe_dispatch!r}; "
                             f"one of ('auto', 'a2a', 'dense')")
        if self.moe_chunks < 1 or self.moe_every < 1 or self.moe_groups < 0:
            raise ValueError("moe_chunks/moe_every must be >= 1 and "
                             "moe_groups >= 0")
        if self.moe_experts > 0:
            if self.moe_top_k not in (1, 2):
                raise ValueError(f"moe_top_k {self.moe_top_k} must be 1 "
                                 f"(Switch) or 2 (GShard)")
            if self.moe_top_k > self.moe_experts:
                raise ValueError(
                    f"moe_top_k {self.moe_top_k} needs at least that many "
                    f"experts (moe_experts={self.moe_experts})")
            if self.moe_every > 1 and self.scan_layers:
                raise ValueError(
                    "moe_every > 1 (interleaved MoE) requires "
                    "scan_layers=False: the scanned stack folds every "
                    "block into one body")
        if self.decode_slots > 0 and not self.decode:
            raise ValueError("decode_slots > 0 (slot-based decode) requires "
                             "decode=True")
        if self.kv_block_size < 0 or self.kv_blocks < 0:
            raise ValueError("kv_block_size / kv_blocks must be >= 0")
        if self.kv_block_size > 0:
            if not self.decode or self.decode_slots < 1:
                raise ValueError(
                    "paged KV (kv_block_size > 0) requires decode=True and "
                    "decode_slots >= 1 (the serving engine owns the slots)")
            if self.max_seq_len % self.kv_block_size:
                raise ValueError(
                    f"max_seq_len {self.max_seq_len} must be a multiple of "
                    f"kv_block_size {self.kv_block_size} (block-padded "
                    f"gathers must cover exactly the dense attend window)")
            if self.kv_blocks < 2:
                raise ValueError(
                    f"kv_blocks {self.kv_blocks} must be >= 2 (block 0 is "
                    f"the reserved trash block)")
        if self.kv_dtype not in ("bf16", "int8"):
            raise ValueError(f"unknown kv_dtype {self.kv_dtype!r}; "
                             f"one of ('bf16', 'int8')")
        if self.kv_dtype == "int8" and not self.kv_block_size:
            raise ValueError(
                "kv_dtype='int8' requires the paged KV pool "
                "(kv_block_size > 0): the scale planes are block-shaped")
        if self.paged_attn not in ("gather", "pallas"):
            raise ValueError(f"unknown paged_attn {self.paged_attn!r}; "
                             f"one of ('gather', 'pallas')")
        if self.paged_attn == "pallas" and not self.kv_block_size:
            raise ValueError("paged_attn='pallas' requires the paged KV "
                             "pool (kv_block_size > 0)")
        if self.kv_sink_tokens < 0 or self.kv_window_tokens < 0:
            raise ValueError("kv_sink_tokens / kv_window_tokens must be "
                             ">= 0")
        if self.kv_sink_tokens and not self.kv_window_tokens:
            raise ValueError(
                "kv_sink_tokens without kv_window_tokens is full attention "
                "with extra steps — set kv_window_tokens > 0 to enable the "
                "sliding window, or drop the sinks")
        if self.kv_window_tokens:
            if not self.kv_block_size:
                raise ValueError(
                    "sliding-window KV (kv_window_tokens > 0) requires the "
                    "paged pool (kv_block_size > 0): retirement returns "
                    "whole blocks to the allocator")
            if (self.kv_window_tokens % self.kv_block_size
                    or self.kv_sink_tokens % self.kv_block_size):
                raise ValueError(
                    f"kv_window_tokens {self.kv_window_tokens} and "
                    f"kv_sink_tokens {self.kv_sink_tokens} must be "
                    f"multiples of kv_block_size {self.kv_block_size} "
                    f"(retirement is whole-block)")
        if self.spec_heads < 0:
            raise ValueError(f"spec_heads must be >= 0, got "
                             f"{self.spec_heads}")
        if self.decode_attend_len is not None and (
                self.decode_attend_len < 1
                or self.decode_attend_len > self.max_seq_len):
            raise ValueError(
                f"decode_attend_len {self.decode_attend_len} must be in "
                f"[1, max_seq_len={self.max_seq_len}]")
        if self.decode and self.attention != "dense":
            # The decode path runs its own masked attention over the KV
            # cache; the training-time backend knob does not apply there.
            import warnings

            warnings.warn(
                f"decode=True always uses the cache-masked dense path; "
                f"attention={self.attention!r} is ignored during decode "
                f"(build the decode model with attention='dense' to "
                f"silence this)", stacklevel=3)

    @property
    def kv_heads(self) -> int:
        return (self.num_kv_heads if self.num_kv_heads is not None
                else self.num_heads)

    @property
    def kv_pages(self) -> int:
        """Block-table width: blocks needed to back one full-context slot
        (0 when the dense decode cache is in use)."""
        if not self.kv_block_size:
            return 0
        return self.max_seq_len // self.kv_block_size

    @property
    def ffn_dim(self) -> int:
        return self.mlp_dim if self.mlp_dim is not None else 4 * self.embed_dim


def gather_free_ce(logits, targets):
    """Per-position cross-entropy [b, s] via logsumexp − one-hot
    contraction. Gather-free on purpose: under TP the vocab dim is
    tensor-sharded, and a take-along-axis gather on a sharded dim inside a
    manual-axis shard_map (the 1F1B pipeline) crashes XLA's SPMD
    partitioner; the one-hot contraction partitions cleanly (Megatron's
    vocab-parallel CE shape) and XLA reduces it to the same FLOPs."""
    logits = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(logits, axis=-1)
    true = jnp.einsum(
        "bsv,bsv->bs", logits,
        jax.nn.one_hot(targets, logits.shape[-1], dtype=jnp.float32))
    return lse - true


def checkpoint_policy(name: str):
    """Map a remat_policy name to a jax.checkpoint policy (None = save
    nothing, recompute everything)."""
    cp = jax.checkpoint_policies
    # attn_out/attn_lse are named inside the flash kernel's vjp fwd
    # (ops/pallas_attention.py): saving them spares the backward a full
    # re-run of the attention forward per layer.
    attn_saved = cp.save_only_these_names("attn_out", "attn_lse")
    policies = {
        "full": None,
        "dots": cp.save_from_both_policies(
            cp.dots_with_no_batch_dims_saveable, attn_saved),
        "dots_all": cp.save_from_both_policies(
            cp.dots_saveable, attn_saved),
        # dots_all + the bf16 post-norm activations (norm_out, named in
        # TransformerBlock): trades one bf16 activation of HBM per norm
        # for skipping the fp32-upcast + cross-lane-reduce norm recompute
        # the r3 profile put at ~10% of the Llama-1B step. Unmeasured on
        # hardware as of r3 (chip access dropped) — benchmark before
        # making it a default.
        "dots_norms": cp.save_from_both_policies(
            cp.dots_saveable,
            cp.save_only_these_names("attn_out", "attn_lse", "norm_out")),
    }
    if name not in policies:
        raise ValueError(
            f"unknown remat_policy {name!r}; one of {sorted(policies)}")
    return policies[name]


def _attention_fn(kind: str) -> Callable:
    if kind == "dense":
        return dense_attention
    if kind == "pallas":
        from pytorchdistributed_tpu.ops.pallas_attention import flash_attention
        return flash_attention
    if kind == "ring":
        from pytorchdistributed_tpu.ops.ring_attention import (
            ring_attention_sharded,
        )
        return ring_attention_sharded
    if kind == "ulysses":
        from pytorchdistributed_tpu.ops.ulysses import ulysses_attention
        return ulysses_attention
    raise ValueError(f"unknown attention backend {kind!r}")


def _cfg_dot_general(cfg, default=None):
    """The config's injectable contraction: None/``default`` for
    quant="none", else ops.quant's shared int8 dot_general. One accessor
    so every weight-matmul site (Dense, fused projections, LM heads,
    fused-CE) flips together with the flag."""
    from pytorchdistributed_tpu.ops.quant import dot_general_for

    return dot_general_for(cfg.quant) or default


def _site_dot_general(cfg, parallel, default=None):
    """Per-site contraction for the TP projections: with
    ``cfg.overlap == "ring"`` and a parallel kind declared, the
    ring-routing injectable (parallel/overlap.py — falls back to the
    monolithic/quant path at trace time when no ring applies); otherwise
    exactly `_cfg_dot_general`. ``parallel`` is "column" (w's feature dim
    tensor-sharded) or "row" (contraction dim tensor-sharded), per the
    Megatron decomposition the kernel's logical axes already declare."""
    if parallel is None:
        return _cfg_dot_general(cfg, default)
    from pytorchdistributed_tpu.parallel.overlap import site_dot_general

    return site_dot_general(cfg, parallel, default)


def _dense_general(features: int, kernel_axes, cfg, name, *,
                   use_bias: bool = True, parallel: str | None = None):
    """Dense with logically-partitioned kernel. Head projections keep heads
    flattened into the feature dim (kernel [embed, heads*head_dim] with
    logical axes (embed, heads)): sharding "heads" over the tensor axis then
    splits whole heads, the Megatron attention shard. ``parallel`` names
    the site's Megatron role so overlap="ring" can route it through the
    matching collective-matmul ring."""
    return nn.Dense(
        features,
        use_bias=use_bias,
        dtype=cfg.dtype,
        param_dtype=cfg.param_dtype,
        dot_general=_site_dot_general(cfg, parallel),
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.normal(stddev=0.02), kernel_axes
        ),
        bias_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), kernel_axes[-1:]
        ),
        name=name,
    )


class SelfAttention(nn.Module):
    """Multi-head self-attention with Megatron-ready head sharding.

    ``deterministic`` is a module attribute (not a call arg) so lifted
    transforms (nn.remat / nn.scan) see a plain (x,) call signature —
    jax.checkpoint cannot mark keyword-only args static.
    """

    cfg: TransformerConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        deterministic = self.deterministic
        b, s, _ = x.shape
        # One fused [embed, 3, heads·head_dim] projection instead of three
        # [embed, heads·head_dim] matmuls: N=768-class matmuls run the MXU
        # at a fraction of its rate on v5e (measured 18 vs 43+ TFLOP/s), so
        # folding q/k/v into one dot is a direct step-time win. The q/k/v
        # stack rides its own *unsharded* kernel dim, so under TP the
        # "heads" dim still splits whole heads and every device holds the
        # q, k and v of its heads locally (the Megatron attention shard).
        # Explicit params: nn.DenseGeneral flattens multi-dim features for
        # its kernel init, which breaks rank-3 logical partitioning.
        # Grouped-query attention (kv_heads < num_heads) splits into a q
        # kernel + a fused [embed, 2, kv_heads·head_dim] kv kernel — both
        # still shard whole heads on the "heads" logical axis.
        def heads(t, n):
            t = t.reshape(b, s, n, cfg.head_dim)
            return nn.with_logical_constraint(
                t, (Logical.BATCH, Logical.SEQ, Logical.HEADS, Logical.KV))

        def fused_proj(name, stack, width):
            kernel = self.param(
                f"{name}_kernel",
                nn.with_logical_partitioning(
                    nn.initializers.normal(stddev=0.02),
                    (Logical.EMBED, None, Logical.HEADS) if stack > 1
                    else (Logical.EMBED, Logical.HEADS)),
                (cfg.embed_dim, stack, width) if stack > 1
                else (cfg.embed_dim, width),
                cfg.param_dtype,
            )
            eq = "bse,ecf->bscf" if stack > 1 else "bse,ef->bsf"
            out = jnp.einsum(eq, x, kernel.astype(cfg.dtype),
                             _dot_general=_site_dot_general(
                                 cfg, "column", jax.lax.dot_general))
            if cfg.use_bias:
                bias = self.param(
                    f"{name}_bias",
                    nn.with_logical_partitioning(
                        nn.initializers.zeros_init(),
                        (None, Logical.HEADS) if stack > 1
                        else (Logical.HEADS,)),
                    (stack, width) if stack > 1 else (width,),
                    cfg.param_dtype,
                )
                out = out + bias.astype(cfg.dtype)
            return out

        if cfg.kv_heads == cfg.num_heads:
            fused = fused_proj("qkv", 3, cfg.num_heads * cfg.head_dim)
            q = heads(fused[..., 0, :], cfg.num_heads)
            k = heads(fused[..., 1, :], cfg.num_heads)
            v = heads(fused[..., 2, :], cfg.num_heads)
        else:
            q = heads(fused_proj("q", 1, cfg.num_heads * cfg.head_dim),
                      cfg.num_heads)
            kv = fused_proj("kv", 2, cfg.kv_heads * cfg.head_dim)
            k = heads(kv[..., 0, :], cfg.kv_heads)
            v = heads(kv[..., 1, :], cfg.kv_heads)

        if cfg.decode:
            # slot-based decode (serving/): the position counter is a
            # per-row [decode_slots] vector — each slot advances alone
            if cfg.decode_slots and b != cfg.decode_slots:
                raise ValueError(
                    f"slot-decode batch {b} != decode_slots "
                    f"{cfg.decode_slots} (the engine owns the batch dim)")
            idx_var = self.variable(
                "cache", "index",
                lambda: jnp.zeros((cfg.decode_slots,) if cfg.decode_slots
                                  else (), jnp.int32))
            idx = idx_var.value
        if cfg.rope:
            cos, sin = rope_tables(cfg.max_seq_len, cfg.head_dim,
                                   cfg.rope_theta)
            if cfg.decode and cfg.decode_slots:
                # per-row offsets: gather [b, s] positions from the tables
                pos = idx[:, None] + jnp.arange(s)
                cos, sin = cos[pos], sin[pos]          # [b, s, d/2]
            elif cfg.decode:
                cos = jax.lax.dynamic_slice_in_dim(cos, idx, s)
                sin = jax.lax.dynamic_slice_in_dim(sin, idx, s)
            else:
                cos, sin = cos[:s], sin[:s]
            q, k = apply_rope(q, cos, sin), apply_rope(k, cos, sin)

        rep = cfg.num_heads // cfg.kv_heads

        if cfg.decode:
            if cfg.kv_block_size:
                # Paged KV (ISSUE 7): one pool of fixed-size blocks shared
                # by every slot + a per-slot block table mapping logical
                # block p//bs to a physical pool block. The table is a
                # cache variable only so it rides the collection plumbing
                # — the serving engine overrides it (and idx) from host
                # state on every compiled call, which is what makes prefix
                # reuse and copy-free admission pure host-side
                # bookkeeping. Falls through to the SAME masked-attention
                # tail as the dense layout: only where K/V rows live
                # differs, which is what keeps paged outputs bitwise-equal
                # to dense.
                bs_blk = cfg.kv_block_size
                pool_dtype = (jnp.int8 if cfg.kv_dtype == "int8"
                              else cfg.dtype)
                table_var = self.variable(
                    "cache", "block_table",
                    lambda: jnp.zeros((cfg.decode_slots, cfg.kv_pages),
                                      jnp.int32))
                if cfg.per_slot_kv_limits and cfg.kv_window_tokens:
                    # per-slot sink/window (ISSUE 15): cache leaves only
                    # so they ride the collection plumbing — the engine
                    # host-stamps them on admission/release, defaulting
                    # to the cfg statics, and the mask below reads each
                    # slot's own values
                    sinks_var = self.variable(
                        "cache", "kv_sinks",
                        lambda: jnp.full((cfg.decode_slots,),
                                         cfg.kv_sink_tokens, jnp.int32))
                    windows_var = self.variable(
                        "cache", "kv_windows",
                        lambda: jnp.full((cfg.decode_slots,),
                                         cfg.kv_window_tokens, jnp.int32))
                cached_k = self.variable(
                    "cache", "cached_key", jnp.zeros,
                    (cfg.kv_blocks, bs_blk, cfg.kv_heads, cfg.head_dim),
                    pool_dtype)
                cached_v = self.variable(
                    "cache", "cached_value", jnp.zeros,
                    (cfg.kv_blocks, bs_blk, cfg.kv_heads, cfg.head_dim),
                    pool_dtype)
                if cfg.kv_dtype == "int8":
                    # fp32 dequant scale per written (token, head) row —
                    # same cache collection, so the engine's block
                    # gather/scatter, export/import and prefix shipping
                    # carry the scales with the codes automatically
                    k_scale_var = self.variable(
                        "cache", "cached_key_scale", jnp.zeros,
                        (cfg.kv_blocks, bs_blk, cfg.kv_heads), jnp.float32)
                    v_scale_var = self.variable(
                        "cache", "cached_value_scale", jnp.zeros,
                        (cfg.kv_blocks, bs_blk, cfg.kv_heads), jnp.float32)
                if not self.is_initializing():
                    # scatter each row's s tokens into its table's blocks;
                    # positions past the context (padded prefill tails)
                    # drop into the reserved trash block 0 instead of
                    # clamping onto a live row
                    pos = idx[:, None] + jnp.arange(s)           # [b, s]
                    inb = jnp.clip(pos // bs_blk, 0, cfg.kv_pages - 1)
                    blk = jnp.take_along_axis(table_var.value, inb, axis=1)
                    blk = jnp.where(pos < cfg.max_seq_len, blk, 0)
                    off = pos % bs_blk
                    if cfg.kv_dtype == "int8":
                        from pytorchdistributed_tpu.ops.quant import (
                            kv_quantize,
                        )

                        qk, sk = kv_quantize(k)
                        qv, sv = kv_quantize(v)
                        cached_k.value = cached_k.value.at[blk, off].set(qk)
                        cached_v.value = cached_v.value.at[blk, off].set(qv)
                        k_scale_var.value = (
                            k_scale_var.value.at[blk, off].set(sk))
                        v_scale_var.value = (
                            v_scale_var.value.at[blk, off].set(sv))
                    else:
                        cached_k.value = cached_k.value.at[blk, off].set(
                            k.astype(cfg.dtype))
                        cached_v.value = cached_v.value.at[blk, off].set(
                            v.astype(cfg.dtype))
                    idx_var.value = idx + s
                attend = cfg.decode_attend_len or cfg.max_seq_len
                na = -(-attend // bs_blk)
                attend = na * bs_blk
                if cfg.paged_attn == "pallas" and s == 1:
                    # decode tick on the Pallas paged kernel: q attends
                    # the pool STRAIGHT through the block table — the
                    # gathered [slots, attend, ...] copy below never
                    # materializes. Tolerance-pinned vs the gather path
                    # (online softmax reassociates); chunks (s > 1:
                    # prefill, spec verify) stay on the gather tail.
                    from pytorchdistributed_tpu.ops.pallas_attention import (
                        paged_flash_attention,
                    )

                    out = paged_flash_attention(
                        q[:, 0], cached_k.value, cached_v.value,
                        table_var.value[:, :na], idx,
                        k_scale=(k_scale_var.value
                                 if cfg.kv_dtype == "int8" else None),
                        v_scale=(v_scale_var.value
                                 if cfg.kv_dtype == "int8" else None),
                        sink_tokens=cfg.kv_sink_tokens,
                        window_tokens=cfg.kv_window_tokens,
                    )[:, None].astype(cfg.dtype)
                    kc = vc = None
                else:
                    # gather the attended blocks back into position
                    # order: with max_seq_len % bs == 0 the gathered
                    # window is exactly the dense attend window, so every
                    # reduction below keeps its shape — the bitwise-
                    # parity property the serving tests pin
                    kc = paged_gather(cached_k.value,
                                      table_var.value[:, :na])
                    vc = paged_gather(cached_v.value,
                                      table_var.value[:, :na])
                    if cfg.kv_dtype == "int8":
                        from pytorchdistributed_tpu.ops.quant import (
                            kv_dequantize,
                        )

                        kc = kv_dequantize(
                            kc, paged_gather(k_scale_var.value,
                                             table_var.value[:, :na]),
                            cfg.dtype)
                        vc = kv_dequantize(
                            vc, paged_gather(v_scale_var.value,
                                             table_var.value[:, :na]),
                            cfg.dtype)
            else:
                cached_k = self.variable(
                    "cache", "cached_key", jnp.zeros,
                    (b, cfg.max_seq_len, cfg.kv_heads, cfg.head_dim),
                    cfg.dtype)
                cached_v = self.variable(
                    "cache", "cached_value", jnp.zeros,
                    (b, cfg.max_seq_len, cfg.kv_heads, cfg.head_dim),
                    cfg.dtype)
                if not self.is_initializing():
                    if cfg.decode_slots:
                        # per-row writes: each slot lands at its own
                        # position (vmapped dynamic_update_slice lowers to
                        # a scatter)
                        row = lambda c, u, i: jax.lax.dynamic_update_slice(  # noqa: E731
                            c, u, (i, 0, 0))
                        cached_k.value = jax.vmap(row)(
                            cached_k.value, k.astype(cfg.dtype), idx)
                        cached_v.value = jax.vmap(row)(
                            cached_v.value, v.astype(cfg.dtype), idx)
                    else:
                        cached_k.value = jax.lax.dynamic_update_slice(
                            cached_k.value, k.astype(cfg.dtype),
                            (0, idx, 0, 0))
                        cached_v.value = jax.lax.dynamic_update_slice(
                            cached_v.value, v.astype(cfg.dtype),
                            (0, idx, 0, 0))
                    idx_var.value = idx + s
                # Static attention window (decode_attend_len): the cache
                # stays max_seq_len-sized, but scores only cover the slots
                # generation can actually reach — generate() sets the
                # bound from prompt_len + max_new_tokens.
                attend = cfg.decode_attend_len or cfg.max_seq_len
                kc = cached_k.value[:, :attend]
                vc = cached_v.value[:, :attend]
            if kc is not None:
                if rep > 1:
                    kc = jnp.repeat(kc, rep, axis=2)
                    vc = jnp.repeat(vc, rep, axis=2)
                # Masked dense attention over the live window: the
                # current chunk's token i (absolute position idx+i) sees
                # cache slots j <= idx+i. fp32 softmax like the training
                # backends. (slot decode: idx is [b], so pos/valid grow a
                # leading row dim — each slot masks against its own
                # position)
                pos = (idx[:, None] if cfg.decode_slots
                       else idx) + jnp.arange(s)
                valid = jnp.arange(attend) <= pos[..., None]
                if cfg.kv_window_tokens:
                    # sink + sliding window (StreamingLLM shape): keep
                    # the first sink tokens plus the trailing window —
                    # the positions outside are exactly the rows the
                    # engine retires to the allocator, so the gathered
                    # garbage there is masked before the softmax
                    j = jnp.arange(attend)
                    if cfg.per_slot_kv_limits and cfg.kv_block_size:
                        # per-slot values (ISSUE 15): with every slot at
                        # the cfg defaults this computes the identical
                        # valid mask, so untouched streams stay bitwise
                        snk = sinks_var.value[:, None, None]
                        win = windows_var.value[:, None, None]
                        valid &= ((j[None, None, :] < snk)
                                  | (j[None, None, :]
                                     > pos[..., None] - win))
                    else:
                        valid &= ((j < cfg.kv_sink_tokens)
                                  | (j > pos[..., None]
                                     - cfg.kv_window_tokens))
                scores = jnp.einsum("bihd,bjhd->bhij", q, kc,
                                    preferred_element_type=jnp.float32)
                scores = scores / jnp.sqrt(cfg.head_dim).astype(jnp.float32)
                scores = jnp.where(valid[:, None] if cfg.decode_slots
                                   else valid[None, None], scores, -jnp.inf)
                probs = jax.nn.softmax(scores, axis=-1)
                out = jnp.einsum("bhij,bjhd->bihd",
                                 probs.astype(cfg.dtype), vc,
                                 preferred_element_type=jnp.float32
                                 ).astype(cfg.dtype)
        else:
            if rep > 1 and cfg.attention != "pallas":
                # Broadcast KV groups to full head count for backends that
                # expect equal head counts (dense / ring / ulysses). The
                # Pallas kernel is grouped-query-native: its index maps
                # stream the shared K/V per group, so the 4x repeat (two
                # activation-sized HBM tensors per layer plus the summed
                # dk/dv transpose in backward) never materializes.
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            # flash/ring/ulysses all take the block knobs (shared kernel
            # bodies); dense has no blocks
            if cfg.attn_block is not None and cfg.attention != "dense":
                attn_kwargs = dict(block_q=cfg.attn_block,
                                   block_k=cfg.attn_block)
            else:
                attn_kwargs = {}
            out = _attention_fn(cfg.attention)(q, k, v, causal=cfg.causal,
                                               **attn_kwargs)

        out = out.reshape(b, s, cfg.num_heads * cfg.head_dim)
        out = _dense_general(
            cfg.embed_dim, (Logical.HEADS, Logical.EMBED), cfg, "out",
            use_bias=cfg.use_bias, parallel="row",
        )(out)
        if cfg.dropout_rate > 0:
            out = nn.Dropout(cfg.dropout_rate)(out, deterministic=deterministic)
        return out


class MlpBlock(nn.Module):
    """Column-parallel wi (embed→mlp), row-parallel wo (mlp→embed): under TP
    rules XLA emits exactly Megatron's f/g psum pattern (parallel/tp.py)."""

    cfg: TransformerConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        deterministic = self.deterministic
        if cfg.activation == "swiglu":
            # Llama FFN: silu(x@W_gate) * (x@W_up), gate+up fused into one
            # [embed, 2, ffn] kernel (same MXU-utilization rationale as the
            # fused qkv projection); the stacked "2" dim is unsharded so
            # "mlp"→tensor still splits clean columns.
            kernel = self.param(
                "wi_kernel",
                nn.with_logical_partitioning(
                    nn.initializers.normal(stddev=0.02),
                    (Logical.EMBED, None, Logical.MLP)),
                (cfg.embed_dim, 2, cfg.ffn_dim),
                cfg.param_dtype,
            )
            gu = jnp.einsum("bse,ecf->bscf", x, kernel.astype(cfg.dtype),
                            _dot_general=_site_dot_general(
                                cfg, "column", jax.lax.dot_general))
            if cfg.use_bias:
                bias = self.param(
                    "wi_bias",
                    nn.with_logical_partitioning(
                        nn.initializers.zeros_init(), (None, Logical.MLP)),
                    (2, cfg.ffn_dim),
                    cfg.param_dtype,
                )
                gu = gu + bias.astype(cfg.dtype)
            h = nn.silu(gu[..., 0, :]) * gu[..., 1, :]
        else:
            h = _dense_general(cfg.ffn_dim, (Logical.EMBED, Logical.MLP), cfg,
                               "wi", use_bias=cfg.use_bias,
                               parallel="column")(x)
            h = nn.gelu(h, approximate=cfg.gelu_approximate)
        h = nn.with_logical_constraint(
            h, (Logical.BATCH, Logical.SEQ, Logical.MLP))
        out = _dense_general(cfg.embed_dim, (Logical.MLP, Logical.EMBED), cfg,
                             "wo", use_bias=cfg.use_bias,
                             parallel="row")(h)
        if cfg.dropout_rate > 0:
            out = nn.Dropout(cfg.dropout_rate)(out, deterministic=deterministic)
        return out


def _layer_norm(cfg, name):
    """cfg.fused_norms=True: the custom_vjp norms (ops/norms.py) — fp32
    normalization math like the flax originals (same param trees, so
    checkpoints are unchanged), but bf16-input + row-stat residuals and a
    single-fusion backward instead of AD's saved fp32 intermediates (the
    r3 profile's ~64 ms/step of norm-backward reduce fusions on Llama-1B,
    BASELINE.md). Default: the flax modules, until the A/B is measured on
    the chip."""
    scale_init = nn.with_logical_partitioning(
        nn.initializers.ones_init(), (Logical.EMBED,))
    bias_init = nn.with_logical_partitioning(
        nn.initializers.zeros_init(), (Logical.EMBED,))
    if cfg.fused_norms:
        from pytorchdistributed_tpu.ops.norms import (
            FusedLayerNorm,
            FusedRMSNorm,
        )

        if cfg.norm == "rmsnorm":
            return FusedRMSNorm(epsilon=cfg.norm_eps,
                                param_dtype=cfg.param_dtype,
                                scale_init=scale_init, name=name)
        return FusedLayerNorm(epsilon=cfg.norm_eps,
                              param_dtype=cfg.param_dtype,
                              scale_init=scale_init, bias_init=bias_init,
                              name=name)
    if cfg.norm == "rmsnorm":
        return nn.RMSNorm(
            epsilon=cfg.norm_eps,
            dtype=jnp.float32,
            param_dtype=cfg.param_dtype,
            scale_init=scale_init,
            name=name,
        )
    return nn.LayerNorm(
        epsilon=cfg.norm_eps,
        dtype=jnp.float32,  # normalize in fp32 regardless of compute dtype
        param_dtype=cfg.param_dtype,
        scale_init=scale_init,
        bias_init=bias_init,
        name=name,
    )


def rope_tables(seq_len: int, head_dim: int, theta: float,
                dtype=jnp.float32):
    """(cos, sin) tables ``[seq, head_dim/2]`` for rotary embeddings."""
    freqs = theta ** (-jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                      / head_dim)
    angles = jnp.arange(seq_len, dtype=jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rope(x, cos, sin):
    """Rotate ``x [b, s, h, d]`` by per-position angles (split-halves
    convention: pair dim i with dim i+d/2 — same rotation group as the
    interleaved convention, chosen because it lowers to two slices instead
    of a strided gather). Tables are ``[s, d/2]`` shared across rows, or
    ``[b, s, d/2]`` per-row (slot decode: each slot at its own offset)."""
    d2 = x.shape[-1] // 2
    x1, x2 = x[..., :d2], x[..., d2:]
    if cos.ndim == 3:
        c = cos[:, :, None, :].astype(x.dtype)
        s = sin[:, :, None, :].astype(x.dtype)
    else:
        c = cos[None, :, None, :].astype(x.dtype)
        s = sin[None, :, None, :].astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


class TransformerBlock(nn.Module):
    """Pre-LN block: x + Attn(LN(x)); x + MLP(LN(x))."""

    cfg: TransformerConfig
    deterministic: bool = True
    # None = cfg-driven (every block is MoE when moe_experts > 0); the
    # unrolled stack passes the per-layer moe_every interleaving decision.
    use_moe: bool | None = None

    def _sow_diagnostics(self, x):
        """In-graph block-boundary health stats (ISSUE 6): sow
        RMS/absmax/non-finite-count of the block OUTPUT — and, under
        quantized training, the int8 clip fraction of the activations
        entering the next block's matmuls — into the "diagnostics"
        collection. Gated entirely on the collection being MUTABLE in
        this apply (the Trainer's diagnostics knob passes it through the
        losses): when it isn't, nothing is traced, so a diagnostics-off
        program is byte-identical HLO to one that predates the knob
        (pinned by tests/test_compiled_invariants.py). Under nn.scan the
        sown vectors stack along the layer axis into the [L, 3] table
        telemetry/diagnostics.py collects."""
        if self.is_initializing() or not self.is_mutable_collection(
                "diagnostics"):
            return
        from pytorchdistributed_tpu.telemetry.diagnostics import (
            activation_stat_vec,
        )

        self.sow("diagnostics", "out_stats", activation_stat_vec(x))
        if self.cfg.quant != "none":
            from pytorchdistributed_tpu.ops.quant import saturation_fraction

            self.sow("diagnostics", "int8_sat",
                     saturation_fraction(x, axis=-1))

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        x = nn.with_logical_constraint(
            x, (Logical.BATCH, Logical.SEQ, Logical.EMBED))

        def norm(tag, v):  # named so remat policies can keep it (bf16)
            return jax.ad_checkpoint.checkpoint_name(
                _layer_norm(cfg, tag)(v).astype(cfg.dtype), "norm_out")

        def ffn(h):
            moe = cfg.moe_experts > 0 and (self.use_moe is None
                                           or self.use_moe)
            if moe:
                from pytorchdistributed_tpu.models.moe import SwitchMoE

                return SwitchMoE(cfg, self.deterministic, name="moe")(h)
            return MlpBlock(cfg, self.deterministic, name="mlp")(h)

        attn = SelfAttention(cfg, self.deterministic, name="attn")
        if cfg.norm_position == "post":
            # original-BERT residual order: LN AFTER each sublayer's add
            x = norm("ln1", x + attn(x))
            x = norm("ln2", x + ffn(x))
        else:
            x = x + attn(norm("ln1", x))
            x = x + ffn(norm("ln2", x))
        self._sow_diagnostics(x)
        return nn.with_logical_constraint(
            x, (Logical.BATCH, Logical.SEQ, Logical.EMBED))


def check_pipeline_decomposition(cfg: TransformerConfig) -> int:
    """Shared pipeline_parts validation (GPT-2/Llama/BERT/ViT): returns the
    stage count after checking the scanned layout divides into it."""
    p = cfg.pipeline_stages
    if cfg.num_layers % p:
        raise ValueError(f"num_layers {cfg.num_layers} not divisible by "
                         f"pipeline_stages {p}")
    if not cfg.scan_layers:
        raise ValueError("pipeline_parts requires scan_layers=True")
    return p


def stack_to_stages(blocks, cfg: TransformerConfig):
    """[L, ...]-stacked block params → [P, L/P, ...] stage groups
    (contiguous layers per stage, matching the stage-axis sharding)."""
    p = cfg.pipeline_stages
    return jax.tree.map(
        lambda a: a.reshape(p, cfg.num_layers // p, *a.shape[1:]), blocks)


def stages_to_stack(stage_grads, cfg: TransformerConfig):
    """Inverse of stack_to_stages for the gradient merge."""
    return jax.tree.map(
        lambda a: a.reshape(cfg.num_layers, *a.shape[2:]), stage_grads)


def make_stage_apply(cfg: TransformerConfig, *, aux: bool = False):
    """Build the pipeline stage body shared by the GPipe apply path
    (TransformerStack._pipelined) and the models' 1F1B ``pipeline_parts``:
    apply ``num_layers/pipeline_stages`` TransformerBlocks from a
    stage-stacked param leaf.

    The returned ``stage_apply(stage_leaf, h, key=None)``:
      * with ``key`` (the schedule's ``stage_microbatch_key``), folds the
        layer index on top and runs the blocks stochastic — dropout streams
        are unique per (stage, micro-batch, layer);
      * with ``aux=True`` returns ``(h, aux_sum)`` where aux_sum collects
        the Switch-MoE load-balance values the blocks sow — raw
        ``block.apply`` outside the module system would otherwise drop them
        silently (a collapsing router with no warning).
    """
    per = cfg.num_layers // cfg.pipeline_stages
    det_block = TransformerBlock(cfg, deterministic=True)
    sto_block = TransformerBlock(cfg, deterministic=False)

    def stage_apply(stage_leaf, h, key=None):
        block = det_block if key is None else sto_block

        def rngs_for(j):
            return (None if key is None
                    else {"dropout": jax.random.fold_in(key, j)})

        if aux:
            from pytorchdistributed_tpu.parallel.pipeline import _to_varying

            def layer(carry, xs):
                h, aux_acc = carry
                lp, j = xs
                h, mods = block.apply({"params": lp}, h, rngs=rngs_for(j),
                                      mutable=["losses"])
                from pytorchdistributed_tpu.training.losses import (
                    pipeline_aux_fold,
                )

                aux_acc = aux_acc + pipeline_aux_fold(mods.get("losses", {}))
                return (h, aux_acc), None

            (h, aux_sum), _ = jax.lax.scan(
                layer, (h, _to_varying(jnp.zeros((), jnp.float32))),
                (stage_leaf, jnp.arange(per)))
            return h, aux_sum

        def layer(h, xs):
            lp, j = xs
            return block.apply({"params": lp}, h, rngs=rngs_for(j)), None

        h, _ = jax.lax.scan(layer, h, (stage_leaf, jnp.arange(per)))
        return h

    return stage_apply


class TransformerStack(nn.Module):
    """num_layers blocks, optionally folded into one `nn.scan` whose carry is
    the activations. The scanned parameter axis gets logical name "stage"
    (→ mesh axis "pipe"), which is what pipeline parallelism shards."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        cfg = self.cfg
        if cfg.pipeline_stages > 1 and not self.is_initializing():
            return self._pipelined(x, deterministic)
        block = TransformerBlock
        if cfg.remat:
            # recompute block activations in backward (GPipe's "time for
            # space", reference 03_model_parallel.ipynb:637-643); the
            # policy selects *selective* recomputation (keep matmul
            # outputs, redo cheap elementwise) vs full-block recompute
            block = nn.remat(block, prevent_cse=not cfg.scan_layers,
                             policy=checkpoint_policy(cfg.remat_policy))
        if cfg.scan_layers:
            x, _ = nn.scan(
                lambda mdl, carry, _: (mdl(carry), None),
                variable_axes={"params": 0, "losses": 0, "cache": 0,
                               "diagnostics": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.num_layers,
                metadata_params={nn.PARTITION_NAME: Logical.STAGE},
            )(block(cfg, deterministic, name="block"), x, None)
        else:
            interleave = cfg.moe_experts > 0 and cfg.moe_every > 1
            for i in range(cfg.num_layers):
                kw = ({"use_moe": (i + 1) % cfg.moe_every == 0}
                      if interleave else {})
                x = block(cfg, deterministic, name=f"block_{i}", **kw)(x)
        return x

    def _pipelined(self, x, deterministic: bool):
        """Apply-path GPipe: reuse the layer-stacked params the init-path
        nn.scan created ([L, ...] leaves, logical axis "stage" → mesh axis
        "pipe") and drive them with the shard_map pipeline schedule
        (parallel/pipeline.py) instead of the sequential scan. Dropout rides
        as a per-(stage, micro-batch, layer) key stream; the Switch-MoE aux
        loss is collected from the schedule and re-sown so the moe loss fn
        sees it exactly like the sequential stack's."""
        from pytorchdistributed_tpu.parallel.pipeline import gpipe_spmd

        cfg = self.cfg
        p = cfg.pipeline_stages
        if not cfg.scan_layers:
            raise ValueError("pipeline_stages > 1 requires scan_layers=True")
        if cfg.num_layers % p != 0:
            raise ValueError(
                f"num_layers {cfg.num_layers} not divisible by "
                f"pipeline_stages {p}")
        stacked = self.get_variable("params", "block")
        # [L, ...] -> [P, L/P, ...]: contiguous layer groups become stages,
        # matching the existing stage-axis sharding layout.
        stage_params = jax.tree.map(
            lambda a: a.reshape(p, cfg.num_layers // p, *a.shape[1:]),
            stacked)
        train_dropout = cfg.dropout_rate > 0 and not deterministic
        dropout_rng = self.make_rng("dropout") if train_dropout else None
        collect_aux = cfg.moe_experts > 0
        out = gpipe_spmd(make_stage_apply(cfg, aux=collect_aux),
                         stage_params, x,
                         num_microbatches=cfg.pipeline_microbatches,
                         remat=cfg.remat, remat_policy=cfg.remat_policy,
                         dropout_rng=dropout_rng, collect_aux=collect_aux)
        if collect_aux:
            out, aux = out
            # same convention as the sequential scan's [L]-sow consumed by
            # losses.moe_token_cross_entropy_loss: a mean over layers
            # (gpipe_spmd already averaged over micro-batches); sow is a
            # silent no-op when "losses" isn't mutable (plain CE loss)
            self.sow("losses", "moe_aux", aux / cfg.num_layers)
        return out


class LMHead(nn.Module):
    """Untied logit projection, setup-style so the kernel is an attribute —
    the fused chunked-CE loss path (ops/fused_ce.py) reads it directly
    instead of materializing logits. Param tree: ``lm_head/kernel``."""

    cfg: TransformerConfig

    def setup(self):
        cfg = self.cfg
        self.kernel = self.param(
            "kernel",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02),
                (Logical.EMBED, Logical.VOCAB)),
            (cfg.embed_dim, cfg.vocab_size),
            cfg.param_dtype,
        )

    def __call__(self, x):
        x = x.astype(self.cfg.dtype)
        kernel = self.kernel.astype(self.cfg.dtype)
        dg = _cfg_dot_general(self.cfg)
        if dg is None:
            return x @ kernel
        return dg(x, kernel, (((x.ndim - 1,), (0,)), ((), ())))


class ProposalHeads(nn.Module):
    """Medusa-style multi-token proposal heads (cfg.spec_heads > 0, ISSUE
    16): head j maps the final hidden state x to ``x + silu(W_j x)`` with
    W_j (and its bias) ZERO-initialized — silu(0) == 0, so every head's
    hidden state starts exactly equal to x and its logits (through the
    shared tied/untied projection the model owns) start exactly equal to
    the base next-token head's; silu'(0) == 0.5 keeps gradients flowing,
    so distillation (training/distill.py) specializes each head to its
    own offset from a sane start. Param tree: ``heads/head_{j}/...``."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        """[..., embed] -> [..., spec_heads, embed] per-head hidden
        states, ready for the model's shared logit projection."""
        cfg = self.cfg
        x = x.astype(cfg.dtype)
        outs = []
        for j in range(cfg.spec_heads):
            r = nn.Dense(
                cfg.embed_dim, use_bias=True, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype,
                kernel_init=nn.with_logical_partitioning(
                    # (None, EMBED), not (EMBED, EMBED): logical axis
                    # names may not repeat within one array
                    nn.initializers.zeros, (None, Logical.EMBED)),
                bias_init=nn.initializers.zeros,
                name=f"head_{j}")(x)
            outs.append(x + nn.silu(r))
        return jnp.stack(outs, axis=-2)


class Embedder(nn.Module):
    """Token + learned positional embeddings; `attend` gives the tied logit
    projection (GPT-2 weight tying)."""

    cfg: TransformerConfig

    def setup(self):
        cfg = self.cfg
        self.tok = nn.Embed(
            cfg.vocab_size, cfg.embed_dim,
            dtype=cfg.dtype, param_dtype=cfg.param_dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02),
                (Logical.VOCAB, Logical.EMBED)),
            name="tok",
        )
        if not cfg.rope:  # RoPE models carry position in q/k rotation
            self.pos = self.param(
                "pos",
                nn.with_logical_partitioning(
                    nn.initializers.normal(stddev=0.02),
                    (None, Logical.EMBED)),
                (cfg.max_seq_len, cfg.embed_dim),
                cfg.param_dtype,
            )
            if cfg.decode:
                self.pos_index = self.variable(
                    "cache", "pos_index",
                    lambda: jnp.zeros(
                        (cfg.decode_slots,) if cfg.decode_slots else (),
                        jnp.int32))

    def __call__(self, tokens):
        seq_len = tokens.shape[1]
        x = self.tok(tokens)
        if self.cfg.rope:
            return x
        if self.cfg.decode:
            idx = self.pos_index.value
            if self.cfg.decode_slots:
                # per-row positions (slot decode): gather [b, s, embed]
                p = self.pos[idx[:, None] + jnp.arange(seq_len)]
            else:
                p = jax.lax.dynamic_slice_in_dim(self.pos, idx, seq_len)
            if not self.is_initializing():
                self.pos_index.value = idx + seq_len
            return x + p.astype(self.cfg.dtype)
        return x + self.pos[:seq_len].astype(self.cfg.dtype)

    def attend(self, x):
        x = x.astype(self.cfg.dtype)
        dg = _cfg_dot_general(self.cfg)
        if dg is None:
            return self.tok.attend(x)
        # the tied logit projection [.., embed] x [vocab, embed]ᵀ through
        # the quantized contraction (same math as Embed.attend)
        emb = self.tok.embedding.astype(self.cfg.dtype)
        return dg(x, emb, (((x.ndim - 1,), (1,)), ((), ())))
