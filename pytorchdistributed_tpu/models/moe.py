"""Mixture-of-Experts — top-k routed expert FFNs over the "expert" mesh
axis (SURVEY.md §2c "EP"; the reference has no MoE content at all, so the
design is TPU-first rather than a port).

TPU-idiomatic expert parallelism is *not* a per-token gather/scatter loop:

  * routing is computed densely (router logits → top-k → one-hot dispatch
    and combine tensors), so every shape is static and XLA can tile the
    whole thing onto the MXU;
  * tokens are routed in **G independent groups** with per-group capacity
    ``ceil(cf · (tokens/G)/experts)``. G defaults to one group per
    (data × fsdp × expert) mesh shard — the GShard layout in which the
    dispatch is a pure permutation of equal tiles, so it lowers to a
    literal ``all_to_all`` instead of the reduce-scatter a global
    capacity buffer forces. G = 1 (single-device / dp-only meshes)
    reproduces the original Switch global-capacity numerics exactly;
  * with an expert axis of size > 1 the dispatch/combine run through the
    EXPLICIT exchange (`ops/overlap.expert_a2a_ffn`): custom_vjp inside
    shard_map, chunked capacity pipelining of the combine a2a behind the
    next chunk's expert matmul, and int8 payloads under ``cfg.quant`` —
    2 a2a forward + 2 backward per MoE layer, all counted by the HLO
    census. Elsewhere (decode, pipeline bodies, non-tiling shapes) the
    dense einsum path runs and the auto-partitioner keeps its old job;
  * each expert processes a fixed capacity of slots; overflow tokens skip
    the expert and ride the residual connection (standard Switch
    behavior) — and the overflow FRACTION is sown into the diagnostics
    tables (``moe_overflow``, with the per-expert routing fractions as
    ``moe_frac``) instead of failing silently;
  * ``decode`` models route PER TOKEN (G = tokens, capacity 1): nothing
    ever overflows and a token's routing is independent of its slot
    neighbours, which is what keeps serving output bitwise-equal to
    offline ``generate()`` regardless of batch composition;
  * the Switch load-balancing auxiliary loss and the ST-MoE router
    z-loss are sown into the "losses" collection under distinct names;
    `training.losses.moe_token_cross_entropy_loss` applies each term's
    own weight.

References (PAPERS.md): Switch Transformer (Fedus et al.) for top-1 +
aux loss; GShard (Lepikhin et al.) for grouped dispatch + top-2; ST-MoE
(Zoph et al.) for the router z-loss.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from pytorchdistributed_tpu.parallel.tp import Logical
from pytorchdistributed_tpu.runtime.mesh import Axis


def moe_groups_for(cfg, num_tokens: int, mesh=None) -> int:
    """The routing-group count G for this config/mesh/token count.

    decode → per-token groups (capacity never binds; serving stays
    bitwise vs `generate()`). An explicit ``cfg.moe_groups`` wins next
    (parity tests pin the sharded grouping on a single device with it).
    Auto (0): one group per (data × fsdp × expert) shard when the expert
    axis is real — the layout whose dispatch is a pure permutation —
    else 1, the original global-capacity numerics."""
    if cfg.decode:
        return num_tokens
    if cfg.moe_groups > 0:
        if num_tokens % cfg.moe_groups:
            raise ValueError(
                f"moe_groups {cfg.moe_groups} does not divide the "
                f"token count {num_tokens}")
        return cfg.moe_groups
    if mesh is None:
        from pytorchdistributed_tpu.parallel.overlap import _ambient_mesh

        mesh = _ambient_mesh()
    if mesh is not None and mesh.shape.get(Axis.EXPERT, 1) > 1:
        shards = (mesh.shape.get(Axis.DATA, 1)
                  * mesh.shape.get(Axis.FSDP, 1)
                  * mesh.shape[Axis.EXPERT])
        if num_tokens >= shards and num_tokens % shards == 0:
            return shards
    return 1


class SwitchMoE(nn.Module):
    """Drop-in MLP replacement: top-k routed expert FFNs.

    Call shape ``[batch, seq, embed] -> [batch, seq, embed]``. Expert
    kernels are stacked ``[experts, ...]`` with logical axis
    ``Logical.EXPERT`` so the rule tables shard them over the "expert"
    mesh axis.
    """

    cfg: "TransformerConfig"  # noqa: F821 — transformer.py's config
    deterministic: bool = True

    def _sow_moe_diagnostics(self, frac, overflow):
        """Routing health into the diagnostics tables (ISSUE 6 contract:
        gated entirely on the collection being mutable, so a
        diagnostics-off program's HLO is untouched): ``moe_frac`` — the
        per-expert first-choice routing fractions [e] (uniform = 1/e; a
        collapsing router shows up as one hot column), and
        ``moe_overflow`` — the fraction of routing assignments that lost
        the capacity race and rode the residual."""
        if self.is_initializing() or not self.is_mutable_collection(
                "diagnostics"):
            return
        self.sow("diagnostics", "moe_frac", frac)
        self.sow("diagnostics", "moe_overflow", overflow)

    def _use_a2a(self, mesh, num_groups: int, experts: int) -> bool:
        """Route dispatch/combine through the explicit a2a shard_map path
        (`ops/overlap.expert_a2a_ffn`)? Mirrors site_dot_general's
        gating: never under decode (per-token groups / single-chip) or
        inside a pipeline stage body (already a manual region), and only
        when the shapes tile the mesh — "a2a" intent still falls back
        rather than erroring, "dense" opts out (the bench A/B knob)."""
        cfg = self.cfg
        if cfg.moe_dispatch == "dense" or cfg.decode:
            return False
        if getattr(cfg, "pipeline_stages", 1) > 1:
            return False
        from pytorchdistributed_tpu.ops.overlap import expert_a2a_applicable

        return expert_a2a_applicable(num_groups, experts, mesh)

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        e, d, f = cfg.moe_experts, cfg.embed_dim, cfg.ffn_dim
        k = min(getattr(cfg, "moe_top_k", 1), e)
        b, s, _ = x.shape
        g = b * s  # token count
        from pytorchdistributed_tpu.parallel.overlap import _ambient_mesh

        mesh = _ambient_mesh()
        G = moe_groups_for(cfg, g, mesh)
        n = g // G  # tokens per routing group
        capacity = max(1, math.ceil(cfg.moe_capacity_factor * n / e))

        # -- router (fp32 for a stable softmax/top_k) --------------------
        router_kernel = self.param(
            "router",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02),
                (Logical.EMBED, Logical.EXPERT)),
            (d, e), jnp.float32)
        xg = x.reshape(G, n, d)
        xg = nn.with_logical_constraint(
            xg, (Logical.EGROUP, None, Logical.EMBED))
        logits = jnp.einsum("gnd,de->gne", xg.astype(jnp.float32),
                            router_kernel)
        probs = jax.nn.softmax(logits, axis=-1)

        # top-k choices. lax.top_k breaks probability ties toward the
        # LOWER expert index — deterministic, unlike a sort on floats.
        gate, idx = lax.top_k(probs, k)                     # [G, n, k]
        if k > 1:
            # GShard-style renormalization over the chosen pair; k=1
            # keeps the raw top probability (the Switch gate) so the
            # original top-1 numerics are untouched.
            gate = gate / jnp.sum(gate, axis=-1, keepdims=True)
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # [G, n, k, e]

        # Switch aux loss on FIRST choices: e · Σ_e frac_e · mean_prob_e.
        # Minimized (=1) at uniform routing; sown for the loss fn to add.
        frac = onehot[:, :, 0, :].mean((0, 1))
        aux = e * jnp.sum(frac * probs.mean((0, 1)))
        self.sow("losses", "moe_aux", aux)
        # ST-MoE router z-loss: mean(logsumexp(logits)²) keeps router
        # logits small/stable. Sown under its own name — the loss fn
        # separates it from the aux leaves and applies its own weight.
        zloss = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
        self.sow("losses", "moe_zloss", zloss)

        # -- capacity assignment: each choice takes its expert's next
        # free slot, in K-MAJOR priority order — the [G, k·n] flatten
        # puts EVERY token's first choice ahead of ANY second choice, so
        # the cumsum race is deterministic and top-1 traffic can never be
        # displaced by top-2 spillover (GShard's ordering).
        oh = onehot.transpose(0, 2, 1, 3).reshape(G, k * n, e)
        pos = jnp.sum(jnp.cumsum(oh, axis=1) * oh,
                      axis=-1).astype(jnp.int32) - 1        # [G, k·n]
        kept = (pos < capacity).astype(jnp.float32)         # overflow→residual
        disp = (oh * kept[..., None])[..., None] * jax.nn.one_hot(
            pos, capacity, dtype=jnp.float32)[:, :, None, :]
        disp = disp.reshape(G, k, n, e, capacity)
        dispatch = jnp.sum(disp, axis=1)                    # [G, n, e, c]
        combine = jnp.sum(
            disp * gate.transpose(0, 2, 1)[..., None, None], axis=1)

        # the overflow fraction, surfaced instead of silently riding the
        # residual: 1 − (assignments that won a slot) / (all assignments)
        overflow = 1.0 - jnp.sum(oh * kept[..., None]) / (G * k * n)
        self._sow_moe_diagnostics(frac, overflow)

        # -- expert FFNs on [e, c, d] slots ------------------------------
        wi = self.param(
            "wi",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02),
                (Logical.EXPERT, Logical.EMBED, Logical.MLP)),
            (e, d, f), cfg.param_dtype)
        wo = self.param(
            "wo",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02),
                (Logical.EXPERT, Logical.MLP, Logical.EMBED)),
            (e, f, d), cfg.param_dtype)

        if self._use_a2a(mesh, G, e):
            from pytorchdistributed_tpu.ops.overlap import expert_a2a_ffn

            out = expert_a2a_ffn(
                xg.astype(cfg.dtype), dispatch.astype(cfg.dtype),
                combine.astype(cfg.dtype), wi.astype(cfg.dtype),
                wo.astype(cfg.dtype), mesh=mesh,
                quant=None if cfg.quant == "none" else cfg.quant,
                chunks=getattr(cfg, "moe_chunks", 1),
                gelu_approx=cfg.gelu_approximate,
                preferred_element_type=cfg.dtype)
        else:
            slots = jnp.einsum("gnec,gnd->gecd", dispatch.astype(cfg.dtype),
                               xg.astype(cfg.dtype))
            slots = nn.with_logical_constraint(
                slots, (None, Logical.EXPERT, None, Logical.EMBED))
            h = nn.gelu(
                jnp.einsum("gecd,edf->gecf", slots, wi.astype(cfg.dtype)),
                approximate=cfg.gelu_approximate)
            h = nn.with_logical_constraint(
                h, (None, Logical.EXPERT, None, Logical.MLP))
            out_slots = jnp.einsum("gecf,efd->gecd", h, wo.astype(cfg.dtype))
            out = jnp.einsum("gnec,gecd->gnd", combine.astype(cfg.dtype),
                             out_slots)
        out = out.reshape(g, d)
        if cfg.dropout_rate > 0:
            out = nn.Dropout(cfg.dropout_rate)(
                out, deterministic=self.deterministic)
        return out.reshape(b, s, d)
