"""Mixture-of-Experts — Switch-style top-1 routing over the "expert" mesh
axis (SURVEY.md §2c "EP", the optional strategy; the reference has no MoE
content at all, so the design is TPU-first rather than a port).

TPU-idiomatic expert parallelism is *not* a per-token gather/scatter loop:

  * routing is computed densely (router logits → top-1 → one-hot dispatch
    and combine tensors), so every shape is static and XLA can tile the
    whole thing onto the MXU;
  * dispatch/combine are einsums against a ``[tokens, experts, capacity]``
    one-hot — when tokens are sharded over "data" and the expert dim of the
    stacked expert MLPs over "expert" (rule table parallel/tp.py
    ``Logical.EXPERT → Axis.EXPERT``), XLA lowers these einsums to the
    all_to_all exchange that GPU frameworks hand-write;
  * each expert processes a fixed ``capacity = ceil(cf · tokens/experts)``
    slots; overflow tokens skip the expert and ride the residual connection
    (standard Switch behavior) — static shapes, no data-dependent control
    flow inside jit;
  * the Switch load-balancing auxiliary loss is sown into the "losses"
    collection; `training.losses.moe_aux_loss` collects it.

Reference for the pattern (PAPERS.md): Switch Transformer (Fedus et al.),
as realized in public JAX codebases (flaxformer/t5x-style dense dispatch).
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorchdistributed_tpu.parallel.tp import Logical


class SwitchMoE(nn.Module):
    """Drop-in MLP replacement: top-1 routed expert FFNs.

    Call shape ``[batch, seq, embed] -> [batch, seq, embed]``. Expert
    kernels are stacked ``[experts, ...]`` with logical axis
    ``Logical.EXPERT`` so the "tp" rule table shards them over the "expert"
    mesh axis.
    """

    cfg: "TransformerConfig"  # noqa: F821 — transformer.py's config
    deterministic: bool = True

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        e, d, f = cfg.moe_experts, cfg.embed_dim, cfg.ffn_dim
        b, s, _ = x.shape
        g = b * s  # token count
        capacity = max(1, math.ceil(cfg.moe_capacity_factor * g / e))

        # -- router (fp32 for a stable softmax/argmax) -------------------
        router_kernel = self.param(
            "router",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02),
                (Logical.EMBED, Logical.EXPERT)),
            (d, e), jnp.float32)
        tokens = x.reshape(g, d)
        logits = tokens.astype(jnp.float32) @ router_kernel     # [g, e]
        probs = jax.nn.softmax(logits, axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)                 # [g]
        gate = jnp.max(probs, axis=-1)                          # [g]
        expert_onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)

        # Switch aux loss: e · Σ_e (token fraction to e) · (mean prob of e).
        # Minimized (=1) at uniform routing; sown for the loss fn to add.
        frac = expert_onehot.mean(0)
        aux = e * jnp.sum(frac * probs.mean(0))
        self.sow("losses", "moe_aux", aux)

        # -- dispatch: each token takes the next free slot of its expert --
        pos = jnp.sum(jnp.cumsum(expert_onehot, axis=0) * expert_onehot,
                      axis=-1).astype(jnp.int32) - 1            # [g]
        kept = pos < capacity                                   # overflow→residual
        dispatch = (expert_onehot * kept[:, None])[:, :, None] * jax.nn.one_hot(
            pos, capacity, dtype=jnp.float32)[:, None, :]       # [g, e, c]
        combine = dispatch * gate[:, None, None]

        # -- expert FFNs on [e, c, d] slots ------------------------------
        wi = self.param(
            "wi",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02),
                (Logical.EXPERT, Logical.EMBED, Logical.MLP)),
            (e, d, f), cfg.param_dtype)
        wo = self.param(
            "wo",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02),
                (Logical.EXPERT, Logical.MLP, Logical.EMBED)),
            (e, f, d), cfg.param_dtype)
        slots = jnp.einsum("gec,gd->ecd", dispatch.astype(cfg.dtype),
                           tokens.astype(cfg.dtype))
        slots = nn.with_logical_constraint(
            slots, (Logical.EXPERT, None, Logical.EMBED))
        h = nn.gelu(jnp.einsum("ecd,edf->ecf", slots, wi.astype(cfg.dtype)),
                    approximate=cfg.gelu_approximate)
        h = nn.with_logical_constraint(h, (Logical.EXPERT, None, Logical.MLP))
        out_slots = jnp.einsum("ecf,efd->ecd", h, wo.astype(cfg.dtype))
        out = jnp.einsum("gec,ecd->gd", combine.astype(cfg.dtype), out_slots)
        if cfg.dropout_rate > 0:
            out = nn.Dropout(cfg.dropout_rate)(
                out, deterministic=self.deterministic)
        return out.reshape(b, s, d)
