"""PyTorch → TPU-framework weight import (GPT-2, Llama, BERT, ViT).

The migration story for users of the reference stack: take the
``state_dict`` of a torch/HuggingFace model — the ecosystem the reference
trains in — and load it into this framework's param trees, so a
torch-pretrained checkpoint serves, fine-tunes, and shards here without
retraining. Pure tensor re-layout on host numpy: no torch autograd, no
device work, and transformers is only needed by the tests.

Conventions handled:
  * HF GPT-2 stores ``Conv1D`` weights ``[in, out]`` (y = x@W + b) — no
    transpose; Llama stores ``nn.Linear`` weights ``[out, in]`` —
    transposed on import.
  * Our fused stacks: GPT-2 ``qkv_kernel [E, 3, H·D]`` from c_attn's
    contiguous q|k|v columns; Llama ``kv_kernel [E, 2, KV·D]`` and
    swiglu ``wi_kernel [E, 2, F]`` (index 0 = gate/silu, 1 = up — the
    convention in models/transformer.py MlpBlock).
  * ``scan_layers=True`` trees stack the per-layer leaves on a leading
    layer axis (``h.block``); unrolled trees use ``h.block_{i}``.
  * Architecture fidelity comes from the family presets: ``norm_eps``
    (gpt2 1e-5, llama 1e-5, bert/vit 1e-12), BERT's post-LN order and
    exact GELU, ViT's exact GELU — logit-level parity vs the torch
    forward is asserted in tests/test_torch_import.py.

Tensors are converted via ``.detach().cpu().numpy()`` when torch tensors
are passed; plain numpy arrays work too (e.g. from a safetensors reader).
"""

from __future__ import annotations

import numpy as np


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch.Tensor without importing torch
        t = t.detach().cpu().numpy()
    return np.asarray(t, np.float32)


def _lin(sd, key) -> np.ndarray:
    """torch ``nn.Linear`` weight ``[out, in]`` → our kernel ``[in, out]``
    (HF GPT-2's Conv1D is already ``[in, out]`` and skips this)."""
    return _np(sd[key]).T


def _check_positions(pos: np.ndarray, cfg) -> np.ndarray:
    if pos.shape[0] < cfg.max_seq_len:
        raise ValueError(
            f"checkpoint has {pos.shape[0]} positions < cfg.max_seq_len "
            f"{cfg.max_seq_len}")
    return pos[: cfg.max_seq_len]


def _finish(tree: dict, cfg) -> dict:
    """Cast every leaf to cfg.param_dtype so the imported tree matches a
    model-initialized one exactly (a bf16-param config must not silently
    double its footprint with fp32 leaves)."""
    import jax

    return jax.tree.map(lambda a: a.astype(cfg.param_dtype), tree)


def _hf_encoder_block(sd, p: str, attn: str, ln1: str, ln2: str) -> dict:
    """One HF post-2018-encoder layer (BERT/ViT share the shape): stacked
    q/k/v Linears under ``attn`` prefix, dense out/wi/wo, two LayerNorms
    named ``ln1``/``ln2`` relative to ``p``."""
    qkv_w = np.stack([_lin(sd, attn + f"{n}.weight")
                      for n in ("query", "key", "value")], axis=1)
    qkv_b = np.stack([_np(sd[attn + f"{n}.bias"])
                      for n in ("query", "key", "value")])
    return {
        "ln1": {"scale": _np(sd[p + ln1 + ".weight"]),
                "bias": _np(sd[p + ln1 + ".bias"])},
        "ln2": {"scale": _np(sd[p + ln2 + ".weight"]),
                "bias": _np(sd[p + ln2 + ".bias"])},
        "attn": {
            "qkv_kernel": qkv_w,            # [E, 3, E]
            "qkv_bias": qkv_b,              # [3, E]
            "out": {"kernel": _lin(sd, p + "attention.output.dense.weight"),
                    "bias": _np(sd[p + "attention.output.dense.bias"])},
        },
        "mlp": {
            "wi": {"kernel": _lin(sd, p + "intermediate.dense.weight"),
                   "bias": _np(sd[p + "intermediate.dense.bias"])},
            "wo": {"kernel": _lin(sd, p + "output.dense.weight"),
                   "bias": _np(sd[p + "output.dense.bias"])},
        },
    }


def _stack_blocks(blocks: list[dict], scan_layers: bool) -> dict:
    """Per-layer param subtrees → the stack's tree: stacked on a leading
    layer axis under "block" (scan_layers) or "block_{i}" children."""
    if not scan_layers:
        return {f"block_{i}": b for i, b in enumerate(blocks)}
    import jax

    return {"block": jax.tree.map(lambda *ls: np.stack(ls), *blocks)}


def gpt2_params_from_torch(state_dict, cfg) -> dict:
    """HF ``GPT2LMHeadModel.state_dict()`` → ``{"params": ...}`` for
    models/gpt2.GPT2 built with ``gpt2_config(...)`` (tied embeddings).

    Accepts keys with or without the ``transformer.`` prefix. ``wpe`` may
    be longer than ``cfg.max_seq_len`` (sliced); shorter raises.
    """
    sd = {k.removeprefix("transformer."): v for k, v in state_dict.items()}
    e = cfg.embed_dim
    if not cfg.tie_embeddings:
        raise ValueError("GPT-2 import expects tie_embeddings=True "
                         "(the released models tie wte and lm_head)")
    wpe = _check_positions(_np(sd["wpe.weight"]), cfg)

    def block(i):
        p = f"h.{i}."
        qkv_w = _np(sd[p + "attn.c_attn.weight"])       # [E, 3E], x@W
        qkv_b = _np(sd[p + "attn.c_attn.bias"])         # [3E]
        return {
            "ln1": {"scale": _np(sd[p + "ln_1.weight"]),
                    "bias": _np(sd[p + "ln_1.bias"])},
            "ln2": {"scale": _np(sd[p + "ln_2.weight"]),
                    "bias": _np(sd[p + "ln_2.bias"])},
            "attn": {
                "qkv_kernel": qkv_w.reshape(e, 3, e),
                "qkv_bias": qkv_b.reshape(3, e),
                "out": {"kernel": _np(sd[p + "attn.c_proj.weight"]),
                        "bias": _np(sd[p + "attn.c_proj.bias"])},
            },
            "mlp": {
                "wi": {"kernel": _np(sd[p + "mlp.c_fc.weight"]),
                       "bias": _np(sd[p + "mlp.c_fc.bias"])},
                "wo": {"kernel": _np(sd[p + "mlp.c_proj.weight"]),
                       "bias": _np(sd[p + "mlp.c_proj.bias"])},
            },
        }

    return _finish({"params": {
        "embed": {"tok": {"embedding": _np(sd["wte.weight"])},
                  "pos": wpe},
        "h": _stack_blocks([block(i) for i in range(cfg.num_layers)],
                           cfg.scan_layers),
        "ln_f": {"scale": _np(sd["ln_f.weight"]),
                 "bias": _np(sd["ln_f.bias"])},
    }}, cfg)


def bert_params_from_torch(state_dict, cfg) -> dict:
    """HF ``BertForMaskedLM.state_dict()`` → ``{"params": ...}`` for
    models/bert.BertMLM built with ``bert_config(...)`` (post-LN blocks,
    exact GELU, eps 1e-12 — the preset pins all three).

    Single-segment convention: HF adds ``token_type_embeddings[0]`` to
    every position when ``token_type_ids`` are all zero (the MLM batch
    contract here has no segment ids), so that row folds into the
    position table. The pooler is dropped (MLM never reads it)."""
    sd = state_dict
    emb = "bert.embeddings."
    pos = _check_positions(_np(sd[emb + "position_embeddings.weight"]), cfg)
    pos = pos + _np(sd[emb + "token_type_embeddings.weight"])[0]

    def lin(key):
        return _lin(sd, key)

    def block(i):
        p = f"bert.encoder.layer.{i}."
        return _hf_encoder_block(sd, p, p + "attention.self.",
                                 ln1="attention.output.LayerNorm",
                                 ln2="output.LayerNorm")

    t = "cls.predictions.transform."
    return _finish({"params": {
        "embed": {
            "tok": {"embedding": _np(sd[emb + "word_embeddings.weight"])},
            "pos": pos},
        "ln_embed": {"scale": _np(sd[emb + "LayerNorm.weight"]),
                     "bias": _np(sd[emb + "LayerNorm.bias"])},
        "encoder": _stack_blocks(
            [block(i) for i in range(cfg.num_layers)], cfg.scan_layers),
        "mlm_dense": {"kernel": lin(t + "dense.weight"),
                      "bias": _np(sd[t + "dense.bias"])},
        "mlm_ln": {"scale": _np(sd[t + "LayerNorm.weight"]),
                   "bias": _np(sd[t + "LayerNorm.bias"])},
        "mlm_bias": _np(sd["cls.predictions.bias"]),
    }}, cfg)


def vit_params_from_torch(state_dict, cfg) -> dict:
    """HF ``ViTForImageClassification.state_dict()`` → ``{"params": ...}``
    for models/vit.ViT built with ``vit_config(...)``. Images here are
    NHWC (the TPU-native layout) — callers feeding torch-preprocessed
    NCHW arrays transpose at the boundary. ``cfg`` is the ViTConfig."""
    sd = state_dict
    tcfg = cfg.transformer

    def lin(key):
        return _lin(sd, key)

    def block(i):
        p = f"vit.encoder.layer.{i}."
        return _hf_encoder_block(sd, p, p + "attention.attention.",
                                 ln1="layernorm_before",
                                 ln2="layernorm_after")

    emb = "vit.embeddings."
    pos = _np(sd[emb + "position_embeddings"])[0]     # [N+1, E]
    if pos.shape[0] != cfg.num_patches + 1:
        # no slicing here (unlike text wpe): the patch grid must match —
        # a resolution/patch-size mismatch needs interpolation, not a crop
        raise ValueError(
            f"checkpoint has {pos.shape[0]} patch positions but the config "
            f"({cfg.image_size}px / {cfg.patch_size}px patches) needs "
            f"{cfg.num_patches + 1}")
    return _finish({"params": {
        "embed": {
            "patch_embed": {
                "kernel": _convw(
                    sd[emb + "patch_embeddings.projection.weight"]),
                "bias": _np(sd[emb + "patch_embeddings.projection.bias"])},
            "cls": _np(sd[emb + "cls_token"]),            # [1, 1, E]
            "pos_embed": pos,
        },
        "encoder": _stack_blocks(
            [block(i) for i in range(tcfg.num_layers)], tcfg.scan_layers),
        "ln_f": {"scale": _np(sd["vit.layernorm.weight"]),
                 "bias": _np(sd["vit.layernorm.bias"])},
        "head": {"kernel": lin("classifier.weight"),
                 "bias": _np(sd["classifier.bias"])},
    }}, tcfg)


def _convw(t) -> np.ndarray:
    """torch Conv2d kernel [O, I, kh, kw] → flax NHWC kernel [kh, kw, I, O]."""
    return _np(t).transpose(2, 3, 1, 0)


def _bn_pair(sd, p: str) -> tuple[dict, dict]:
    """One torch BatchNorm's tensors → (our params {scale, bias},
    our batch_stats {mean, var}). ``num_batches_tracked`` is dropped: it
    only feeds torch's momentum=None cumulative-average mode; our EMA is
    momentum-based (training/trainer.py BN_EMA_MOMENTUM)."""
    return ({"scale": _np(sd[p + "weight"]), "bias": _np(sd[p + "bias"])},
            {"mean": _np(sd[p + "running_mean"]),
             "var": _np(sd[p + "running_var"])})


def resnet_params_from_torch(state_dict, cfg) -> dict:
    """torchvision ResNet ``state_dict()`` → ``{"params": ...,
    "batch_stats": ...}`` for models/resnet.ResNet — the migration bridge
    for the reference's own vision model (``ModelParallelResNet50`` is
    built from torchvision's resnet50, reference
    03_model_parallel.ipynb:325-349 (cell 5); BASELINE config[1]).

    Handles both block types (Bottleneck: resnet50-style conv1..3;
    BasicBlock: resnet18-style conv1..2) and the downsample branch
    (torch ``downsample.0/.1`` → our ``down_conv``/``down_bn``). Conv
    kernels relayout NCHW→NHWC; BN ``weight/bias`` become scale/bias
    params and ``running_mean/var`` become the "batch_stats" EMA buffers
    torch semantics call non-parameter state — exactly how our Trainer
    carries them (buffers outside the optimizer tree).

    Requires ``cfg.torch_padding=True``: under XLA SAME the stride-2
    convs and the stem max-pool pad asymmetrically, so torch weights in a
    SAME model would see every spatial activation shifted — close-enough
    logits that silently aren't the released model. Build with
    ``resnet50(torch_padding=True)``."""
    sd = state_dict
    if not cfg.torch_padding:
        raise ValueError(
            "torch weights need torch conv padding: build the model with "
            "resnet50(torch_padding=True) — XLA SAME pads stride-2 convs "
            "asymmetrically and would shift every activation")
    n_classes = _np(sd["fc.weight"]).shape[0]
    if n_classes != cfg.num_classes:
        raise ValueError(f"checkpoint fc has {n_classes} classes, config "
                         f"has {cfg.num_classes}")
    convs = ("conv1", "conv2", "conv3") if cfg.bottleneck else (
        "conv1", "conv2")

    params: dict = {}
    stats: dict = {}
    params["stem_conv"] = {"kernel": _convw(sd["conv1.weight"])}
    params["stem_bn"], stats["stem_bn"] = _bn_pair(sd, "bn1.")
    for stage, n_blocks in enumerate(cfg.stage_sizes):
        for b in range(n_blocks):
            t = f"layer{stage + 1}.{b}."          # torchvision naming
            ours = f"stage{stage + 1}_block{b}"   # models/resnet naming
            bp: dict = {}
            bs: dict = {}
            for i, conv in enumerate(convs, start=1):
                bp[conv] = {"kernel": _convw(sd[t + f"conv{i}.weight"])}
                bp[f"bn{i}"], bs[f"bn{i}"] = _bn_pair(sd, t + f"bn{i}.")
            if t + "downsample.0.weight" in sd:
                bp["down_conv"] = {
                    "kernel": _convw(sd[t + "downsample.0.weight"])}
                bp["down_bn"], bs["down_bn"] = _bn_pair(
                    sd, t + "downsample.1.")
            params[ours] = bp
            stats[ours] = bs
    params["fc"] = {"kernel": _lin(sd, "fc.weight"),
                    "bias": _np(sd["fc.bias"])}
    # all-fp32 on purpose (no _finish): ResNet params/stats are fp32 with
    # bf16 compute via cfg.dtype, matching a model-initialized tree
    return {"params": params, "batch_stats": stats}


def resnet50_params_from_torch(state_dict, cfg) -> dict:
    """`resnet_params_from_torch` under the name the runbooks use."""
    return resnet_params_from_torch(state_dict, cfg)


def llama_params_from_torch(state_dict, cfg, *, rms_norm_eps=None) -> dict:
    """HF ``LlamaForCausalLM.state_dict()`` → ``{"params": ...}`` for
    models/llama.Llama built with ``llama_config(...)``.

    ``rms_norm_eps``: the source checkpoint's ``LlamaConfig.rms_norm_eps``.
    Pass it whenever the HF config is at hand — epsilon lives in the config,
    not the state_dict, so a mismatch cannot be detected from weights alone:
    our preset pins ``norm_eps=1e-5`` (Llama-2/3), but Llama-1 checkpoints
    and HF's ``LlamaConfig`` default use 1e-6, and importing one of those
    under the preset would silently run every RMSNorm with the wrong
    epsilon. A mismatch with ``cfg.norm_eps`` raises; fix it with
    ``llama_config(..., norm_eps=<checkpoint eps>)``."""
    if rms_norm_eps is not None and rms_norm_eps != cfg.norm_eps:
        raise ValueError(
            f"checkpoint rms_norm_eps={rms_norm_eps} != cfg.norm_eps="
            f"{cfg.norm_eps}; build the config with "
            f"llama_config(..., norm_eps={rms_norm_eps})")
    if cfg.tie_embeddings:
        raise ValueError(
            "Llama import expects tie_embeddings=False (the released "
            "models carry a separate lm_head; a tied config would "
            "silently drop it)")
    sd = {k.removeprefix("model."): v for k, v in state_dict.items()}

    def lin(key):
        return _lin(sd, key)

    def block(i):
        p = f"layers.{i}."
        q = lin(p + "self_attn.q_proj.weight")   # [E, H·D]
        k = lin(p + "self_attn.k_proj.weight")   # [E, KV·D]
        v = lin(p + "self_attn.v_proj.weight")
        if cfg.kv_heads == cfg.num_heads:
            # MHA sizes (7b/13b): SelfAttention uses the single fused
            # [E, 3, H·D] qkv stack, not the GQA q+kv split
            attn = {"qkv_kernel": np.stack([q, k, v], axis=1)}
        else:
            attn = {"q_kernel": q, "kv_kernel": np.stack([k, v], axis=1)}
        attn["out"] = {"kernel": lin(p + "self_attn.o_proj.weight")}
        gate = lin(p + "mlp.gate_proj.weight")   # [E, F]
        up = lin(p + "mlp.up_proj.weight")
        return {
            "ln1": {"scale": _np(sd[p + "input_layernorm.weight"])},
            "ln2": {"scale":
                    _np(sd[p + "post_attention_layernorm.weight"])},
            "attn": attn,
            "mlp": {
                "wi_kernel": np.stack([gate, up], axis=1),  # 0=gate 1=up
                "wo": {"kernel": lin(p + "mlp.down_proj.weight")},
            },
        }

    return _finish({"params": {
        "embed": {"tok": {"embedding": _np(sd["embed_tokens.weight"])}},
        "h": _stack_blocks([block(i) for i in range(cfg.num_layers)],
                           cfg.scan_layers),
        "ln_f": {"scale": _np(sd["norm.weight"])},
        "lm_head": {"kernel": _np(state_dict["lm_head.weight"]).T},
    }}, cfg)
