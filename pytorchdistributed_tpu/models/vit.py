"""ViT — Vision Transformer (BASELINE config[4]: "ViT-L/16 multi-host DP
across pod slices").

Patchify via a strided Conv (one big matmul for the MXU, NHWC layout),
prepend a CLS token, run the shared bidirectional TransformerStack, classify
from the CLS representation. The patchify front-end is its own module
(`PatchEmbed`) so the 1F1B pipeline decomposition can apply it as the
pre-stage, mirroring GPT-2/Llama/BERT's ``pipeline_parts`` shape.
"""

from __future__ import annotations

import dataclasses

import flax.linen as nn
import jax.numpy as jnp
import optax

from pytorchdistributed_tpu.models.transformer import (
    TransformerConfig,
    TransformerStack,
    _layer_norm,
    check_pipeline_decomposition,
    make_stage_apply,
    stack_to_stages,
    stages_to_stack,
)
from pytorchdistributed_tpu.parallel.tp import Logical


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    transformer: TransformerConfig
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


class PatchEmbed(nn.Module):
    """images [B, H, W, C] → tokens [B, num_patches+1, embed]: strided-conv
    patchify + CLS token + learned positions (everything before block 0)."""

    cfg: ViTConfig

    @nn.compact
    def __call__(self, images):
        cfg, tcfg = self.cfg, self.cfg.transformer
        p = cfg.patch_size
        x = nn.Conv(
            tcfg.embed_dim, (p, p), strides=(p, p), padding="VALID",
            dtype=tcfg.dtype, param_dtype=tcfg.param_dtype,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.lecun_normal(),
                (None, None, Logical.CONV_IN, Logical.EMBED)),
            bias_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), (Logical.EMBED,)),
            name="patch_embed",
        )(images.astype(tcfg.dtype))
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)

        cls = self.param(
            "cls",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init(), (None, None, Logical.EMBED)),
            (1, 1, tcfg.embed_dim), tcfg.param_dtype,
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (b, 1, c)).astype(tcfg.dtype), x], axis=1)
        pos = self.param(
            "pos_embed",
            nn.with_logical_partitioning(
                nn.initializers.normal(stddev=0.02), (None, Logical.EMBED)),
            (cfg.num_patches + 1, tcfg.embed_dim), tcfg.param_dtype,
        )
        return x + pos[None].astype(tcfg.dtype)


def _head_dense(cfg: ViTConfig):
    return nn.Dense(
        cfg.num_classes, dtype=jnp.float32,
        param_dtype=cfg.transformer.param_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.zeros_init(), (Logical.EMBED, None)),
        name="head",
    )


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images, *, deterministic: bool = True):
        cfg, tcfg = self.cfg, self.cfg.transformer
        x = PatchEmbed(cfg, name="embed")(images)
        x = TransformerStack(tcfg, name="encoder")(
            x, deterministic=deterministic)
        x = _layer_norm(tcfg, "ln_f")(x)
        return _head_dense(cfg)(x[:, 0])

    @nn.nowrap
    def pipeline_parts(self):
        """1F1B decomposition (see GPT2.pipeline_parts): pre = PatchEmbed,
        stages = encoder layer groups, head = ln_f + CLS classifier + CE
        over integer labels (``targets_of`` reads batch["label"] — the
        image-classification batch contract)."""
        from pytorchdistributed_tpu.parallel.pipeline import PipelineParts

        cfg, tcfg = self.cfg, self.cfg.transformer
        check_pipeline_decomposition(tcfg)

        def split(params):
            pp = params["params"]
            stage = stack_to_stages(pp["encoder"]["block"], tcfg)
            head = {"ln_f": pp["ln_f"], "head": pp["head"]}
            return pp["embed"], stage, head

        def pre_apply(pre, images):
            return PatchEmbed(cfg).apply({"params": pre}, images)

        def targets_of(batch):
            return batch["label"]

        def head_loss(head, h, labels):
            x = _layer_norm(tcfg, None).apply({"params": head["ln_f"]}, h)
            logits = _head_dense(cfg).apply({"params": head["head"]},
                                            x[:, 0])
            return optax.softmax_cross_entropy_with_integer_labels(
                logits.astype(jnp.float32), labels).mean()

        def merge_grads(pre_g, stage_g, head_g):
            blocks = stages_to_stack(stage_g, tcfg)
            return {"params": {
                "embed": pre_g, "encoder": {"block": blocks},
                "ln_f": head_g["ln_f"], "head": head_g["head"],
            }}

        return PipelineParts(
            split, pre_apply, make_stage_apply(tcfg), head_loss,
            merge_grads, targets_of,
            stage_apply_aux=(make_stage_apply(tcfg, aux=True)
                             if tcfg.moe_experts > 0 else None))


def vit_config(size: str = "base", *, image_size: int = 224,
               patch_size: int = 16, num_classes: int = 1000,
               **overrides) -> ViTConfig:
    presets = {
        "test": dict(num_layers=2, embed_dim=64, num_heads=4),
        "base": dict(num_layers=12, embed_dim=768, num_heads=12),
        "large": dict(num_layers=24, embed_dim=1024, num_heads=16,
                      mlp_dim=4096),
        "huge": dict(num_layers=32, embed_dim=1280, num_heads=16,
                     mlp_dim=5120),
    }
    # Released-ViT fidelity (torch_import): exact erf GELU, eps 1e-12
    # (pre-LN is ViT's native order already).
    kw = dict(vocab_size=1, causal=False,
              max_seq_len=(image_size // patch_size) ** 2 + 1,
              norm_eps=1e-12, gelu_approximate=False)
    kw.update(presets[size])
    kw.update(overrides)
    return ViTConfig(
        transformer=TransformerConfig(**kw),
        image_size=image_size, patch_size=patch_size,
        num_classes=num_classes,
    )
