"""ResNet family — ResNet-18 (BASELINE config[0] CIFAR smoke) and ResNet-50
(config[1] ImageNet DDP; the model the reference's model/pipeline-parallel
lesson splits across GPUs, reference 03_model_parallel.ipynb:325-349).

TPU-first choices: NHWC layout (XLA:TPU's native conv layout), bf16 compute
with fp32 normalization statistics, and **sync batch norm**: in training
the norm uses the current global batch's statistics — because the batch is
sharded inside jit, the `jnp.mean` over the batch axis lowers to a
cross-chip psum, torch's SyncBatchNorm wrapper with zero framework code.
An EMA of those statistics rides the flax "batch_stats" collection
(updated in the train step, carried in TrainState, checkpointed) and is
what `deterministic=True` (eval / serving) normalizes with — so eval
output is independent of the eval batch composition and batch-1 inference
is meaningful.

Stages are named so the pipeline partitioner (parallel/pipeline.py) can cut
the network at stage boundaries, mirroring the reference's two-stage manual
split (seq1=conv1..layer2 / seq2=layer3..fc, 03_model_parallel.ipynb:336-344).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorchdistributed_tpu.parallel.tp import Logical


def _conv(features, kernel, strides, cfg, name):
    # torch_padding: torchvision's explicit symmetric (k-1)//2 per side.
    # Identical to XLA SAME at stride 1 (odd kernels), but stride-2 convs
    # under SAME pad one less on the low edge (stem 7x7: (2,3) vs torch's
    # (3,3); block 3x3: (0,1) vs (1,1)) — same output shape, shifted
    # receptive fields, so torch-imported weights only reproduce torch
    # activations under the torch rule (see torch_import).
    padding = (tuple(((k - 1) // 2,) * 2 for k in kernel)
               if cfg.torch_padding else "SAME")
    return nn.Conv(
        features, kernel, strides=strides, padding=padding, use_bias=False,
        dtype=cfg.dtype, param_dtype=jnp.float32,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.he_normal(),
            (None, None, Logical.CONV_IN, Logical.CONV_OUT)),
        name=name,
    )


class SyncBatchNorm(nn.Module):
    """Training (``use_running_average=False``): normalize by the *global*
    batch statistics (fp32) — with the batch sharded over data axes, XLA
    turns the means into psums, the TPU-native SyncBatchNorm. The raw
    batch statistics are published through the mutable "batch_stats"
    collection (no second pass: the very reductions used to normalize);
    the TRAINER folds them into the running EMA in one tree-level pass
    (training/trainer.py BN_EMA_MOMENTUM) — torch's buffer semantics,
    where running stats are state, not per-module parameter updates.
    Eval: normalize by the EMA."""

    epsilon: float = 1e-5
    zero_init_scale: bool = False
    use_running_average: bool = True

    @nn.compact
    def __call__(self, x):
        c = x.shape[-1]
        xf = x.astype(jnp.float32)
        ema_mean = self.variable("batch_stats", "mean",
                                 lambda: jnp.zeros((c,), jnp.float32))
        ema_var = self.variable("batch_stats", "var",
                                lambda: jnp.ones((c,), jnp.float32))
        if self.use_running_average:
            mean, var = ema_mean.value, ema_var.value
        else:
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(xf, axis=axes)
            var = jnp.mean(jnp.square(xf), axis=axes) - jnp.square(mean)
            if (not self.is_initializing()
                    and self.is_mutable_collection("batch_stats")):
                # raw stats out; the EMA fold is the Trainer's (one pass
                # over the whole tree instead of 2 tiny ops x 100+ layers)
                ema_mean.value = mean
                ema_var.value = var
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init() if self.zero_init_scale
                else nn.initializers.ones_init(),
                (Logical.CONV_OUT,)),
            (c,), jnp.float32)
        bias = self.param(
            "bias",
            nn.with_logical_partitioning(
                nn.initializers.zeros_init(), (Logical.CONV_OUT,)),
            (c,), jnp.float32)
        y = (xf - mean) * jax.lax.rsqrt(var + self.epsilon)
        return (y * scale + bias).astype(x.dtype)


def _bn(cfg, name, *, deterministic: bool, zero_init_scale: bool = False):
    return SyncBatchNorm(zero_init_scale=zero_init_scale,
                         use_running_average=deterministic, name=name)


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)
    bottleneck: bool = True
    num_classes: int = 1000
    width: int = 64
    dtype: object = jnp.bfloat16
    # CIFAR stem: 3x3 conv, no max-pool (for 32x32 inputs).
    cifar_stem: bool = False
    # Pad stride-2 convs and the stem max-pool the way torch does
    # (symmetric explicit) instead of XLA SAME. Required for exact parity
    # with torchvision-trained weights (torch_import.py); default stays
    # SAME — the committed bench configs were measured on it.
    torch_padding: bool = False


class BasicBlock(nn.Module):
    cfg: ResNetConfig
    features: int
    strides: int = 1
    deterministic: bool = True

    @nn.compact
    def __call__(self, x):
        cfg, det = self.cfg, self.deterministic
        r = _conv(self.features, (3, 3), (self.strides,) * 2, cfg, "conv1")(x)
        r = nn.relu(_bn(cfg, "bn1", deterministic=det)(r))
        r = _conv(self.features, (3, 3), (1, 1), cfg, "conv2")(r)
        # zero-init the last BN scale: each residual branch starts as identity
        r = _bn(cfg, "bn2", deterministic=det, zero_init_scale=True)(r)
        if x.shape != r.shape:
            x = _conv(self.features, (1, 1), (self.strides,) * 2, cfg,
                      "down_conv")(x)
            x = _bn(cfg, "down_bn", deterministic=det)(x)
        return nn.relu(x + r)


class BottleneckBlock(nn.Module):
    cfg: ResNetConfig
    features: int
    strides: int = 1
    deterministic: bool = True

    @nn.compact
    def __call__(self, x):
        cfg, det = self.cfg, self.deterministic
        r = _conv(self.features, (1, 1), (1, 1), cfg, "conv1")(x)
        r = nn.relu(_bn(cfg, "bn1", deterministic=det)(r))
        r = _conv(self.features, (3, 3), (self.strides,) * 2, cfg, "conv2")(r)
        r = nn.relu(_bn(cfg, "bn2", deterministic=det)(r))
        r = _conv(self.features * 4, (1, 1), (1, 1), cfg, "conv3")(r)
        r = _bn(cfg, "bn3", deterministic=det, zero_init_scale=True)(r)
        if x.shape != r.shape:
            x = _conv(self.features * 4, (1, 1), (self.strides,) * 2, cfg,
                      "down_conv")(x)
            x = _bn(cfg, "down_bn", deterministic=det)(x)
        return nn.relu(x + r)


class ResNet(nn.Module):
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        cfg = self.cfg
        det = deterministic
        x = x.astype(cfg.dtype)
        if cfg.cifar_stem:
            x = _conv(cfg.width, (3, 3), (1, 1), cfg, "stem_conv")(x)
            x = nn.relu(_bn(cfg, "stem_bn", deterministic=det)(x))
        else:
            x = _conv(cfg.width, (7, 7), (2, 2), cfg, "stem_conv")(x)
            x = nn.relu(_bn(cfg, "stem_bn", deterministic=det)(x))
            x = nn.max_pool(x, (3, 3), strides=(2, 2),
                            padding=(((1, 1), (1, 1)) if cfg.torch_padding
                                     else "SAME"))

        block = BottleneckBlock if cfg.bottleneck else BasicBlock
        for stage, n_blocks in enumerate(cfg.stage_sizes):
            for b in range(n_blocks):
                x = block(
                    cfg,
                    features=cfg.width * 2**stage,
                    strides=2 if b == 0 and stage > 0 else 1,
                    deterministic=det,
                    name=f"stage{stage + 1}_block{b}",
                )(x)

        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)  # global avg pool
        return nn.Dense(
            cfg.num_classes, dtype=jnp.float32, param_dtype=jnp.float32,
            kernel_init=nn.with_logical_partitioning(
                nn.initializers.zeros_init(), (Logical.EMBED, None)),
            name="fc",
        )(x)


def resnet18(num_classes: int = 1000, *, cifar_stem: bool = False,
             **kw) -> ResNet:
    return ResNet(ResNetConfig(stage_sizes=(2, 2, 2, 2), bottleneck=False,
                               num_classes=num_classes,
                               cifar_stem=cifar_stem, **kw))


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet(ResNetConfig(stage_sizes=(3, 4, 6, 3), bottleneck=True,
                               num_classes=num_classes, **kw))
