"""Toy models matching the reference's demos.

`LinearRegression` is the reference's training model `nn.Linear(20, 1)`
(reference ddp_gpus.py:78); `MLP` is the 4-layer demo net from the
DataParallel lesson (reference 01_multi_gpus_data_parallelism.ipynb cell 5).
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn


class LinearRegression(nn.Module):
    out_dim: int = 1

    @nn.compact
    def __call__(self, x):
        return nn.Dense(self.out_dim)(x)


class MLP(nn.Module):
    """``dot_general``: optional injectable contraction for every Dense —
    pass ``Policy.int8_fwd().dot_general()`` (parallel/precision.py) to run
    the weight matmuls int8-quantized; None = ``lax.dot_general``."""

    features: Sequence[int] = (128, 256, 128, 10)
    dot_general: Any = None

    @nn.compact
    def __call__(self, x):
        for f in self.features[:-1]:
            x = nn.relu(nn.Dense(f, dot_general=self.dot_general)(x))
        return nn.Dense(self.features[-1], dot_general=self.dot_general)(x)
