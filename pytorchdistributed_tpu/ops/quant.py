"""Int8 quantized-training matmuls — AQT-style dynamic per-channel scaling.

The bf16 MFU plateau (BASELINE.md r5: llama-1B 60.5-62.0%, gpt2-medium
53.8% after five rounds of kernel-shape and remat-policy A/Bs) is a
*arithmetic-rate* ceiling, not a schedule one: every remaining knob was
measured and rejected as noise. The next step-function changes the
arithmetic itself — TPU v5e's MXU executes int8×int8→int32 at ~2× its
bf16 rate, and the AQT line of work (Abdolrashidi et al.,
"Pareto-Optimal Quantized ResNet Is Mostly 4-bit") plus the INT8/FP8
training-format results (Micikevicius et al., "FP8 Formats for Deep
Learning") show dynamic per-channel absmax scaling preserves convergence
for weight-matmul-dominated training.

The primitive here is ``quantized_dot_general(mode)`` — a drop-in for
``jax.lax.dot_general`` (same signature, so it injects straight into
``flax.linen.Dense(dot_general=...)`` and ``jnp.einsum(_dot_general=...)``)
that per call:

  1. computes a dynamic **per-channel absmax scale** for each operand —
     the absmax over the contraction dims, kept per remaining channel
     (per activation row, per weight column), so one outlier row cannot
     flatten the whole tensor's resolution;
  2. rounds each operand to int8 on that scale and contracts in
     int8×int8→**int32** (exact integer accumulation — on the MXU this is
     the ~2× rate path; on CPU/older chips it is a correct reference);
  3. rescales the int32 result by the outer product of the two scale
     vectors in fp32 and casts to the caller's result dtype.

Backward (``jax.custom_vjp``, residuals = the unquantized bf16 operands —
same memory as bf16 training):

  * ``mode="int8_fwd"`` (the safe default): backward runs as ordinary
    bf16/fp32 ``dot_general`` VJPs. Forward-only quantization is the
    convergence-conservative recipe — gradients see the quantized loss
    surface but are themselves full precision.
  * ``mode="int8"``: both backward contractions (dL/dx = g·Wᵀ and
    dL/dW = xᵀ·g) also run in int8, with **stochastic rounding on the
    gradient operand**. Round-to-nearest on gradients biases the many
    near-zero entries to exactly zero and stalls training; stochastic
    rounding is unbiased (E[q] = x), the standard int8-backward fix.

Stochastic rounding noise: there is no PRNG stream threaded through the
model's matmul call sites, so the uniform noise is derived from the
gradient's own fp32 bit pattern through a murmur3-style avalanche
finalizer. The mixer decorrelates the noise from the value's fractional
part (tested: rounding is unbiased to <1e-3 over dense value sweeps), and
because gradients change every step the noise decorrelates across steps —
the property plain round-to-nearest lacks.

Sharding: everything here is plain HLO (abs/max/divide/round/convert/dot),
so the SPMD partitioner shards it like the bf16 matmul it replaces —
logical-axis annotations on the params and activations are untouched, TP's
column/row splits still apply to the int8 operands, and a contraction over
a tensor-sharded dim turns the absmax into a (cheap, correct) cross-shard
max. The compiled-invariant suite pins the resulting int8 convert/dot mix
(tests/test_compiled_invariants.py "int8_ops").

Scope: contractions with batch dimensions (the MoE expert-batched einsums)
are not supported — the weight matmuls this subsystem targets (QKV/out,
MLP, LM head, fused-CE logits) have none. ``NotImplementedError`` fires
rather than silently falling back.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

MODES = ("int8_fwd", "int8")

_QMAX = 127.0  # symmetric int8: codes -127..127 (the -128 code is unused,
#                keeping the scale exactly absmax/127 and negation exact)


class _QuantSpec(NamedTuple):
    """Static config threaded through custom_vjp as a nondiff arg."""

    mode: str                 # "int8_fwd" | "int8"
    preferred: np.dtype | None  # caller's preferred_element_type


# ---------------------------------------------------------------------------
# quantize / dequantize
# ---------------------------------------------------------------------------


def absmax_scale(x, contract_dims):
    """Per-channel scale [x.shape with contract dims = 1], fp32: absmax
    over the contraction dims / 127, so the channel's largest magnitude
    maps to the last int8 code. All-zero channels get scale 1 (their
    quantized values are 0 regardless; 1 avoids the 0/0)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=contract_dims,
                   keepdims=True)
    return jnp.where(amax > 0, amax, jnp.float32(1.0)) / jnp.float32(_QMAX)


def quantize(x, scale):
    """Round-to-nearest int8 on ``scale`` (forward-path rounding)."""
    q = jnp.round(x.astype(jnp.float32) / scale)
    return jnp.clip(q, -_QMAX, _QMAX).astype(jnp.int8)


def _hash_uniform(y):
    """Uniform [0, 1) noise derived from ``y``'s own fp32 bits via the
    murmur3 avalanche finalizer. The mixer's output is decorrelated from
    the input's low-order (fractional) bits — the property stochastic
    rounding needs — and, unlike a fixed PRNG key, the noise pattern
    changes whenever the values do (every training step)."""
    bits = lax.bitcast_convert_type(y.astype(jnp.float32), jnp.uint32)
    h = bits ^ (bits >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    # top-ish 24 bits -> [0, 1): fp32 represents k/2^24 exactly
    return (h >> np.uint32(8)).astype(jnp.float32) / jnp.float32(1 << 24)


def stochastic_quantize(x, scale):
    """Stochastically-rounded int8: floor(y + u), u ~ U[0,1) — unbiased
    (E[q·scale] = x), the gradient-operand rounding for mode="int8"."""
    y = x.astype(jnp.float32) / scale
    q = jnp.floor(y + _hash_uniform(y))
    return jnp.clip(q, -_QMAX, _QMAX).astype(jnp.int8)


# ---------------------------------------------------------------------------
# the int8 contraction (shared by forward and the quantized backward)
# ---------------------------------------------------------------------------


def _int8_dot_value(lhs, rhs, dims, *, sr_lhs=False, sr_rhs=False):
    """fp32 value of an int8-quantized dot_general (no batch dims):
    per-channel scales, int8 operands, int32 accumulation, fp32 rescale.
    ``sr_*`` selects stochastic rounding for that operand (the gradient
    in the quantized backward)."""
    (lc, rc), _ = dims
    ls = absmax_scale(lhs, lc)
    rs = absmax_scale(rhs, rc)
    ql = (stochastic_quantize if sr_lhs else quantize)(lhs, ls)
    qr = (stochastic_quantize if sr_rhs else quantize)(rhs, rs)
    out = lax.dot_general(ql, qr, dims, preferred_element_type=jnp.int32)
    # rescale: dot_general output is [lhs_free..., rhs_free...]; line the
    # squeezed per-channel scales up with trailing/leading broadcast 1s
    nrf = rhs.ndim - len(rc)
    ls_o = jnp.squeeze(ls, axis=lc)
    ls_o = ls_o.reshape(ls_o.shape + (1,) * nrf)
    rs_o = jnp.squeeze(rs, axis=rc)
    return out.astype(jnp.float32) * ls_o * rs_o


def _grad_dims(lhs_ndim, rhs_ndim, dims):
    """dot_general dims + output-transpose permutations for the two VJP
    contractions of a batch-free dot: dlhs = dot(g, rhs) over rhs's free
    dims, drhs = dot(lhs, g) over lhs's free dims. The cotangent g has
    layout [lhs_free..., rhs_free...]."""
    (lc, rc), _ = dims
    lf = [d for d in range(lhs_ndim) if d not in lc]
    rf = [d for d in range(rhs_ndim) if d not in rc]
    nlf = len(lf)
    # dlhs: contract g's trailing (rhs-free) dims with rhs's free dims;
    # result is [lf..., sorted(rc)...] — map each rhs contract dim back to
    # its paired lhs dim and permute into lhs's layout
    dl_dims = ((tuple(range(nlf, nlf + len(rf))), tuple(rf)), ((), ()))
    dl_axes = lf + [lc[rc.index(d)] for d in sorted(rc)]
    dl_perm = tuple(dl_axes.index(a) for a in range(lhs_ndim))
    # drhs: contract lhs's free dims with g's leading (lhs-free) dims;
    # result is [sorted(lc)..., rf...]
    dr_dims = ((tuple(lf), tuple(range(nlf))), ((), ()))
    dr_axes = [rc[lc.index(d)] for d in sorted(lc)] + rf
    dr_perm = tuple(dr_axes.index(a) for a in range(rhs_ndim))
    return (dl_dims, dl_perm), (dr_dims, dr_perm)


# ---------------------------------------------------------------------------
# custom_vjp core
# ---------------------------------------------------------------------------


def _result_dtype(lhs, rhs, spec: _QuantSpec):
    if spec.preferred is not None:
        return spec.preferred
    return jnp.promote_types(lhs.dtype, rhs.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _quant_dot(lhs, rhs, dims, spec: _QuantSpec):
    return _int8_dot_value(lhs, rhs, dims).astype(
        _result_dtype(lhs, rhs, spec))


def _quant_dot_fwd(lhs, rhs, dims, spec: _QuantSpec):
    return _quant_dot(lhs, rhs, dims, spec), (lhs, rhs)


def _quant_dot_bwd(dims, spec: _QuantSpec, res, g):
    lhs, rhs = res
    if spec.mode == "int8_fwd":
        # safe default: the backward is the ordinary full-precision VJP of
        # the reference dot on the saved (unquantized) operands
        def ref(l, r):
            return lax.dot_general(l, r, dims,
                                   preferred_element_type=spec.preferred)

        _, vjp = jax.vjp(ref, lhs, rhs)
        return tuple(vjp(g))
    # mode="int8": both grad contractions quantized, stochastic rounding
    # on the gradient operand (unbiased), round-to-nearest on the saved
    # forward operands
    (dl_dims, dl_perm), (dr_dims, dr_perm) = _grad_dims(
        lhs.ndim, rhs.ndim, dims)
    dl = jnp.transpose(
        _int8_dot_value(g, rhs, dl_dims, sr_lhs=True), dl_perm)
    dr = jnp.transpose(
        _int8_dot_value(lhs, g, dr_dims, sr_rhs=True), dr_perm)
    return dl.astype(lhs.dtype), dr.astype(rhs.dtype)


_quant_dot.defvjp(_quant_dot_fwd, _quant_dot_bwd)


# ---------------------------------------------------------------------------
# the injectable
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def quantized_dot_general(mode: str):
    """The ``lax.dot_general`` drop-in for ``mode`` ("int8_fwd" | "int8").

    Cached per mode so every call site shares ONE callable — flax module
    attributes and jit caches key on identity. ``precision`` is accepted
    and ignored (the int8 path has exactly one precision);
    ``preferred_element_type`` selects the result dtype like the real
    dot_general's."""
    if mode not in MODES:
        raise ValueError(f"unknown quantization mode {mode!r}; "
                         f"one of {MODES} (or 'none' upstream)")

    def dot_general(lhs, rhs, dimension_numbers, precision=None,
                    preferred_element_type=None):
        del precision
        (lc, rc), (lb, rb) = dimension_numbers
        dims = ((tuple(map(int, lc)), tuple(map(int, rc))),
                (tuple(map(int, lb)), tuple(map(int, rb))))
        if dims[1] != ((), ()):
            raise NotImplementedError(
                "quantized_dot_general supports contractions without batch "
                "dimensions (the weight-matmul shapes); got batch dims "
                f"{dims[1]}")
        pref = (None if preferred_element_type is None
                else np.dtype(preferred_element_type))
        return _quant_dot(lhs, rhs, dims, _QuantSpec(mode, pref))

    dot_general.__name__ = f"int8_dot_general_{mode}"
    dot_general.__qualname__ = dot_general.__name__
    return dot_general


# ---------------------------------------------------------------------------
# KV-cache block quantization (serving, ISSUE 13)
# ---------------------------------------------------------------------------
#
# The paged KV pool stores int8 codes plus an fp32 scale per written
# (token, head) row — absmax over head_dim / 127, the same symmetric
# recipe as the matmul path above. Per-row granularity (rather than
# per-block) means an incremental decode write never has to requantize
# neighbours already resident in the block, which is what makes int8
# compose with the engine's one-token-per-tick `.at[blk, off].set`
# write path without read-modify-write of whole blocks.


def kv_quantize(x):
    """Quantize a KV tensor ``[..., head_dim]`` for pool storage.

    Returns ``(codes int8 [...same shape], scale fp32 [...minus last
    dim])`` with scale = absmax over head_dim / 127 per leading row.
    Zero rows get scale 1 (codes are all-zero anyway)."""
    scale = absmax_scale(x, (x.ndim - 1,))
    return quantize(x, scale), jnp.squeeze(scale, axis=x.ndim - 1)


def kv_dequantize(codes, scale, dtype):
    """Invert :func:`kv_quantize`: ``codes int8 [..., head_dim]`` ×
    ``scale fp32 [...]`` → ``dtype``. This exact spelling (int8→fp32,
    multiply, cast) is the canonical dequant all readers — the in-model
    gather path, the reference oracle and the Pallas kernel — must
    match, so the int8 tolerance-twin suites pin one math."""
    return (codes.astype(jnp.float32) * scale[..., None]).astype(dtype)


# ---------------------------------------------------------------------------
# optional quantization stats (telemetry/diagnostics.py — ISSUE 6)
# ---------------------------------------------------------------------------


def saturation_fraction(x, axis=-1) -> jax.Array:
    """Fraction of elements that land on the clip boundary (|q| == 127)
    after per-channel absmax quantization over ``axis`` — the
    "int8 saturation" health stat. With scale = absmax/127 the channel
    maximum quantizes to exactly ±127, so the clean-distribution baseline
    is ≈ 1/channel_size; a rising fraction means the channel's mass is
    piling onto its own absmax (outlier-dominated rows losing
    resolution). OPTIONAL stats output, OFF by default: nothing in the
    forward/backward dot path calls this — only the diagnostics sow
    sites do (models/transformer.py, gated on the "diagnostics"
    collection being mutable) — so the pinned int8 HLO censuses
    (`int8_ops`) of non-diagnostics programs are untouched."""
    axis = axis % max(getattr(x, "ndim", 1), 1)
    scale = absmax_scale(x, (axis,))
    q = quantize(x, scale).astype(jnp.int32)
    return jnp.mean((jnp.abs(q) >= int(_QMAX)).astype(jnp.float32))


def int8_dot_stats(lhs, rhs, dimension_numbers) -> dict[str, jax.Array]:
    """Saturation fractions of both operands of a quantized contraction,
    computed exactly as ``quantized_dot_general`` would quantize them
    (same per-channel absmax scales, round-to-nearest). A standalone
    probe for A/B'ing a matmul site's int8 health outside the model —
    the in-model path sows `saturation_fraction` of the block input
    instead (one number per layer, the diagnostics table shape)."""
    (lc, rc), (lb, rb) = dimension_numbers
    if (tuple(lb), tuple(rb)) != ((), ()):
        raise NotImplementedError(
            "int8_dot_stats mirrors quantized_dot_general: contractions "
            f"without batch dimensions only (got batch dims {(lb, rb)})")
    ls = absmax_scale(lhs, tuple(lc))
    rs = absmax_scale(rhs, tuple(rc))
    ql = quantize(lhs, ls).astype(jnp.int32)
    qr = quantize(rhs, rs).astype(jnp.int32)
    lim = int(_QMAX)
    return {
        "lhs_sat_frac": jnp.mean((jnp.abs(ql) >= lim).astype(jnp.float32)),
        "rhs_sat_frac": jnp.mean((jnp.abs(qr) >= lim).astype(jnp.float32)),
    }


def dot_general_for(quant: str):
    """Config-level selector: ``None`` for "none" (callers fall back to
    ``lax.dot_general``), else the shared injectable for the mode. The one
    place the model zoo, the fused-CE head and the precision Policy all go
    through, so flag wiring stays in lockstep."""
    if quant in (None, "none"):
        return None
    return quantized_dot_general(quant)
