"""Named collective wrappers — the framework's L0 (SURVEY.md §1).

The reference's L0 is NCCL, reached through `backend="nccl"` (reference
ddp_gpus.py:22) with ring-allreduce = scatter-reduce + all-gather explained at
02_ddp.ipynb:33-47. On TPU there is NO userspace collective library: these are
XLA HLO ops executed by the runtime over the ICI torus (intra-slice) or DCN
(cross-slice), already implemented as the hardware-optimal ring/torus
algorithms. These wrappers exist so schedules and tests can name the
operation they mean; inside `jit` + sharding, XLA usually inserts them
automatically, which is the TPU answer to DDP's bucketed Reducer — a
claim that is now FORCED and MEASURED rather than assumed: the Trainer
wires XLA's latency-hiding scheduler flags, ops/overlap.py decomposes
the TP matmul collectives into ppermute rings, and
utils/hlo.overlap_census counts the async start/done pairs and the ops
scheduled inside them (ISSUE 5).

All functions must run inside `shard_map`/`pmap`-style contexts where the
named axis is bound.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def all_reduce_sum(x, axis_name: str):
    """NCCL allreduce(sum) ≙ `lax.psum` (ring-allreduce, 02_ddp.ipynb:33-47)."""
    return lax.psum(x, axis_name)


def all_reduce_mean(x, axis_name: str):
    """DDP's gradient averaging: allreduce(sum) / world_size."""
    return lax.pmean(x, axis_name)


def all_gather(x, axis_name: str, *, axis: int = 0, tiled: bool = True):
    """NCCL allgather: concatenate shards along ``axis`` on every member."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, *, axis: int = 0):
    """NCCL reduce-scatter: sum then keep this member's shard (the first
    half of ring-allreduce, 02_ddp.ipynb:33-40; FSDP's gradient op)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def broadcast_from(x, axis_name: str, *, root: int = 0):
    """NCCL broadcast: everyone takes ``root``'s value (DDP ctor's
    rank0→all param sync, reference ddp_gpus.py:35)."""
    idx = lax.axis_index(axis_name)
    size = lax.axis_size(axis_name)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis_name) if size > 1 else x


def ring_schedule(axis_size: int, shift: int = 1) -> list[tuple[int, int]]:
    """The (source, destination) permutation of a ring rotation: member i
    sends to i+shift (mod n), i.e. everyone *receives* from i-shift. One
    definition shared by `ppermute_ring`, ring attention's K/V rotation
    and the decomposed collective matmuls (ops/overlap.py), so every ring
    in the codebase agrees on hop direction — a ring whose send direction
    silently disagreed with the index arithmetic `(my - step) % n` would
    compute with the wrong shard and no shape error would catch it."""
    if axis_size < 1:
        raise ValueError(f"ring_schedule needs axis_size >= 1, "
                         f"got {axis_size}")
    if shift % axis_size == 0:
        # a zero-shift "rotation" is the identity; emitting it as a
        # ppermute would still pay a collective for a no-op
        return [(i, i) for i in range(axis_size)]
    return [(i, (i + shift) % axis_size) for i in range(axis_size)]


def ppermute_ring(x, axis_name: str, *, shift: int = 1):
    """Rotate shards around the ring: member i receives from i-shift.
    The building block of ring attention (SURVEY.md §5), the decomposed
    collective matmuls (ops/overlap.py) and pipelined stage-boundary
    transfer."""
    n = lax.axis_size(axis_name)
    return lax.ppermute(x, axis_name, ring_schedule(n, shift))


def all_to_all(x, axis_name: str, *, split_axis: int, concat_axis: int):
    """NCCL alltoall: re-shard which dimension is split across the axis
    (Ulysses-style head↔sequence redistribution). Axis bounds are
    validated here: an out-of-range split/concat axis otherwise surfaces
    as an XLA lowering crash deep inside the partitioner, with no hint of
    which call site passed the bad dimension."""
    ndim = jnp.ndim(x)
    for name, ax in (("split_axis", split_axis),
                     ("concat_axis", concat_axis)):
        if not isinstance(ax, int) or not 0 <= ax < ndim:
            raise ValueError(
                f"all_to_all {name}={ax!r} out of range for a rank-{ndim} "
                f"operand (valid axes: 0..{ndim - 1})")
    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
        tiled=True,
    )


def expert_dispatch(slots, axis_name: str):
    """MoE dispatch all_to_all (GShard's first exchange): slot tensors
    ``[groups_local, experts, capacity, ...]`` are re-sharded so every
    member of the expert axis holds ALL groups' slots for ITS experts —
    ``[groups_local·ep, experts/ep, capacity, ...]``. Tree-mapped so a
    pre-quantized ``(int8 codes, fp32 scales)`` payload ships as one
    logical exchange (the same composition the gather ring uses for its
    ppermute hops). ``expert_combine`` is the exact transpose."""
    return jax.tree.map(
        lambda t: lax.all_to_all(t, axis_name, split_axis=1, concat_axis=0,
                                 tiled=True),
        slots)


def expert_combine(slots, axis_name: str):
    """MoE combine all_to_all: the transpose of `expert_dispatch` — expert
    outputs ``[groups_local·ep, experts/ep, capacity, ...]`` return to the
    group-sharded layout ``[groups_local, experts, capacity, ...]``."""
    return jax.tree.map(
        lambda t: lax.all_to_all(t, axis_name, split_axis=0, concat_axis=1,
                                 tiled=True),
        slots)


def axis_index(axis_name: str):
    return lax.axis_index(axis_name)


def axis_size(axis_name: str) -> int:
    return lax.axis_size(axis_name)
