from pytorchdistributed_tpu.ops.collectives import (  # noqa: F401
    all_gather,
    all_reduce_mean,
    all_reduce_sum,
    all_to_all,
    broadcast_from,
    ppermute_ring,
    reduce_scatter,
    ring_schedule,
)
from pytorchdistributed_tpu.ops.overlap import (  # noqa: F401
    ring_column_matmul,
    ring_row_matmul,
)
from pytorchdistributed_tpu.ops.quant import (  # noqa: F401
    dot_general_for,
    quantized_dot_general,
)
