from pytorchdistributed_tpu.ops.collectives import (  # noqa: F401
    all_gather,
    all_reduce_mean,
    all_reduce_sum,
    all_to_all,
    broadcast_from,
    ppermute_ring,
    reduce_scatter,
)
