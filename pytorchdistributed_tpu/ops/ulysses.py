"""Ulysses (DeepSpeed-style) sequence parallelism: all-to-all head↔sequence
redistribution (SURVEY.md §5 "Ulysses-style all-to-all head redistribution as
the alternative when heads ≥ shards").

Inputs arrive sequence-sharded ([B, S/n, H, D] per device). One
`lax.all_to_all` re-shards them head-wise ([B, S, H/n, D]) so each device
runs full-sequence attention for its head subset; a second all-to-all
restores sequence sharding. Two all-to-alls per attention call vs ring's n
ppermutes — cheaper when the head count divides evenly.

The interior is the Pallas flash kernel (ops/pallas_attention.py), NOT
dense attention: each device sees the *full* sequence for its heads, so a
dense interior would materialize the [S, S] score matrix and forfeit the
long-context purpose of sequence parallelism. ``impl="xla"`` keeps the
dense interior as a debugging reference.
"""

from __future__ import annotations

import functools

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from pytorchdistributed_tpu.ops.attention import dense_attention
from pytorchdistributed_tpu.runtime.mesh import Axis


def _ulysses_local(q, k, v, *, axis_name: str, causal: bool,
                   scale: float | None, impl: str, interpret: bool,
                   block_q: int = 1024, block_k: int = 1024):
    n = lax.axis_size(axis_name)
    if q.shape[2] % n != 0 or k.shape[2] % n != 0:
        # k/v may carry fewer heads than q (grouped-query); BOTH counts
        # must split over the shards for the all-to-alls to tile
        raise ValueError(
            f"Ulysses needs q heads ({q.shape[2]}) and kv heads "
            f"({k.shape[2]}) divisible by the seq axis size ({n}); use "
            f"ring attention otherwise")
    # [B, S/n, H, D] -> [B, S, H/n, D]: split heads, gather sequence.
    to_heads = functools.partial(
        lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1,
        tiled=True)
    q, k, v = to_heads(q), to_heads(k), to_heads(v)
    if impl == "pallas":
        from pytorchdistributed_tpu.ops.pallas_attention import (
            flash_attention,
        )

        out = flash_attention(q, k, v, causal=causal, scale=scale,
                              block_q=block_q, block_k=block_k,
                              interpret=interpret)
    else:
        out = dense_attention(q, k, v, causal=causal, scale=scale)
    # [B, S, H/n, D] -> [B, S/n, H, D]
    return lax.all_to_all(out, axis_name=axis_name, split_axis=1,
                          concat_axis=2, tiled=True)


def ulysses_attention(q, k, v, *, causal: bool = False, mesh=None,
                      scale: float | None = None, impl: str = "pallas",
                      block_q: int = 1024, block_k: int = 1024,
                      interpret: bool | None = None,
                      check_vma: bool | None = None):
    """Sequence-parallel attention via head redistribution; same calling
    convention as ring_attention_sharded, including ``check_vma``: None =
    checked whenever the kernels compile for real hardware, opted out
    under Pallas interpret mode (the CPU sim), whose internals
    false-positive the checker — see ring_attention_sharded's docstring.
    The checked compiled path is hardware-verified alongside the ring's
    (tests/test_attention.py::test_ulysses_check_vma_tpu)."""
    if mesh is None:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or not mesh.axis_names:
            raise ValueError(
                "ulysses attention needs a mesh: call under "
                "jax.set_mesh(mesh) or pass mesh=")
    if impl not in ("pallas", "xla"):
        raise ValueError(f"unknown ulysses attention impl {impl!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if check_vma is None:
        check_vma = not interpret
    spec = P((Axis.DATA, Axis.FSDP), Axis.SEQ, Axis.TENSOR, None)
    fn = jax.shard_map(
        functools.partial(_ulysses_local, axis_name=Axis.SEQ, causal=causal,
                          scale=scale, impl=impl, block_q=block_q,
                          block_k=block_k, interpret=interpret),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=check_vma,
    )
    return fn(q, k, v)
