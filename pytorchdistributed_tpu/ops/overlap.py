"""Latency-hiding decomposed collective matmuls (ISSUE 5 tentpole).

The reference's headline DDP mechanism is *overlap*: the bucketed Reducer
starts gradient all-reduce while backward still runs (reference
ddp_gpus.py:35, 02_ddp.ipynb:33-47). On TPU the analog for TP matmuls is
the hand-decomposed **collective matmul** (Wang et al., "Overlapping
Communication with Dependent Computation via Decomposition", ASPLOS'23):
instead of one monolithic all-gather/reduce-scatter that serializes
against the MXU, the collective is unrolled into a ring of `ppermute`
hops interleaved with the matmul chunks that consume/produce each shard —
XLA's scheduler can then issue hop i+1's DMA while chunk i multiplies,
hiding the ICI latency entirely at ICI-bound shapes.

Two primitives, transposes of each other:

  * ``ring_column_matmul(x, w)`` — the **all-gather→matmul** ring for a
    column-parallel projection (w's trailing feature dim sharded over the
    ring axis). x enters the manual region *seq-split* over the ring axis
    (a free slice — it was replicated there), and each of the n steps
    multiplies the seq-chunk currently held while `ppermute`-ing it to
    the neighbor; after n-1 hops every device has computed the full-seq
    output for its feature shard. Same per-device FLOPs as the monolithic
    matmul; the gather traffic rides the hops, hidden behind the chunks.
  * ``ring_row_matmul(x, w)`` — the **matmul→reduce-scatter** ring for a
    row-parallel projection (x's feature dim and w's contraction dim
    sharded over the ring axis). Each step computes the partial product
    for one seq-chunk and folds it into an accumulator that travels the
    ring; after n-1 hops each device holds its seq-chunk fully reduced.
    This is exactly the reduce-scatter half of the Megatron `g`
    all-reduce, decomposed; the all-gather half is left to the SPMD
    partitioner at the region boundary (where the scheduler-flag wiring,
    trainer._default_compiler_options, makes it async).

The backward pairs each ring with its transpose — d(ag-matmul)/dx is a
matmul→reduce-scatter ring, d(mm-rs)/dx is an all-gather→matmul ring, and
both dw's are a third ring (`_dw_ring_shard`) that rotates the seq-split
operand against the resident one — so the backward hides its collectives
the same way forward does. Like ops/ring_attention.py (the structure
this module deliberately mirrors), the ``custom_vjp`` lives INSIDE the
full-manual `jax.shard_map` region: flax's lifted scan leaks tracers on
this jax vintage when a custom_vjp *wraps* a shard_map, and inside the
region the replicated weight's gradient sum over the batch/seq axes is
handled by shard_map's own transpose (it psums input cotangents over
the axes an in_spec leaves unmentioned — the early-issued gradient
reduce of the ISSUE's part (b)).

Numerics: every chunk contracts with fp32 accumulation
(``preferred_element_type``) and the result is cast once, so the ring is
allclose (1e-5 fp32 / bf16-tolerance) to the monolithic matmul — the
seq-chunking never splits a contraction in the column/dw rings, and the
row ring's fp32 traveling accumulator is at least as accurate as the
bf16 partial-sum all-reduce it replaces (tests/test_overlap.py pins
this).

Int8 composition (ops/quant.py): with ``quant`` set, the column ring
pre-quantizes its traveling operand ONCE (per-row absmax scales over the
contraction dim — identical scales to the monolithic quantized dot,
since the gathered dim is not contracted) and ships the **int8 payload +
fp32 row scales** around the ring — gather traffic ÷4 vs fp32 (÷2 vs
bf16) on top of the overlap. The row/dw rings quantize their resident
operands per chunk via quant's `_int8_dot_value` (their traveling tensor
is a partial-sum accumulator / the already-shipped payload, so nothing
extra moves). ``quant="int8"`` additionally stochastic-rounds the
gradient operand in the backward rings, mirroring the monolithic mode's
semantics (scales there are per-shard rather than cross-shard —
documented, covered by the parity tolerance, not bit-equality);
``int8_fwd`` keeps the backward rings full-precision on the saved
operands, exactly like the monolithic custom_vjp.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from pytorchdistributed_tpu.ops.collectives import (
    expert_combine,
    expert_dispatch,
    ring_schedule,
)
from pytorchdistributed_tpu.ops.quant import (
    _int8_dot_value,
    absmax_scale,
    quantize,
    stochastic_quantize,
)
from pytorchdistributed_tpu.runtime.mesh import Axis

# batch leaves split over the data axes inside the manual region, the
# same layout batch_leaf_sharding gives them outside it
_BATCH = (Axis.DATA, Axis.FSDP)


class _OverlapSpec(NamedTuple):
    """Static ring configuration, threaded through custom_vjp as a
    nondiff arg."""

    axis_name: str              # the ring axis (normally "tensor")
    quant: str | None           # None | "int8_fwd" | "int8"


def _bwd_quant(spec: _OverlapSpec) -> _OverlapSpec:
    """The backward rings' spec: quantized only in full "int8" mode —
    "int8_fwd" keeps its backward in full precision on the saved
    operands, the same contract as quant._quant_dot_bwd."""
    return spec if spec.quant == "int8" else spec._replace(quant=None)


# ---------------------------------------------------------------------------
# per-shard ring passes (run inside shard_map, every mesh axis manual)
# ---------------------------------------------------------------------------


def _chunk_dot(a, b, dims, *, quant, sr_lhs=False, sr_rhs=False):
    """One ring chunk's contraction, fp32 result: plain dot with fp32
    accumulation, or the quantized dot (per-chunk dynamic scales — for
    seq-chunked operands these equal the monolithic scales, the
    contraction dim is never chunked)."""
    if quant:
        return _int8_dot_value(a, b, dims, sr_lhs=sr_lhs, sr_rhs=sr_rhs)
    return lax.dot_general(a, b, dims, preferred_element_type=jnp.float32)


def _ag_matmul_shard(x, w, spec: _OverlapSpec, *, sr_ring=False):
    """All-gather→matmul ring. x [b, s_l, e] is this device's seq chunk;
    w [e, *f_local] the local feature shard (rank 2 or 3 — the fused QKV
    / SwiGLU kernels carry a stack dim). Returns the full-seq output for
    the local feature shard, [b, s_l·n, *f_local], fp32.

    With quant set, the traveling payload is quantized ONCE up front
    (per-row scales over e — the dim the ring never splits) and the hops
    carry int8 values + fp32 scales: comm bytes ÷4 vs fp32."""
    axis = spec.axis_name
    n = lax.axis_size(axis)
    my = lax.axis_index(axis)
    b, s_l, _ = x.shape
    dims = (((2,), (0,)), ((), ()))
    perm = ring_schedule(n, 1)  # receive from my-1: hop i holds (my-i)%n
    out = jnp.zeros((b, s_l * n) + w.shape[1:], jnp.float32)

    if spec.quant:
        sx = absmax_scale(x, (2,))                  # [b, s_l, 1]
        qx = (stochastic_quantize if sr_ring else quantize)(x, sx)
        sw = absmax_scale(w, (0,))                  # [1, *f_local]
        qw = quantize(w, sw)
        sw_out = jnp.squeeze(sw, axis=0)            # broadcast over (b, s)

        def chunk(blk):
            q_blk, s_blk = blk
            y = lax.dot_general(q_blk, qw, dims,
                                preferred_element_type=jnp.int32)
            s_row = s_blk.reshape(s_blk.shape[:2] + (1,) * (w.ndim - 1))
            return y.astype(jnp.float32) * s_row * sw_out

        blk = (qx, sx)  # the int8 payload + its row scales travel
    else:
        def chunk(blk):
            return lax.dot_general(blk, w, dims,
                                   preferred_element_type=jnp.float32)

        blk = x

    for i in range(n):
        src = (my - i) % n
        y = chunk(blk)
        start = (0, src * s_l) + (0,) * (w.ndim - 1)
        out = lax.dynamic_update_slice(out, y, start)
        if i != n - 1:
            # the hop the scheduler hides behind the NEXT chunk's matmul
            blk = jax.tree.map(lambda t: lax.ppermute(t, axis, perm), blk)
    return out


def _matmul_rs_shard(y, w, y_dims, w_dims, spec: _OverlapSpec, *,
                     sr_lhs=False):
    """Matmul→reduce-scatter ring. y [b, S_l, *k_local] holds the full
    (ring-wise) seq extent with its trailing dims being this device's
    contraction shard; w carries the matching local shard. Contracts
    ``y_dims``×``w_dims`` per seq-chunk and ring-reduces the partials:
    after the last hop each device holds its own seq chunk fully summed
    over the ring axis — the reduce-scatter, decomposed. Returns
    [b, S_l/n, *w_free] fp32."""
    axis = spec.axis_name
    n = lax.axis_size(axis)
    my = lax.axis_index(axis)
    s_l = y.shape[1] // n
    dims = ((y_dims, w_dims), ((), ()))
    perm = ring_schedule(n, 1)

    def partial_for(dst):
        start = (0, dst * s_l) + (0,) * (y.ndim - 2)
        y_chunk = lax.dynamic_slice(
            y, start, (y.shape[0], s_l) + y.shape[2:])
        return _chunk_dot(y_chunk, w, dims, quant=spec.quant,
                          sr_lhs=sr_lhs)

    # classic ring reduce-scatter: the accumulator for chunk p starts at
    # device p+1 and travels home, collecting every device's partial —
    # at step i, device q folds in its partial for chunk (q + n-1-i) % n
    acc = partial_for((my + n - 1) % n)
    for i in range(1, n):
        acc = lax.ppermute(acc, axis, perm)
        acc = acc + partial_for((my + n - 1 - i) % n)
    return acc


def _dw_ring_shard(ring, resident, spec: _OverlapSpec, *, ring_is_lhs,
                   sr_ring=False, sr_resident=False):
    """The shared weight-gradient ring: ``ring`` [b, s_l, A] is the
    seq-split operand (rotates), ``resident`` [b, s_l·n, *B] stays put;
    each hop contracts the visiting block against the resident rows it
    corresponds to, accumulating the local dw partial [A, *B] (or
    [*B, A] with ``ring_is_lhs=False``). The sum over the batch/seq
    axes — DDP's gradient reduce for this weight — is inserted by
    shard_map's transpose when the cotangent crosses the region boundary
    (those axes are unmentioned in the weight's in_spec), which issues
    it HERE, at this layer's backward, rather than batched at the end:
    the early-reduce ordering of the ISSUE's part (b)."""
    axis = spec.axis_name
    n = lax.axis_size(axis)
    my = lax.axis_index(axis)
    b, s_l, _ = ring.shape
    dims = (((0, 1), (0, 1)), ((), ()))
    perm = ring_schedule(n, 1)
    blk = ring
    acc = None
    for i in range(n):
        src = (my - i) % n
        rows = lax.dynamic_slice(
            resident, (0, src * s_l) + (0,) * (resident.ndim - 2),
            (b, s_l) + resident.shape[2:])
        if ring_is_lhs:
            d = _chunk_dot(blk, rows, dims, quant=spec.quant,
                           sr_lhs=sr_ring, sr_rhs=sr_resident)
        else:
            d = _chunk_dot(rows, blk, dims, quant=spec.quant,
                           sr_lhs=sr_resident, sr_rhs=sr_ring)
        acc = d if acc is None else acc + d
        if i != n - 1:
            blk = lax.ppermute(blk, axis, perm)
    return acc


# ---------------------------------------------------------------------------
# the per-shard cores (custom_vjp INSIDE the manual region)
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _column_core(x, w, spec: _OverlapSpec):
    return _ag_matmul_shard(x, w, spec)


def _column_core_fwd(x, w, spec: _OverlapSpec):
    return _ag_matmul_shard(x, w, spec), (x, w)


def _column_core_bwd(spec: _OverlapSpec, res, g):
    x, w = res
    bspec = _bwd_quant(spec)
    sr = bspec.quant is not None  # stochastic-round the gradient operand
    # dx = RS-ring(g · w over w's free dims): the forward gather's
    # transpose — g's trailing dims contract with w's trailing dims
    w_free = tuple(range(1, w.ndim))
    g_dims = tuple(range(2, 2 + len(w_free)))
    dx = _matmul_rs_shard(g, w, g_dims, w_free, bspec, sr_lhs=sr)
    # dw = AG(x)^T · g, as the ring that rotates x against resident g;
    # the batch/seq-axis psum happens in shard_map's transpose
    dw = _dw_ring_shard(x, g, bspec, ring_is_lhs=True, sr_resident=sr)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_column_core.defvjp(_column_core_fwd, _column_core_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _row_core(x, w, spec: _OverlapSpec):
    return _matmul_rs_shard(x, w, (2,), (0,), spec)


def _row_core_fwd(x, w, spec: _OverlapSpec):
    return _matmul_rs_shard(x, w, (2,), (0,), spec), (x, w)


def _row_core_bwd(spec: _OverlapSpec, res, g):
    x, w = res
    bspec = _bwd_quant(spec)
    sr = bspec.quant is not None
    # dx = AG-ring(g) · w^T: the gradient travels (int8 payload under
    # full int8 mode); the local transpose of the resident shard is free
    dx = _ag_matmul_shard(g, jnp.swapaxes(w, 0, 1), bspec, sr_ring=sr)
    # dw = x^T · AG(g): rotate g against resident x, output [F_local, e]
    dw = _dw_ring_shard(g, x, bspec, ring_is_lhs=False, sr_ring=sr)
    return dx.astype(x.dtype), dw.astype(w.dtype)


_row_core.defvjp(_row_core_fwd, _row_core_bwd)


# ---------------------------------------------------------------------------
# public API (global arrays in, global arrays out)
# ---------------------------------------------------------------------------


def _seq_split(spec: _OverlapSpec):
    """The seq-dim entry/exit spec: split over the context axis AND the
    ring axis (the ring's chunk dimension). Splitting a
    tensor-replicated activation this way is a local slice, not a
    collective."""
    return (Axis.SEQ, spec.axis_name)


def ring_column_matmul(x, w, *, mesh, axis_name: str = Axis.TENSOR,
                       quant: str | None = None,
                       preferred_element_type=None):
    """``x @ w`` (x [b, s, e], w [e, *f]) with w's trailing feature dim
    sharded over ``axis_name``: the all-gather→matmul ring. Output
    [b, s, *f], feature-sharded over the ring axis at the boundary."""
    spec = _OverlapSpec(axis_name,
                        None if quant in (None, "none") else quant)
    fn = jax.shard_map(
        functools.partial(_column_core, spec=spec),
        mesh=mesh,
        in_specs=(P(_BATCH, _seq_split(spec), None),
                  P(*((None,) * (w.ndim - 1) + (axis_name,)))),
        out_specs=P(*((_BATCH, Axis.SEQ) + (None,) * (w.ndim - 2)
                      + (axis_name,))),
        check_vma=False,
    )
    out_dtype = (jnp.promote_types(x.dtype, w.dtype)
                 if preferred_element_type is None
                 else np.dtype(preferred_element_type))
    return fn(x, w).astype(out_dtype)


def ring_row_matmul(x, w, *, mesh, axis_name: str = Axis.TENSOR,
                    quant: str | None = None,
                    preferred_element_type=None):
    """``x @ w`` (x [b, s, F], w [F, e]) with the contraction dim F
    sharded over ``axis_name``: the matmul→reduce-scatter ring. Output
    [b, s, e], seq-split over the ring axis at the boundary (the
    partitioner re-gathers — async under the overlap scheduler flags —
    where downstream consumes it replicated)."""
    spec = _OverlapSpec(axis_name,
                        None if quant in (None, "none") else quant)
    fn = jax.shard_map(
        functools.partial(_row_core, spec=spec),
        mesh=mesh,
        in_specs=(P(_BATCH, Axis.SEQ, axis_name), P(axis_name, None)),
        out_specs=P(_BATCH, _seq_split(spec), None),
        check_vma=False,
    )
    out_dtype = (jnp.promote_types(x.dtype, w.dtype)
                 if preferred_element_type is None
                 else np.dtype(preferred_element_type))
    return fn(x, w).astype(out_dtype)


# ---------------------------------------------------------------------------
# expert-parallel MoE: explicit all_to_all dispatch/combine (ISSUE 14)
# ---------------------------------------------------------------------------
#
# The GShard exchange, decomposed the same way the rings decompose the TP
# collectives. The routing front-end (models/moe.py) assigns tokens to
# per-GROUP capacity slots — G groups, one per (data × fsdp × expert)
# mesh shard — so the slot tensor [G, e, c, d] enters the manual region
# group-sharded and the dispatch is a PURE PERMUTATION of equal tiles:
# `lax.all_to_all(split_axis=experts, concat_axis=groups)` hands every
# member of the expert axis ALL groups' slots for ITS experts, the local
# expert FFN runs on [G_l·ep, e/ep, c, d], and the combine a2a (the exact
# transpose) carries the outputs home. With global capacity this would be
# a reduce-scatter, not an a2a — per-group capacity is what makes the
# exchange explicit and therefore schedulable.
#
# The custom_vjp lives INSIDE the shard_map (the same flax-scan-tracer
# constraint as the rings); the weight cotangents' sum over the data/fsdp
# axes — absent from wi/wo's in_specs — is inserted by shard_map's own
# transpose. The backward reuses the two exchange directions (the
# cotangent rides the dispatch direction out, the input cotangent rides
# the combine direction home) and recomputes the FFN internals from the
# saved post-dispatch residual, so backward costs exactly one more
# dispatch/combine pair: 2 a2a forward, 2 backward per MoE layer.


class _ExpertSpec(NamedTuple):
    """Static expert-exchange configuration, threaded through custom_vjp
    as a nondiff arg."""

    axis_name: str              # the expert mesh axis
    quant: str | None           # None | "int8_fwd" | "int8"
    chunks: int                 # capacity-dim software-pipeline depth
    gelu_approx: bool           # the FFN activation's approximate flag


def _q8(x, cdims, *, sr=False):
    """(int8 codes, fp32 row scales) over ``cdims`` — the a2a payload
    format, matching the gather ring's pre-quantized hops."""
    s = absmax_scale(x, cdims)
    return (stochastic_quantize if sr else quantize)(x, s), s


def _dq8(blk):
    q, s = blk
    return q.astype(jnp.float32) * s


def _expert_act(spec: _ExpertSpec):
    return functools.partial(jax.nn.gelu, approximate=spec.gelu_approx)


def _expert_ffn_shard(recv, wi, wo, spec: _ExpertSpec):
    """The local expert FFN on post-dispatch slots: ``recv``
    [G2, e_l, c, d] (or the shipped (int8, scales) payload under quant),
    ``wi`` [e_l, d, f] / ``wo`` [e_l, f, d] this member's expert shard.
    The quantized contractions are hand-rolled int8 einsums + fp32
    rescale by the scale outer product: quant's `_int8_dot_value` refuses
    batch dimensions and the expert dim IS one here. The payload's row
    scales are the ones that rode the a2a — identical to monolithic
    quantization, since the contraction dim d is never split by the
    exchange."""
    act = _expert_act(spec)
    if spec.quant:
        qr, sr = recv if isinstance(recv, tuple) else _q8(recv, (3,))
        qwi, swi = _q8(wi, (1,))
        z = jnp.einsum("gecd,edf->gecf", qr, qwi,
                       preferred_element_type=jnp.int32)
        z = (z.astype(jnp.float32) * sr
             * swi.reshape(1, wi.shape[0], 1, wi.shape[2]))
        h = act(z)
        qh, sh = _q8(h, (3,))
        qwo, swo = _q8(wo, (1,))
        y = jnp.einsum("gecf,efd->gecd", qh, qwo,
                       preferred_element_type=jnp.int32)
        return (y.astype(jnp.float32) * sh
                * swo.reshape(1, wo.shape[0], 1, wo.shape[2]))
    z = jnp.einsum("gecd,edf->gecf", recv, wi,
                   preferred_element_type=jnp.float32)
    return jnp.einsum("gecf,efd->gecd", act(z), wo,
                      preferred_element_type=jnp.float32)


def _expert_pipeline_shard(slots, wi, wo, spec: _ExpertSpec, *,
                           sr_payload=False):
    """Dispatch → expert FFN → combine, with the capacity dim chunked
    into ``spec.chunks`` software-pipeline stages: chunk i+1's dispatch
    a2a is issued BEFORE chunk i's FFN, and chunk i's combine a2a has no
    consumer until the final concatenate — so the scheduler can hide both
    exchanges behind the neighbouring chunk's expert matmuls (the rings'
    latency-hiding recipe, with a2a hops instead of ppermute). A chunk
    count that doesn't divide capacity silently degrades to monolithic —
    the knob can never turn a valid program into a shape error.

    Returns ``(out_slots [g_l, e, c, d] fp32, recv [g_l·ep, e/ep, c, d])``
    — the dequantized post-dispatch residual the backward recomputes the
    FFN from, saving a third a2a pair."""
    axis = spec.axis_name
    c = slots.shape[2]
    k = spec.chunks if spec.chunks > 1 and c % spec.chunks == 0 else 1
    cc = c // k

    def shipped(i):
        blk = lax.dynamic_slice_in_dim(slots, i * cc, cc, axis=2)
        if spec.quant:
            blk = _q8(blk, (3,), sr=sr_payload)
        return expert_dispatch(blk, axis)

    recv = shipped(0)
    outs, recvs = [], []
    for i in range(k):
        nxt = shipped(i + 1) if i + 1 < k else None  # prefetched hop
        recvs.append(_dq8(recv) if spec.quant else recv)
        y = _expert_ffn_shard(recv, wi, wo, spec)
        outs.append(expert_combine(y, axis))
        recv = nxt
    out = outs[0] if k == 1 else jnp.concatenate(outs, axis=2)
    res = recvs[0] if k == 1 else jnp.concatenate(recvs, axis=2)
    return out, res


def _expert_fwd_parts(x, dispatch, gates, wi, wo, spec: _ExpertSpec):
    """Local slot-build → exchange pipeline → weighted combine. ``x``
    [G_l, n, d]; ``dispatch``/``gates`` [G_l, n, e, c] one-hot slot
    assignments / gate-weighted assignments from the router."""
    slots = jnp.einsum("gnec,gnd->gecd", dispatch, x,
                       preferred_element_type=jnp.float32)
    out_slots, recv = _expert_pipeline_shard(slots, wi, wo, spec)
    out = jnp.einsum("gnec,gecd->gnd", gates, out_slots,
                     preferred_element_type=jnp.float32)
    return out, out_slots, recv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5,))
def _expert_core(x, dispatch, gates, wi, wo, spec: _ExpertSpec):
    return _expert_fwd_parts(x, dispatch, gates, wi, wo, spec)[0]


def _expert_core_fwd(x, dispatch, gates, wi, wo, spec: _ExpertSpec):
    out, out_slots, recv = _expert_fwd_parts(x, dispatch, gates, wi, wo,
                                             spec)
    return out, (x, dispatch, gates, wi, wo, recv, out_slots)


def _expert_core_bwd(spec: _ExpertSpec, res, g):
    x, dispatch, gates, wi, wo, recv, out_slots = res
    axis = spec.axis_name
    # full "int8" mode stochastic-rounds the traveling cotangent payloads
    # (the rings' gradient-hop semantics); the expert-side matmuls then
    # run fp32 on the saved/dequantized operands — the saved-operand
    # contract of the monolithic quantized dot. "int8_fwd" ships fp32.
    sr = spec.quant == "int8"
    g = g.astype(jnp.float32)
    dgates = jnp.einsum("gnd,gecd->gnec", g, out_slots)
    dout = jnp.einsum("gnec,gnd->gecd", gates.astype(jnp.float32), g)
    # the cotangent travels TO the experts over the dispatch-direction
    # a2a (the forward combine's transpose) ...
    if sr:
        dy = _dq8(expert_dispatch(_q8(dout, (3,), sr=True), axis))
    else:
        dy = expert_dispatch(dout, axis)
    wi32, wo32 = wi.astype(jnp.float32), wo.astype(jnp.float32)
    z = jnp.einsum("gecd,edf->gecf", recv, wi32,
                   preferred_element_type=jnp.float32)
    h, act_vjp = jax.vjp(_expert_act(spec), z)
    dwo = jnp.einsum("gecf,gecd->efd", h, dy)
    dh = jnp.einsum("gecd,efd->gecf", dy, wo32)
    (dz,) = act_vjp(dh)
    dwi = jnp.einsum("gecd,gecf->edf", recv, dz)
    drecv = jnp.einsum("gecf,edf->gecd", dz, wi32)
    # ... and home again over the combine direction (dispatch's
    # transpose). dwi/dwo's sum over the data/fsdp axes happens in
    # shard_map's transpose at the region boundary.
    if sr:
        dslots = _dq8(expert_combine(_q8(drecv, (3,), sr=True), axis))
    else:
        dslots = expert_combine(drecv, axis)
    dx = jnp.einsum("gnec,gecd->gnd", dispatch.astype(jnp.float32), dslots)
    ddispatch = jnp.einsum("gnd,gecd->gnec", x.astype(jnp.float32), dslots)
    return (dx.astype(x.dtype), ddispatch.astype(dispatch.dtype),
            dgates.astype(gates.dtype), dwi.astype(wi.dtype),
            dwo.astype(wo.dtype))


_expert_core.defvjp(_expert_core_fwd, _expert_core_bwd)


def expert_a2a_ffn(x, dispatch, gates, wi, wo, *, mesh,
                   axis_name: str = Axis.EXPERT, quant: str | None = None,
                   chunks: int = 1, gelu_approx: bool = True,
                   preferred_element_type=None):
    """Expert-parallel MoE FFN with explicit all_to_all dispatch/combine
    under shard_map.

    ``x`` [G, n, d] grouped tokens, ``dispatch``/``gates`` [G, n, e, c]
    the router's slot assignments, ``wi`` [e, d, f] / ``wo`` [e, f, d]
    the stacked expert kernels (expert dim sharded over ``axis_name``).
    G must tile data × fsdp × expert (``expert_a2a_applicable`` is the
    static gate callers check before routing here). With ``quant``, the
    dispatch payload ships as pre-quantized int8 codes + fp32 row scales
    and the expert matmuls consume them directly — exchange traffic ÷4
    vs fp32 on top of the overlap. ``chunks`` > 1 pipelines the exchange
    behind the expert matmuls chunk by chunk."""
    spec = _ExpertSpec(axis_name,
                       None if quant in (None, "none") else quant,
                       max(1, int(chunks)), bool(gelu_approx))
    grp = (Axis.DATA, Axis.FSDP, axis_name)
    fn = jax.shard_map(
        functools.partial(_expert_core, spec=spec),
        mesh=mesh,
        in_specs=(P(grp, None, None),
                  P(grp, None, None, None),
                  P(grp, None, None, None),
                  P(axis_name, None, None),
                  P(axis_name, None, None)),
        out_specs=P(grp, None, None),
        check_vma=False,
    )
    out_dtype = (x.dtype if preferred_element_type is None
                 else np.dtype(preferred_element_type))
    return fn(x, dispatch, gates, wi, wo).astype(out_dtype)


def expert_a2a_applicable(num_groups: int, num_experts: int, mesh,
                          axis_name: str = Axis.EXPERT) -> bool:
    """Static check that the explicit exchange tiles these shapes on this
    mesh: an expert axis of size > 1 that divides the expert count, and a
    group count that tiles data × fsdp × expert (each shard owns whole
    groups). Callers fall back to the dense einsum path when False, so
    the dispatch knob can never turn a valid program into a shape
    error."""
    if mesh is None or axis_name not in getattr(mesh, "shape", {}):
        return False
    ep = mesh.shape[axis_name]
    if ep <= 1 or num_experts % ep:
        return False
    shards = (mesh.shape.get(Axis.DATA, 1) * mesh.shape.get(Axis.FSDP, 1)
              * ep)
    return num_groups >= shards and num_groups % shards == 0


def ring_divisibility(x_shape, w_shape, mesh, axis_name: str,
                      kind: str) -> bool:
    """Static check that the ring decomposition tiles these shapes on
    this mesh: seq must split over (seq × ring) chunks, the batch over
    the data axes, and the sharded weight dim over the ring. Callers
    fall back to the monolithic matmul when False (decode's s=1 and
    ragged eval widths land here), so the knob can never turn a valid
    program into a shape error."""
    if axis_name not in mesh.shape:
        return False
    n = mesh.shape[axis_name]
    if n <= 1 or len(x_shape) != 3:
        return False
    b, s, _ = x_shape
    data = mesh.shape.get(Axis.DATA, 1) * mesh.shape.get(Axis.FSDP, 1)
    seq = mesh.shape.get(Axis.SEQ, 1)
    if b % data or s % (seq * n) or (s // (seq * n)) == 0:
        return False
    sharded_dim = w_shape[-1] if kind == "column" else w_shape[0]
    if kind == "row" and (len(w_shape) != 2 or x_shape[-1] != w_shape[0]):
        return False
    return sharded_dim % n == 0
