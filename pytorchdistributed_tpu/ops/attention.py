"""Dense (reference) attention — the baseline every parallel variant is
tested against.

The reference repo contains no attention model at all (its LLaMA cell,
03_model_parallel.ipynb:86, never ran — SURVEY.md §5 "Long-context"), so this
is the framework's own reference implementation: numerically-stable softmax
attention on [batch, seq, heads, head_dim] tensors, fp32 accumulation (MXU
inputs stay bf16, sums run fp32 — parallel/precision.py policy).

Sharded variants (ring, Ulysses, Pallas flash) must match this function to
tolerance; see tests/test_attention.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def causal_mask(q_len: int, kv_len: int, *, q_offset: int = 0,
                kv_offset: int = 0, dtype=jnp.float32) -> jax.Array:
    """[q_len, kv_len] additive mask; offsets position the blocks within the
    global sequence (used by blockwise/ring variants)."""
    q_pos = q_offset + jnp.arange(q_len)[:, None]
    kv_pos = kv_offset + jnp.arange(kv_len)[None, :]
    return jnp.where(q_pos >= kv_pos, 0.0, -jnp.inf).astype(dtype)


def dense_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """softmax(q·kᵀ/√d [+mask])·v over [B, S, H, D] tensors."""
    head_dim = q.shape[-1]
    scale = (head_dim**-0.5) if scale is None else scale
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        logits = logits + causal_mask(q.shape[1], k.shape[1])[None, None]
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)
