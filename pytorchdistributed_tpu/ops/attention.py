"""Dense (reference) attention — the baseline every parallel variant is
tested against.

The reference repo contains no attention model at all (its LLaMA cell,
03_model_parallel.ipynb:86, never ran — SURVEY.md §5 "Long-context"), so this
is the framework's own reference implementation: numerically-stable softmax
attention on [batch, seq, heads, head_dim] tensors, fp32 accumulation (MXU
inputs stay bf16, sums run fp32 — parallel/precision.py policy).

Sharded variants (ring, Ulysses, Pallas flash) must match this function to
tolerance; see tests/test_attention.py.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def causal_mask(q_len: int, kv_len: int, *, q_offset: int = 0,
                kv_offset: int = 0, dtype=jnp.float32) -> jax.Array:
    """[q_len, kv_len] additive mask; offsets position the blocks within the
    global sequence (used by blockwise/ring variants)."""
    q_pos = q_offset + jnp.arange(q_len)[:, None]
    kv_pos = kv_offset + jnp.arange(kv_len)[None, :]
    return jnp.where(q_pos >= kv_pos, 0.0, -jnp.inf).astype(dtype)


def paged_gather(pool, block_tables):
    """Gather block-table paged K or V back into position order.

    ``pool`` is the engine's shared block pool ``[num_blocks, block_size,
    kv_heads, head_dim]``; ``block_tables`` maps each slot's logical block
    j (positions [j*bs, (j+1)*bs)) to a physical pool block:
    ``[slots, blocks_per_slot]`` int32. Returns ``[slots,
    blocks_per_slot*block_size, kv_heads, head_dim]`` — the exact tensor
    the dense per-slot cache would hold over that window, so downstream
    masked attention is bitwise-identical to the dense path. Table
    entries past a slot's live length point at the reserved trash block
    (0); their rows are finite garbage the position mask zeroes exactly.
    """
    g = pool[block_tables]          # [slots, nb, bs, kv_heads, head_dim]
    slots, nb, bs = g.shape[:3]
    return g.reshape(slots, nb * bs, *g.shape[3:])


def paged_attention(q, k_pool, v_pool, block_tables, lengths, *,
                    k_scale=None, v_scale=None, sink_tokens: int = 0,
                    window_tokens: int = 0,
                    scale: float | None = None) -> jax.Array:
    """Reference paged decode attention — the math twin of the serving
    tick's in-model path (models/transformer.py paged branch), exposed so
    the parity tests and the Pallas kernel have a standalone oracle.

    Args:
      q: ``[slots, q_len, heads, head_dim]`` current-chunk queries (q_len
        is 1 for a decode tick, >1 for a chunked-prefill step).
      k_pool / v_pool: ``[num_blocks, block_size, kv_heads, head_dim]``,
        model dtype or int8 (the compressed pool — pass the scales).
      block_tables: ``[slots, blocks_per_slot]`` int32.
      lengths: ``[slots]`` int32 — tokens already cached per slot; query
        token i of a slot sits at absolute position lengths + i and
        attends cache positions <= it. The CURRENT chunk's K/V must
        already be written into the pool (the model writes before it
        attends), exactly like the dense decode contract.
      k_scale / v_scale: ``[num_blocks, block_size, kv_heads]`` fp32
        per-(token, head) dequant scales for an int8 pool (the canonical
        ops/quant.kv_dequantize math, cast to q's dtype — bitwise-equal
        to the in-model int8 gather read).
      sink_tokens / window_tokens: sink+sliding-window mask
        (window_tokens 0 = full attention): position j is attendable by
        the query at position p iff ``j < sink_tokens or
        j > p - window_tokens`` (and j <= p).

    Returns ``[slots, q_len, heads, head_dim]`` in q's dtype. Bitwise
    equal (fp32 accumulate, fp32 softmax) to the dense cache path over
    the same window — including the ``/ sqrt(d)`` spelling of the scale
    (multiplying by the reciprocal rounds differently), when ``scale`` is
    left at None.
    """
    head_dim = q.shape[-1]
    kc = paged_gather(k_pool, block_tables)
    vc = paged_gather(v_pool, block_tables)
    if (k_scale is None) != (v_scale is None):
        raise ValueError("pass both k_scale and v_scale or neither")
    if k_scale is not None:
        from pytorchdistributed_tpu.ops.quant import kv_dequantize

        kc = kv_dequantize(kc, paged_gather(k_scale, block_tables), q.dtype)
        vc = kv_dequantize(vc, paged_gather(v_scale, block_tables), q.dtype)
    rep = q.shape[2] // kc.shape[2]
    if rep > 1:
        kc = jnp.repeat(kc, rep, axis=2)
        vc = jnp.repeat(vc, rep, axis=2)
    pos = lengths[:, None] + jnp.arange(q.shape[1])          # [slots, q]
    valid = jnp.arange(kc.shape[1]) <= pos[..., None]        # [slots, q, j]
    if window_tokens:
        j = jnp.arange(kc.shape[1])
        valid &= (j < sink_tokens) | (j > pos[..., None] - window_tokens)
    scores = jnp.einsum("bihd,bjhd->bhij", q, kc,
                        preferred_element_type=jnp.float32)
    if scale is None:
        scores = scores / jnp.sqrt(head_dim).astype(jnp.float32)
    else:
        scores = scores * scale
    scores = jnp.where(valid[:, None], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhij,bjhd->bihd", probs.astype(vc.dtype), vc,
                     preferred_element_type=jnp.float32)
    return out.astype(q.dtype)


def dense_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """softmax(q·kᵀ/√d [+mask])·v over [B, S, H, D] tensors."""
    head_dim = q.shape[-1]
    scale = (head_dim**-0.5) if scale is None else scale
    logits = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        logits = logits + causal_mask(q.shape[1], k.shape[1])[None, None]
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum(
        "bhqk,bkhd->bqhd", probs.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.astype(q.dtype)
