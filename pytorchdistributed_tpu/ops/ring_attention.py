"""Ring attention — context parallelism over the "seq" mesh axis.

Required for framework completeness (SURVEY.md §5 "Long-context": the only
"ring" in the reference is ring-allreduce of *gradients*,
02_ddp.ipynb:33-47 — ring attention is the missing long-context analog).

Mechanism: Q stays put; K/V shards rotate around the ring one hop per step
(`lax.ppermute`, which XLA lowers to neighbor ICI transfers on the TPU
torus). Each device folds the visiting K/V block into a numerically-stable
online-softmax accumulator (the FlashAttention recurrence). The per-block
local compute is the Pallas flash kernel (`impl="pallas"`, the default):
logits for a (q_block, k_block) tile live only in VMEM, so per-device
memory is O(S_local · block) and the full [S, S] score matrix never
materializes — not even one ring step's [S_local, S_local] slab in HBM.
Communication of step i+1 overlaps compute of step i because XLA schedules
the ppermute DMA asynchronously.

The backward is a hand-written **reverse ring** under `jax.custom_vjp`, NOT
scan AD: reverse-mode AD of the forward scan would save the rotated (k, v)
carry at every ring step — O(S_full) residuals per device, silently
defeating the memory claim at exactly the sizes where ring attention
matters. Instead the VJP re-runs the rotation (recomputing each K/V block's
position by re-rotating — activation recomputation in the communication
dimension) while dK/dV accumulators *co-travel* with their blocks: after n
hops each block's gradient arrives back home fully accumulated. Residuals
are (q, k, v, o, lse) — O(S_local), same contract as the single-chip flash
kernel (ops/pallas_attention.py). tests/test_attention.py asserts both the
value/grad equivalence vs dense attention and the O(S_local) residual bound.

For causal masking each visiting block is one of three static cases — fully
visible (block index < mine), diagonal (== mine, local causal mask), or
fully masked (> mine, skipped via `lax.switch`) — so the Pallas kernels
never need dynamic global offsets.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.ad_checkpoint
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.sharding import PartitionSpec as P

from pytorchdistributed_tpu.ops.pallas_attention import (
    _bwd_dkv_kernel,
    _bwd_dq_kernel,
    _fwd_kernel,
    _out_sds,
    _vmem_scratch,
)
from pytorchdistributed_tpu.runtime.mesh import Axis

_NEG_INF = -1e30  # finite stand-in for -inf: keeps exp() exact zero without
                  # generating NaNs in (m - new_m) when a row is all-masked

def _vary_like(like):
    """Promoter onto ``like``'s varying-manual-axes set (identity when the
    trace carries no vma, i.e. check_vma=False or outside shard_map)."""
    vma = getattr(jax.typeof(like), "vma", None)
    if not vma:
        return lambda x: x
    return lambda x: lax.pcast(x, tuple(vma), to="varying")


# vma-typed pallas_call out_shapes: one definition, shared with the flash
# kernels (pallas_attention._out_sds) — the ring's accumulators vary
# exactly like the block operands they update.
_sds = _out_sds


class _RingSpec(NamedTuple):
    """Static configuration threaded through custom_vjp as a nondiff arg."""

    axis_name: str
    causal: bool
    scale: float
    impl: str          # "pallas" | "xla"
    block_q: int
    block_k: int
    interpret: bool


# ---------------------------------------------------------------------------
# Per-visiting-block local compute — Pallas flash kernels
# ---------------------------------------------------------------------------
# All kernels run on folded [B·H_local, S_local, D] operands. `causal=True`
# means the *diagonal* ring case (q block == kv block globally), so local
# positions give the exact global mask; fully-visible blocks use
# causal=False; fully-masked blocks never reach a kernel.
#
# The kernel BODIES are the single-chip flash kernels themselves
# (pallas_attention._fwd_kernel/_bwd_dq_kernel/_bwd_dkv_kernel) traced with
# ``carry=True``: the ring's (m, l, acc) / dQ / dK/dV accumulators enter
# and leave through HBM each hop so they survive across ring steps, while
# the flagship carry=False path keeps its trace-time zero-init (no HBM
# zero-read). One definition of the masking/dtype logic — closes VERDICT
# r3 weak #6's port-the-fix contract.


def _pallas_fwd_update(q, k_blk, v_blk, acc, m, l, *, causal: bool,
                       spec: _RingSpec):
    bh, s, d = q.shape
    bq, bk = min(spec.block_q, s), min(spec.block_k, s)
    nq, nk = pl.cdiv(s, bq), pl.cdiv(s, bk)
    kernel = functools.partial(
        _fwd_kernel, block_q=bq, block_k=bk, causal=causal,
        scale=spec.scale, num_k_blocks=nk, seq_len=s, carry=True)
    qspec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    kspec = pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0))
    rowspec = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))
    m2, l2, acc2 = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[qspec, kspec, kspec, rowspec, rowspec, qspec],
        out_specs=[rowspec, rowspec, qspec],
        out_shape=[
            _sds((bh, s, 1), jnp.float32, q),
            _sds((bh, s, 1), jnp.float32, q),
            _sds((bh, s, d), jnp.float32, q),
        ],
        scratch_shapes=[
            _vmem_scratch((bq, d)),
            _vmem_scratch((bq, 1)),
            _vmem_scratch((bq, 1)),
        ],
        interpret=spec.interpret,
    )(q, k_blk, v_blk, m, l, acc)
    return acc2, m2, l2


def _xla_fwd_update(q, k_blk, v_blk, acc, m, l, *, causal: bool,
                    spec: _RingSpec):
    """Reference block update (materializes the [S_local, S_local] logits
    slab — for debugging the kernels, not for long-context use)."""
    logits = jnp.einsum("bqd,bkd->bqk", q, k_blk,
                        preferred_element_type=jnp.float32) * spec.scale
    if causal:
        s = q.shape[1]
        mask = jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]
        logits = jnp.where(mask[None], logits, _NEG_INF)
    m_new = jnp.maximum(m, jnp.max(logits, axis=-1, keepdims=True))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(logits - m_new)
    if causal:
        p = jnp.where(mask[None], p, 0.0)
    l_new = l * corr + jnp.sum(p, -1, keepdims=True)
    pv = jnp.einsum("bqk,bkd->bqd", p.astype(v_blk.dtype), v_blk,
                    preferred_element_type=jnp.float32)
    return acc * corr + pv, m_new, l_new


def _pallas_bwd_update(q, k_blk, v_blk, do, lse, delta, dq, dk_blk, dv_blk,
                       *, causal: bool, spec: _RingSpec):
    bh, s, d = q.shape
    bq, bk = min(spec.block_q, s), min(spec.block_k, s)
    nq, nk = pl.cdiv(s, bq), pl.cdiv(s, bk)
    qspec = pl.BlockSpec((1, bq, d), lambda b, i, j: (b, i, 0))
    rowspec = pl.BlockSpec((1, bq, 1), lambda b, i, j: (b, i, 0))
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_q=bq, block_k=bk, causal=causal,
            scale=spec.scale, num_k_blocks=nk, seq_len=s, carry=True),
        grid=(bh, nq, nk),
        in_specs=[
            qspec,
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, bk, d), lambda b, i, j: (b, j, 0)),
            qspec, rowspec, rowspec, qspec,
        ],
        out_specs=qspec,
        out_shape=_sds((bh, s, d), jnp.float32, q),
        scratch_shapes=[_vmem_scratch((bq, d))],
        interpret=spec.interpret,
    )(q, k_blk, v_blk, do, lse, delta, dq)
    # dKV grid transposes the roles: k blocks outer, q blocks sequential
    # (plus the unified kernel's GQA group dim, trivially 1 here — the
    # ring path folds q and kv heads identically).
    kspec = pl.BlockSpec((1, bk, d), lambda b, i, g, j: (b, i, 0))
    qspec_t = pl.BlockSpec((1, bq, d), lambda b, i, g, j: (b, j, 0))
    rowspec_t = pl.BlockSpec((1, bq, 1), lambda b, i, g, j: (b, j, 0))
    dk_blk, dv_blk = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=bq, block_k=bk, causal=causal,
            scale=spec.scale, num_q_blocks=nq, seq_len=s, group=1,
            carry=True),
        grid=(bh, nk, 1, nq),
        in_specs=[qspec_t, kspec, kspec, qspec_t, rowspec_t, rowspec_t,
                  kspec, kspec],
        out_specs=[kspec, kspec],
        out_shape=[
            _sds((bh, s, d), jnp.float32, q),
            _sds((bh, s, d), jnp.float32, q),
        ],
        scratch_shapes=[_vmem_scratch((bk, d)), _vmem_scratch((bk, d))],
        interpret=spec.interpret,
    )(q, k_blk, v_blk, do, lse, delta, dk_blk, dv_blk)
    return dq, dk_blk, dv_blk


def _xla_bwd_update(q, k_blk, v_blk, do, lse, delta, dq, dk_blk, dv_blk,
                    *, causal: bool, spec: _RingSpec):
    s_blk = jnp.einsum("bqd,bkd->bqk", q, k_blk,
                       preferred_element_type=jnp.float32) * spec.scale
    p = jnp.exp(s_blk - lse)
    if causal:
        s = q.shape[1]
        mask = (jnp.arange(s)[:, None] >= jnp.arange(s)[None, :])[None]
        p = jnp.where(mask, p, 0.0)
    dp = jnp.einsum("bqd,bkd->bqk", do, v_blk,
                    preferred_element_type=jnp.float32)
    ds = p * (dp - delta) * spec.scale
    dq = dq + jnp.einsum("bqk,bkd->bqd", ds.astype(k_blk.dtype), k_blk,
                         preferred_element_type=jnp.float32)
    dk_blk = dk_blk + jnp.einsum("bqk,bqd->bkd", ds.astype(q.dtype), q,
                                 preferred_element_type=jnp.float32)
    dv_blk = dv_blk + jnp.einsum("bqk,bqd->bkd", p.astype(do.dtype), do,
                                 preferred_element_type=jnp.float32)
    return dq, dk_blk, dv_blk


# ---------------------------------------------------------------------------
# The ring itself (per-shard body under shard_map) — custom_vjp
# ---------------------------------------------------------------------------


def _ring_fwd_pass(q, k, v, spec: _RingSpec):
    """Forward ring on folded [B·H, S_local, D] operands. Returns
    (out, lse) with lse [B·H, S_local, 1] fp32."""
    n = lax.axis_size(spec.axis_name)
    my = lax.axis_index(spec.axis_name)
    bh, s, d = q.shape
    update = (_pallas_fwd_update if spec.impl == "pallas"
              else _xla_fwd_update)
    perm = [(j, (j + 1) % n) for j in range(n)]

    # Freshly-created accumulators are UNVARYING under check_vma=True
    # shard_map; promote them to q's varying-manual-axes set up front —
    # the causal lax.switch requires every branch to return identical vma,
    # and the skip branch passes these through while the kernel branches
    # return q-varying outputs (q varies over ALL the mesh axes its
    # sharding touches, not just the ring axis). No-op when the checker
    # is off (empty vma).
    vary = _vary_like(q)
    acc0 = vary(jnp.zeros((bh, s, d), jnp.float32))
    m0 = vary(jnp.full((bh, s, 1), _NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((bh, s, 1), jnp.float32))

    def step(carry, i):
        acc, m, l, k_blk, v_blk = carry
        src = (my - i) % n  # which block this device holds at step i
        if spec.causal:
            # 0: fully visible, 1: diagonal (local causal mask), 2: skip
            mode = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
            acc, m, l = lax.switch(
                mode,
                [functools.partial(update, causal=False, spec=spec),
                 functools.partial(update, causal=True, spec=spec),
                 lambda q, kb, vb, acc, m, l: (acc, m, l)],
                q, k_blk, v_blk, acc, m, l)
        else:
            acc, m, l = update(q, k_blk, v_blk, acc, m, l, causal=False,
                               spec=spec)
        k_blk = lax.ppermute(k_blk, spec.axis_name, perm)
        v_blk = lax.ppermute(v_blk, spec.axis_name, perm)
        return (acc, m, l, k_blk, v_blk), None

    (acc, m, l, _, _), _ = lax.scan(step, (acc0, m0, l0, k, v),
                                    jnp.arange(n))
    l = jnp.maximum(l, 1e-30)
    out = (acc / l).astype(q.dtype)
    return out, m + jnp.log(l)


def _fold(t):  # [B, S, H, D] -> [B*H, S, D]
    b, s, h, d = t.shape
    return t.transpose(0, 2, 1, 3).reshape(b * h, s, d)


def _unfold(t, b, h):  # [B*H, S, D] -> [B, S, H, D]
    bh, s, d = t.shape
    return t.reshape(b, h, s, d).transpose(0, 2, 1, 3)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _ring_core(q, k, v, spec: _RingSpec):
    out, _ = _ring_fwd_pass(q, k, v, spec)
    return out


def _ring_core_fwd(q, k, v, spec: _RingSpec):
    out, lse = _ring_fwd_pass(q, k, v, spec)
    # Named so remat policies can keep the ring's residuals: without these,
    # `jax.checkpoint` re-runs the whole forward ring (n ppermute hops + n
    # kernel launches per layer) during backward just to regenerate
    # (out, lse) — same pattern as ops/pallas_attention._flash_vjp_fwd.
    out = jax.ad_checkpoint.checkpoint_name(out, "attn_out")
    lse = jax.ad_checkpoint.checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse)


def _ring_core_bwd(spec: _RingSpec, res, do):
    """Reverse ring: re-rotate K/V (recomputing each step's block position
    instead of having saved it) while the co-travelling dK/dV accumulators
    collect every q-shard's contribution; after n hops they arrive home."""
    q, k, v, o, lse = res
    n = lax.axis_size(spec.axis_name)
    my = lax.axis_index(spec.axis_name)
    bh, s, d = q.shape
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)
    update = (_pallas_bwd_update if spec.impl == "pallas"
              else _xla_bwd_update)
    perm = [(j, (j + 1) % n) for j in range(n)]

    # see _ring_fwd_pass: promoted so the causal switch's skip branch
    # agrees with the kernel branches under check_vma=True
    vary = _vary_like(q)
    dq0 = vary(jnp.zeros((bh, s, d), jnp.float32))
    dkv0 = vary(jnp.zeros((bh, s, d), jnp.float32))

    def step(carry, i):
        k_blk, v_blk, dq, dk_blk, dv_blk = carry
        src = (my - i) % n
        if spec.causal:
            mode = jnp.where(src == my, 1, jnp.where(src < my, 0, 2))
            dq, dk_blk, dv_blk = lax.switch(
                mode,
                [functools.partial(update, causal=False, spec=spec),
                 functools.partial(update, causal=True, spec=spec),
                 lambda q, kb, vb, do, lse, delta, dq, dk, dv: (dq, dk, dv)],
                q, k_blk, v_blk, do, lse, delta, dq, dk_blk, dv_blk)
        else:
            dq, dk_blk, dv_blk = update(
                q, k_blk, v_blk, do, lse, delta, dq, dk_blk, dv_blk,
                causal=False, spec=spec)
        # dK/dV ride the same rotation as their blocks — the n-th hop
        # returns both to the home device, gradient complete.
        rot = lambda x: lax.ppermute(x, spec.axis_name, perm)
        return (rot(k_blk), rot(v_blk), dq, rot(dk_blk), rot(dv_blk)), None

    (_, _, dq, dk, dv), _ = lax.scan(
        step, (k, v, dq0, dkv0, dkv0), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool,
                          scale: float | None, impl: str, block_q: int,
                          block_k: int, interpret: bool):
    """Per-shard body: q,k,v are the local [B, S_local, H_local, D] blocks;
    runs inside shard_map with ``axis_name`` bound."""
    b, s, h, d = q.shape
    spec = _RingSpec(
        axis_name=axis_name, causal=causal,
        scale=(d**-0.5) if scale is None else scale,
        impl=impl, block_q=block_q, block_k=block_k, interpret=interpret)
    out = _ring_core(_fold(q), _fold(k), _fold(v), spec)
    return _unfold(out, b, h)


def ring_attention_sharded(q, k, v, *, causal: bool = False,
                           mesh=None, scale: float | None = None,
                           impl: str = "pallas", block_q: int = 512,
                           block_k: int = 512,
                           interpret: bool | None = None,
                           check_vma: bool | None = None):
    """Drop-in replacement for ops.attention.dense_attention on inputs whose
    seq dim is sharded over the "seq" mesh axis (and heads optionally over
    "tensor"). Uses the ambient mesh (`jax.set_mesh`) unless given one.

    ``impl="pallas"`` (default) runs each visiting block through the flash
    VMEM recurrence; ``impl="xla"`` is the plain-einsum reference path.

    ``check_vma``: shard_map's varying-manual-axes checker. Default (None)
    = ON whenever the kernels run compiled (the production TPU path —
    verified end-to-end on hardware, tests/test_attention.py::
    test_ring_check_vma_tpu, v5e 2026-07-31) and OFF under Pallas
    interpret mode (the CPU sim every test runs on), whose internal
    evaluation mixes varying and invariant index constants that the
    checker rejects ("Primitive dynamic_slice requires varying manual
    axes to match ... please open an issue at github.com/jax-ml/jax") —
    an interpreter limitation, not a property of this ring. The checked
    default covers ``impl="xla"`` too; its acceptance is pinned by
    tests/test_attention.py::test_ring_xla_impl_checked_sim (trace-time
    property, sim-testable) plus one checked xla step in the TPU-gated
    hardware-evidence tests (ADVICE r5)."""
    if mesh is None:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or not mesh.axis_names:
            raise ValueError(
                "ring attention needs a mesh: call under jax.set_mesh(mesh) "
                "or pass mesh=")
    if impl not in ("pallas", "xla"):
        raise ValueError(f"unknown ring attention impl {impl!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if check_vma is None:
        # checked by default on the compiled path; interpret mode (and the
        # xla reference impl riding the same sim) opts out — see docstring
        check_vma = not interpret
    spec = P((Axis.DATA, Axis.FSDP), Axis.SEQ, Axis.TENSOR, None)
    fn = jax.shard_map(
        functools.partial(_ring_attention_local, axis_name=Axis.SEQ,
                          causal=causal, scale=scale, impl=impl,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=check_vma,
    )
    return fn(q, k, v)
