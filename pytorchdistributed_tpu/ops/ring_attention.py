"""Ring attention — context parallelism over the "seq" mesh axis.

Required for framework completeness (SURVEY.md §5 "Long-context": the only
"ring" in the reference is ring-allreduce of *gradients*,
02_ddp.ipynb:33-47 — ring attention is the missing long-context analog).

Mechanism: Q stays put; K/V shards rotate around the ring one hop per step
(`lax.ppermute`, which XLA lowers to neighbor ICI transfers on the TPU
torus). Each device folds the visiting K/V block into a numerically-stable
online-softmax accumulator (the FlashAttention recurrence), so the full
[S, S] score matrix never materializes and per-device memory is
O(S_local · S_block). Communication of step i+1 overlaps compute of step i
because XLA schedules the ppermute DMA asynchronously.

Gradients come for free: the loop is a `lax.scan`, so reverse-mode AD
produces the reverse ring automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from pytorchdistributed_tpu.runtime.mesh import Axis

_NEG_INF = -1e30  # finite stand-in for -inf: keeps exp() exact zero without
                  # generating NaNs in (m - new_m) when a row is all-masked


def _ring_attention_local(q, k, v, *, axis_name: str, causal: bool,
                          scale: float | None = None):
    """Per-shard body: q,k,v are the local [B, S_local, H_local, D] blocks;
    runs inside shard_map with ``axis_name`` bound."""
    n = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, s, h, d = q.shape
    scale = (d**-0.5) if scale is None else scale
    q32 = q.astype(jnp.float32) * scale
    q_pos = my * s + jnp.arange(s)

    def step(carry, i):
        o, m, l, kv = carry
        k_blk, v_blk = kv
        src = (my - i) % n  # block id we hold after i forward rotations
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32))
        if causal:
            kv_pos = src * s + jnp.arange(s)
            mask = q_pos[:, None] >= kv_pos[None, :]
            logits = jnp.where(mask[None, None], logits, _NEG_INF)
        blk_max = jnp.max(logits, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(logits - new_m[..., None])
        if causal:
            p = jnp.where(mask[None, None], p, 0.0)
        new_l = l * corr + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p, v_blk.astype(jnp.float32))
        new_o = o * corr.transpose(0, 2, 1)[..., None] + pv
        # rotate K/V one hop around the ring (ICI neighbor transfer)
        perm = [(j, (j + 1) % n) for j in range(n)]
        kv = jax.tree.map(lambda x: lax.ppermute(x, axis_name, perm), kv)
        return (new_o, new_m, new_l, kv), None

    # Mark the accumulators device-varying (jax 0.9 vma typing): inside
    # shard_map a fresh zeros array is "invariant" while the scan writes
    # varying values into it — pcast aligns the carry types.
    vma = (Axis.DATA, Axis.FSDP, Axis.SEQ, Axis.TENSOR)
    o0 = lax.pcast(jnp.zeros((b, s, h, d), jnp.float32), vma, to="varying")
    m0 = lax.pcast(jnp.full((b, h, s), _NEG_INF, jnp.float32), vma,
                   to="varying")
    l0 = lax.pcast(jnp.zeros((b, h, s), jnp.float32), vma, to="varying")
    (o, m, l, _), _ = lax.scan(step, (o0, m0, l0, (k, v)), jnp.arange(n))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, *, causal: bool = False,
                           mesh=None, scale: float | None = None):
    """Drop-in replacement for ops.attention.dense_attention on inputs whose
    seq dim is sharded over the "seq" mesh axis (and heads optionally over
    "tensor"). Uses the ambient mesh (`jax.set_mesh`) unless given one.
    """
    if mesh is None:
        mesh = jax.sharding.get_abstract_mesh()
        if mesh is None or not mesh.axis_names:
            raise ValueError(
                "ring attention needs a mesh: call under jax.set_mesh(mesh) "
                "or pass mesh=")
    spec = P((Axis.DATA, Axis.FSDP), Axis.SEQ, Axis.TENSOR, None)
    fn = jax.shard_map(
        functools.partial(_ring_attention_local, axis_name=Axis.SEQ,
                          causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    return fn(q, k, v)
