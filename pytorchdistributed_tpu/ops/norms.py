"""Fused LayerNorm / RMSNorm with hand-written backward (custom_vjp).

Why not flax's nn.LayerNorm/nn.RMSNorm + AD: with fp32 normalization math
over bf16 activations (the TPU mixed-precision contract), AD saves the
UPCAST fp32 [batch, seq, embed] intermediates as residuals and re-reads
them across several backward fusions — the r3 Llama-1B profile attributed
~64 ms/step to norm-backward reduce fusions (BASELINE.md). Here the
residuals are the bf16 input plus the per-row statistics ([..., 1] fp32 —
negligible), the upcast is re-done inside the one backward fusion (free:
it fuses into the reduce), and the whole dx expression is a single
elementwise+row-reduce program XLA can emit as one pass:

    rmsnorm:   dx = rsigma · (g − xhat · mean(g ∘ xhat)),  g = dy·scale
    layernorm: dx = rsigma · (g − mean(g) − xhat · mean(g ∘ xhat))

with xhat recomputed from (x, stats). Parameter grads reduce over the row
axes in the same pass: dscale = Σ dy ∘ xhat, dbias = Σ dy.

The flax Modules below are drop-in replacements for nn.RMSNorm /
nn.LayerNorm (same param names/shapes/partitioning, fp32 output), so
checkpoints and sharding rules are unchanged. Equivalence vs the flax
originals is asserted in tests/test_norms.py.

Reference parity note: the reference never defines a norm (its models come
from torchvision/PyTorch, SURVEY.md §2a); this is hot-path kernel work the
TPU build owns outright.
"""

from __future__ import annotations

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rmsnorm(x, scale, eps: float):
    """y = x / sqrt(mean(x², -1) + eps) · scale, computed in fp32,
    returned fp32 (caller casts to its compute dtype)."""
    y, _ = _rms_fwd_math(x, scale, eps)
    return y


def _rms_fwd_math(x, scale, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    rsigma = jax.lax.rsqrt(var + eps)
    y = x32 * rsigma * scale.astype(jnp.float32)
    return y, rsigma


def _rms_fwd(x, scale, eps):
    y, rsigma = _rms_fwd_math(x, scale, eps)
    return y, (x, rsigma, scale)


def _rms_bwd(eps, res, dy):
    x, rsigma, scale = res
    x32 = x.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    xhat = x32 * rsigma
    g = dy32 * scale.astype(jnp.float32)
    c = jnp.mean(g * xhat, axis=-1, keepdims=True)
    dx = (rsigma * (g - xhat * c)).astype(x.dtype)
    dscale = jnp.sum(dy32 * xhat,
                     axis=tuple(range(x.ndim - 1))).astype(scale.dtype)
    return dx, dscale


rmsnorm.defvjp(_rms_fwd, _rms_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def layernorm(x, scale, bias, eps: float):
    """y = (x − mean(x)) / sqrt(var(x) + eps) · scale + bias in fp32."""
    y, _, _ = _ln_fwd_math(x, scale, bias, eps)
    return y


def _ln_fwd_math(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    xc = x32 - mu
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    rsigma = jax.lax.rsqrt(var + eps)
    y = xc * rsigma * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y, mu, rsigma


def _ln_fwd(x, scale, bias, eps):
    y, mu, rsigma = _ln_fwd_math(x, scale, bias, eps)
    return y, (x, mu, rsigma, scale)


def _ln_bwd(eps, res, dy):
    x, mu, rsigma, scale = res
    x32 = x.astype(jnp.float32)
    dy32 = dy.astype(jnp.float32)
    xhat = (x32 - mu) * rsigma
    g = dy32 * scale.astype(jnp.float32)
    c1 = jnp.mean(g, axis=-1, keepdims=True)
    c2 = jnp.mean(g * xhat, axis=-1, keepdims=True)
    dx = (rsigma * (g - c1 - xhat * c2)).astype(x.dtype)
    row_axes = tuple(range(x.ndim - 1))
    dscale = jnp.sum(dy32 * xhat, axis=row_axes).astype(scale.dtype)
    dbias = jnp.sum(dy32, axis=row_axes).astype(scale.dtype)
    return dx, dscale, dbias


layernorm.defvjp(_ln_fwd, _ln_bwd)


class FusedRMSNorm(nn.Module):
    """nn.RMSNorm drop-in (param "scale", fp32 math/output) over the fused
    custom_vjp above."""

    epsilon: float = 1e-6
    param_dtype: jnp.dtype = jnp.float32
    scale_init: nn.initializers.Initializer = nn.initializers.ones_init()

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", self.scale_init, (x.shape[-1],),
                           self.param_dtype)
        return rmsnorm(x, scale, self.epsilon)


class FusedLayerNorm(nn.Module):
    """nn.LayerNorm drop-in (params "scale"/"bias", fp32 math/output)."""

    epsilon: float = 1e-6
    param_dtype: jnp.dtype = jnp.float32
    scale_init: nn.initializers.Initializer = nn.initializers.ones_init()
    bias_init: nn.initializers.Initializer = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", self.scale_init, (x.shape[-1],),
                           self.param_dtype)
        bias = self.param("bias", self.bias_init, (x.shape[-1],),
                          self.param_dtype)
        return layernorm(x, scale, bias, self.epsilon)
