"""Chunked (memory-fused) LM-head cross-entropy.

The naive head materializes fp32 logits ``[batch, seq, vocab]`` — for
GPT-2-small at batch 16×1024 that is 3.2 GB written to and re-read from HBM
per step, and the head (~31% of model FLOPs) runs at a fraction of MXU rate
because it is bandwidth-bound. Measured on one v5e chip (fwd+bwd of the
head alone, N=16384 tokens): 47 TFLOP/s naive → 123 TFLOP/s chunked.

The fix is the standard one (Megatron's fused CE; also the
"cut-cross-entropy" family): compute logits one row-chunk at a time inside
a `lax.scan`, reduce each chunk to its per-token loss immediately, and
`jax.checkpoint` the chunk so the backward rebuilds its logits instead of
storing them. Peak logits memory drops from N×V to chunk×V and XLA keeps
the matmul compute-bound.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def chunked_softmax_ce(x, w, targets, *, chunk: int = 2048,
                       transpose_w: bool = True, dot_general=None):
    """Per-position cross-entropy of ``softmax(x @ w.T)`` against integer
    ``targets``, never materializing more than ``chunk`` rows of logits.

    Args:
      x: ``[..., embed]`` activations (any leading shape; flattened).
      w: ``[vocab, embed]`` (the tied embedding table; ``transpose_w=True``)
         or ``[embed, vocab]`` (an untied lm_head kernel).
      targets: integer array matching ``x``'s leading shape.
      chunk: target for rows of logits alive at once; the true peak is
        ``max(chunk, batch)`` — chunks are cut along seq only (see the
        sharding note), so a batch wider than ``chunk`` sets the floor.
        The seq axis is padded up to a chunk multiple (padded rows use
        target 0 and are dropped).
      dot_general: injectable contraction for the logit matmul (default
        ``lax.dot_general``); the int8 quantized-training path
        (ops/quant.py, TransformerConfig.quant) passes its drop-in here so
        the fused head's per-chunk logits ride the MXU's int8 rate too —
        accumulation stays fp32 out of the contraction, so the logsumexp
        numerics are unchanged in kind.

    Returns per-position CE with ``targets``'s shape, fp32.

    Sharding note (found by the r5 compiled-invariant census): chunks are
    cut along the SEQUENCE axis with the batch dimension kept whole and
    batched through the matmul. An earlier layout flattened [B, S, E] to
    [N, E] and sliced N — under a data-sharded batch each 2048-row chunk
    then crossed shard boundaries, and the SPMD partitioner quietly
    inserted per-step hidden-state all-gathers plus a grouped [V, E] grad
    all-reduce (visible in the llama1b_2l optimized HLO). Seq is
    unsharded under DP/FSDP, so slicing it is shard-local; with batch
    untouched the only collective left is the ordinary deferred grad
    psum. (Context-parallel configs shard seq too, but those run
    attention under shard_map and use the unfused loss.)
    """
    lead = x.shape[:-1]
    e = x.shape[-1]
    if len(lead) <= 1:
        # no batch axis to protect (head-only microbenches, single
        # positions): treat everything as seq under a unit batch
        x = x.reshape((1,) + lead + (e,))
        targets = targets.reshape((1,) + lead)
    b = x.shape[0]
    xs = x.reshape(b, -1, e)
    ts = targets.reshape(b, -1)
    s = xs.shape[1]
    # rows of logits alive per chunk: b * cs ≈ `chunk`. When b alone
    # exceeds `chunk` (huge-batch, short-seq), cs clamps to 1 and the
    # peak is b rows, not chunk — chunking the batch axis instead would
    # reintroduce the sharded-dim slicing this layout exists to avoid,
    # so the cap is documented as max(chunk, batch) rather than silently
    # re-sliced. (Still a V/s-fold saving over the dense head.)
    cs = max(1, min(chunk // max(b, 1), s))
    pad = (-s) % cs
    if pad:
        xs = jnp.concatenate(
            [xs, jnp.zeros((b, pad, e), xs.dtype)], axis=1)
        ts = jnp.concatenate(
            [ts, jnp.zeros((b, pad), ts.dtype)], axis=1)

    dims = ((2,), (1,)) if transpose_w else ((2,), (0,))
    dg = dot_general if dot_general is not None else jax.lax.dot_general

    @jax.checkpoint
    def one(xc, tc):
        # fp32 accumulation straight out of the MXU — strictly better
        # numerics than the unfused bf16-logits-then-cast path
        logits = dg(
            xc, w, (dims, ((), ())), preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        true = jnp.take_along_axis(logits, tc[:, :, None], axis=-1)[..., 0]
        return lse - true

    def body(_, args):
        return None, one(*args)

    # scan over seq-chunks: [b, k, cs, e] -> k x [b, cs, e]
    k = xs.shape[1] // cs
    _, ce = jax.lax.scan(
        body, None,
        (xs.reshape(b, k, cs, e).swapaxes(0, 1),
         ts.reshape(b, k, cs).swapaxes(0, 1)))
    ce = ce.swapaxes(0, 1).reshape(b, -1)
    if pad:
        ce = ce[:, :s]
    return ce.reshape(lead)
