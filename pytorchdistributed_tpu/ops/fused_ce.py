"""Chunked (memory-fused) LM-head cross-entropy.

The naive head materializes fp32 logits ``[batch, seq, vocab]`` — for
GPT-2-small at batch 16×1024 that is 3.2 GB written to and re-read from HBM
per step, and the head (~31% of model FLOPs) runs at a fraction of MXU rate
because it is bandwidth-bound. Measured on one v5e chip (fwd+bwd of the
head alone, N=16384 tokens): 47 TFLOP/s naive → 123 TFLOP/s chunked.

The fix is the standard one (Megatron's fused CE; also the
"cut-cross-entropy" family): compute logits one row-chunk at a time inside
a `lax.scan`, reduce each chunk to its per-token loss immediately, and
`jax.checkpoint` the chunk so the backward rebuilds its logits instead of
storing them. Peak logits memory drops from N×V to chunk×V and XLA keeps
the matmul compute-bound.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def chunked_softmax_ce(x, w, targets, *, chunk: int = 2048,
                       transpose_w: bool = True):
    """Per-position cross-entropy of ``softmax(x @ w.T)`` against integer
    ``targets``, never materializing more than ``chunk`` rows of logits.

    Args:
      x: ``[..., embed]`` activations (any leading shape; flattened).
      w: ``[vocab, embed]`` (the tied embedding table; ``transpose_w=True``)
         or ``[embed, vocab]`` (an untied lm_head kernel).
      targets: integer array matching ``x``'s leading shape.
      chunk: rows of logits alive at once. The flattened token count is
        padded up to a multiple (padded rows use target 0 and are dropped).

    Returns per-position CE with ``targets``'s shape, fp32.
    """
    lead = x.shape[:-1]
    e = x.shape[-1]
    xf = x.reshape(-1, e)
    tf = targets.reshape(-1)
    n = xf.shape[0]
    c = min(chunk, n)
    pad = (-n) % c
    if pad:
        xf = jnp.concatenate([xf, jnp.zeros((pad, e), xf.dtype)])
        tf = jnp.concatenate([tf, jnp.zeros((pad,), tf.dtype)])

    dims = ((1,), (1,)) if transpose_w else ((1,), (0,))

    @jax.checkpoint
    def one(xc, tc):
        # fp32 accumulation straight out of the MXU — strictly better
        # numerics than the unfused bf16-logits-then-cast path
        logits = jax.lax.dot_general(
            xc, w, (dims, ((), ())), preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        true = jnp.take_along_axis(logits, tc[:, None], axis=-1)[:, 0]
        return lse - true

    def body(_, args):
        return None, one(*args)

    _, ce = jax.lax.scan(
        body, None,
        (xf.reshape(-1, c, e), tf.reshape(-1, c)))
    ce = ce.reshape(-1)
    if pad:
        ce = ce[:n]
    return ce.reshape(lead)
