"""Flash attention as Pallas TPU kernels (SURVEY.md §5: "blockwise /
Flash-style Pallas attention kernel").

Forward: one fused kernel, grid (batch·heads, q_blocks, k_blocks). The
online-softmax accumulator (m, l, acc) lives in VMEM scratch and is carried
across the sequentially-executed k_blocks grid dimension; HBM traffic is one
read of each Q/K/V block and one write of each O block — the flash
recurrence. The per-row logsumexp (LSE = m + log l) is written out as a
second kernel output; it is the only softmax statistic the backward needs.

Backward: two fused Pallas kernels under `jax.custom_vjp`, the
FlashAttention-2 split:

  * dKV kernel, grid (batch·heads, k_blocks, q_blocks): for its K/V block,
    scans Q/dO blocks accumulating  dV = Pᵀ·dO  and  dK = dSᵀ·Q  in VMEM
    scratch, where  P = exp(S − LSE)  is recomputed from Q·Kᵀ (no S×S
    residual is ever stored) and  dS = P ∘ (dP − Δ)·scale  with
    dP = dO·Vᵀ and the precomputed row statistic Δ = rowsum(dO ∘ O);
  * dQ kernel, grid (batch·heads, q_blocks, k_blocks): same recompute,
    accumulating  dQ = dS·K  across K blocks.

Residuals are (Q, K, V, O, LSE) — O(s·d) memory, gradients numerically
identical to dense attention (tests/test_attention.py).

Causal blocks strictly above the diagonal are skipped in all three kernels
(their contribution is exactly zero). Padded Q/K tails (seq_len not
divisible by the block size) are masked. On non-TPU backends (the CPU test
sim) the kernels run in Pallas interpret mode automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.ad_checkpoint
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _out_sds(shape, dtype, like):
    """pallas_call out_shape typed after operand ``like``: under a
    check_vma=True shard_map (ring_attention_sharded / ulysses_attention
    compiled on hardware) every kernel output must declare its
    varying-manual-axes set, and the outputs vary exactly like the
    operands they are computed from. Outside a checked trace the aval
    carries an empty/absent vma and this is a plain ShapeDtypeStruct."""
    vma = getattr(jax.typeof(like), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)


def _fwd_kernel(q_ref, k_ref, v_ref, *refs,
                block_q: int, block_k: int, causal: bool, scale: float,
                num_k_blocks: int, seq_len: int, carry: bool = False):
    """Online-softmax forward, one definition for both attention paths.

    ``carry`` is static and selects the ref layout at trace time (no HBM
    zero-read is ever emitted for the carry=False flagship path):
      * False (single-chip flash): refs = (o_ref, lse_ref, acc_s, m_s, l_s)
        — (m, l, acc) init to zeros/-inf in VMEM and the last k-block
        normalizes into (o, lse);
      * True (one ring-attention hop, ops/ring_attention.py): refs =
        (m_in, l_in, acc_in, m_out, l_out, acc_out, acc_s, m_s, l_s) — the
        statistics enter and leave through HBM so they survive across ring
        steps, and normalization happens once after the last hop."""
    if carry:
        (m_in, l_in, acc_in, m_out, l_out, acc_out,
         acc_s, m_s, l_s) = refs
    else:
        o_ref, lse_ref, acc_s, m_s, l_s = refs
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        if carry:
            m_s[...] = m_in[0]
            l_s[...] = l_in[0]
            acc_s[...] = acc_in[0]
        else:
            acc_s[...] = jnp.zeros_like(acc_s)
            m_s[...] = jnp.full_like(m_s, _NEG_INF)
            l_s[...] = jnp.zeros_like(l_s)

    qi = pl.program_id(1)
    q_start = qi * block_q
    k_start = ki * block_k

    run = True
    if causal:
        # skip blocks strictly above the diagonal
        run = q_start + block_q - 1 >= k_start

    @pl.when(run)
    def _compute():
        # Dots stay in the input dtype (bf16 on the training path) with fp32
        # accumulation — upcasting operands first would push the matmul off
        # the MXU's fast path (fp32 matmul is ~4x slower on TPU). The scale
        # is applied to the fp32 logits, not the operands.
        q = q_ref[0]                                      # [bq, d]
        k = k_ref[0]                                      # [bk, d]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        # mask the padded K tail (seq_len not divisible by block_k) and,
        # for causal, positions above the diagonal
        k_pos = k_start + lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        valid = k_pos < seq_len
        if causal:
            q_pos = q_start + lax.broadcasted_iota(jnp.int32, logits.shape, 0)
            valid = valid & (q_pos >= k_pos)
        logits = jnp.where(valid, logits, _NEG_INF)
        m_prev = m_s[...]
        blk_max = jnp.max(logits, axis=-1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, blk_max)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.where(valid, jnp.exp(logits - m_new), 0.0)  # [bq, bk]
        l_s[...] = l_s[...] * corr + jnp.sum(p, -1, keepdims=True)
        m_s[...] = m_new
        # zero the padded V tail: p is 0 there, but 0·garbage(NaN) = NaN
        v = _zero_pad_rows(v_ref[0], k_start, seq_len)     # [bk, d]
        acc_s[...] = acc_s[...] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        if carry:
            m_out[0] = m_s[...]
            l_out[0] = l_s[...]
            acc_out[0] = acc_s[...]
        else:
            l = jnp.maximum(l_s[...], 1e-30)
            o_ref[0] = (acc_s[...] / l).astype(o_ref.dtype)
            lse_ref[0] = m_s[...] + jnp.log(l)    # [bq, 1]


def _flash_fwd(q, k, v, *, causal: bool, scale: float, block_q: int,
               block_k: int, interpret: bool):
    bh, s, d = q.shape
    # Grouped-query attention, kernel-native: k/v may carry fewer heads
    # (shape [B·H_kv, S, D]); each q-head program reads its group's shared
    # K/V block via the index map — the 4x-materialized jnp.repeat the
    # caller would otherwise need never hits HBM.
    group = bh // k.shape[0]
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    nq, nk = pl.cdiv(s, block_q), pl.cdiv(s, block_k)

    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale, num_k_blocks=nk, seq_len=s)

    return pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, qi, ki: (b // group, ki, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, qi, ki: (b // group, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            # row statistics ride as [bh, s, 1] with block (1, block_q, 1):
            # the trailing 1 equals the array dim, so the TPU tiling
            # constraint reduces to block_q % 8 == 0 — identical to the Q
            # block's own constraint (a rank-2 [bh, s] slice can't satisfy it)
            pl.BlockSpec((1, block_q, 1), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_shape=[
            _out_sds((bh, s, d), q.dtype, q),
            _out_sds((bh, s, 1), jnp.float32, q),
        ],
        scratch_shapes=[
            _vmem_scratch((block_q, d)),
            _vmem_scratch((block_q, 1)),
            _vmem_scratch((block_q, 1)),
        ],
        interpret=interpret,
    )(q, k, v)


def _vmem_scratch(shape):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.VMEM(shape, jnp.float32)


def _zero_pad_rows(x, start, seq_len):
    """Zero rows of a [rows, d] block that fall beyond seq_len: padded tail
    blocks load unspecified garbage (NaN in interpret mode), and a matmul
    against even a zeroed operand turns 0·NaN into NaN."""
    pos = start + lax.broadcasted_iota(jnp.int32, x.shape, 0)
    return jnp.where(pos < seq_len, x, jnp.zeros_like(x))


def _recompute_p_ds(q_blk, k_blk, v_blk, do_blk, lse_blk, delta_blk, *,
                    scale, causal, q_start, k_start, seq_len):
    """Shared bwd math: rebuild P = exp(S − LSE) for one (q, k) block pair
    and form dS = P ∘ (dO·Vᵀ − Δ)·scale. Blocks stay in their input dtype
    for the dots (MXU fast path); accumulation is fp32. lse_blk/delta_blk
    are [bq, 1] column statistics. Returns (p, ds), both [bq, bk] fp32,
    zero on masked (padded / acausal) positions."""
    s_blk = jax.lax.dot_general(
        q_blk, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale        # [bq, bk]
    shape = s_blk.shape
    q_pos = q_start + lax.broadcasted_iota(jnp.int32, shape, 0)
    k_pos = k_start + lax.broadcasted_iota(jnp.int32, shape, 1)
    valid = (q_pos < seq_len) & (k_pos < seq_len)
    if causal:
        valid = valid & (q_pos >= k_pos)
    p = jnp.where(valid, jnp.exp(s_blk - lse_blk), 0.0)    # lse: [bq, 1]
    dp = jax.lax.dot_general(
        do_blk, v_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                # [bq, bk]
    # where, not rely on p==0: on masked rows dp/Δ hold garbage from padded
    # tail blocks, and 0·NaN = NaN
    ds = jnp.where(valid, p * (dp - delta_blk) * scale, 0.0)
    return p, ds


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                    block_q: int, block_k: int, causal: bool, scale: float,
                    num_q_blocks: int, seq_len: int, group: int,
                    carry: bool = False):
    # grid (B·H_kv, k_blocks, group, q_blocks): for one (kv-head, K block)
    # the group's q-heads and their q blocks run CONSECUTIVELY, so the
    # VMEM accumulator legally carries dK/dV across all of them — the
    # grouped-query reduction happens inside the kernel instead of an XLA
    # sum over a 4x-repeated dk tensor.
    #
    # ``carry`` (static, see _fwd_kernel): False → refs = (dk_ref, dv_ref,
    # dk_acc, dv_acc), zero-init specialized at trace time (the flagship
    # path never reads zeros from HBM); True → refs = (dk_in, dv_in,
    # dk_ref, dv_ref, dk_acc, dv_acc), the ring's co-travelling dK/dV
    # accumulators entering/leaving through HBM each hop (group is 1
    # there — the ring path is not GQA-folded).
    if carry:
        dk_in, dv_in, dk_ref, dv_ref, dk_acc, dv_acc = refs
    else:
        dk_ref, dv_ref, dk_acc, dv_acc = refs
    gi, qi = pl.program_id(2), pl.program_id(3)

    @pl.when((qi == 0) & (gi == 0))
    def _init():
        if carry:
            dk_acc[...] = dk_in[0]
            dv_acc[...] = dv_in[0]
        else:
            dk_acc[...] = jnp.zeros_like(dk_acc)
            dv_acc[...] = jnp.zeros_like(dv_acc)

    ki = pl.program_id(1)
    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        # this K block only sees Q rows at or below the diagonal
        run = q_start + block_q - 1 >= k_start

    @pl.when(run)
    def _compute():
        q = _zero_pad_rows(q_ref[0], q_start, seq_len)
        k = _zero_pad_rows(k_ref[0], k_start, seq_len)
        v = _zero_pad_rows(v_ref[0], k_start, seq_len)
        do = _zero_pad_rows(do_ref[0], q_start, seq_len)
        p, ds = _recompute_p_ds(
            q, k, v, do, lse_ref[0], delta_ref[0], scale=scale,
            causal=causal, q_start=q_start, k_start=k_start, seq_len=seq_len)
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # pᵀ·dO [bk, d]
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # dsᵀ·q [bk, d]

    @pl.when((qi == num_q_blocks - 1) & (gi == group - 1))
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs,
                   block_q: int, block_k: int, causal: bool, scale: float,
                   num_k_blocks: int, seq_len: int, carry: bool = False):
    # ``carry`` (static, see _fwd_kernel): False → refs = (dq_ref, dq_acc),
    # zero-init at trace time; True → refs = (dq_in, dq_ref, dq_acc), the
    # ring hop's dQ accumulator entering through HBM.
    if carry:
        dq_in, dq_ref, dq_acc = refs
    else:
        dq_ref, dq_acc = refs
    qi, ki = pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_acc[...] = dq_in[0] if carry else jnp.zeros_like(dq_acc)

    q_start = qi * block_q
    k_start = ki * block_k
    run = True
    if causal:
        run = q_start + block_q - 1 >= k_start

    @pl.when(run)
    def _compute():
        q = _zero_pad_rows(q_ref[0], q_start, seq_len)
        k = _zero_pad_rows(k_ref[0], k_start, seq_len)
        v = _zero_pad_rows(v_ref[0], k_start, seq_len)
        do = _zero_pad_rows(do_ref[0], q_start, seq_len)
        _, ds = _recompute_p_ds(
            q, k, v, do, lse_ref[0], delta_ref[0], scale=scale,
            causal=causal, q_start=q_start, k_start=k_start, seq_len=seq_len)
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)            # ds·k [bq, d]

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, *, causal: bool, scale: float,
               block_q: int, block_k: int, interpret: bool):
    bh, s, d = q.shape
    group = bh // k.shape[0]  # grouped-query: see _flash_fwd
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    nq, nk = pl.cdiv(s, block_q), pl.cdiv(s, block_k)

    # Δ_i = dOᵢ·Oᵢ — tiny elementwise reduce; XLA fuses it into the
    # surrounding graph, no reason to burn a kernel launch on it. Shaped
    # [bh, s, 1] to match the LSE layout (see _flash_fwd out_specs).
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)

    q_spec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0))
    row_spec = pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0))

    # dKV: grid (b·kv_heads, k_blocks, group, q_blocks) — the group and q
    # dims run sequentially innermost so dK/dV accumulate across the whole
    # q-head group (see _bwd_dkv_kernel).
    def qmap(bkv, ki, gi, qi):
        return (bkv * group + gi, qi, 0)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale, num_q_blocks=nq, seq_len=s, group=group)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh // group, nk, group, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), qmap),
            pl.BlockSpec((1, block_k, d),
                         lambda bkv, ki, gi, qi: (bkv, ki, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bkv, ki, gi, qi: (bkv, ki, 0)),
            pl.BlockSpec((1, block_q, d), qmap),
            pl.BlockSpec((1, block_q, 1), qmap),
            pl.BlockSpec((1, block_q, 1), qmap),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d),
                         lambda bkv, ki, gi, qi: (bkv, ki, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda bkv, ki, gi, qi: (bkv, ki, 0)),
        ],
        out_shape=[
            _out_sds(k.shape, k.dtype, k),
            _out_sds(v.shape, v.dtype, v),
        ],
        scratch_shapes=[
            _vmem_scratch((block_k, d)),
            _vmem_scratch((block_k, d)),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    # dQ: grid (bh, q_blocks, k_blocks) — k is the sequential inner dim.
    dq_kernel = functools.partial(
        _bwd_dq_kernel, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale, num_k_blocks=nk, seq_len=s)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=[
            q_spec,
            pl.BlockSpec((1, block_k, d),
                         lambda b, qi, ki: (b // group, ki, 0)),
            pl.BlockSpec((1, block_k, d),
                         lambda b, qi, ki: (b // group, ki, 0)),
            q_spec,
            row_spec,
            row_spec,
        ],
        out_specs=q_spec,
        out_shape=_out_sds(q.shape, q.dtype, q),
        scratch_shapes=[_vmem_scratch((block_q, d))],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    out, _ = _flash_fwd(q, k, v, causal=causal, scale=scale, block_q=block_q,
                        block_k=block_k, interpret=interpret)
    return out


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out, lse = _flash_fwd(q, k, v, causal=causal, scale=scale,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret)
    # Named so remat policies can keep the kernel's residuals: without
    # these, `jax.checkpoint` re-runs the forward kernel during backward
    # just to regenerate (out, lse) — a full extra attention pass per layer
    # (models/transformer.py checkpoint_policy saves both names).
    out = jax.ad_checkpoint.checkpoint_name(out, "attn_out")
    lse = jax.ad_checkpoint.checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, g, causal=causal, scale=scale,
                      block_q=block_q, block_k=block_k, interpret=interpret)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: float | None = None, block_q: int = 1024,
                    block_k: int = 1024, interpret: bool | None = None):
    """[B, S, H, D] fused flash attention; drop-in for dense_attention.

    Default block 1024 (measured, v5e, S=1024 D=64 BH=256): 0.75 ms/call
    vs 1.92 at block 512 — fewer, fatter grid programs beat the 25% causal
    block-skip at this scale; VMEM per program stays ~1.5 MB even at
    D=128. For much longer sequences the 1024 grid still tiles and skips
    acausal blocks.

    Grouped-query attention is kernel-native: k/v may carry fewer heads
    than q (num_heads divisible by kv_heads); each q-head program streams
    its group's shared K/V blocks via the index maps, so the repeated K/V
    never materializes in HBM and the grouped dK/dV reduction happens in
    the kernel accumulator."""
    b, s, h, d = q.shape
    hk = k.shape[2]
    if h % hk:
        raise ValueError(f"q heads {h} not divisible by kv heads {hk}")
    scale = (d**-0.5) if scale is None else scale
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def fold(t):  # [B,S,Hx,D] -> [B*Hx, S, D]
        return t.transpose(0, 2, 1, 3).reshape(-1, s, d)

    out = _flash(fold(q), fold(k), fold(v), causal, scale, block_q, block_k,
                 interpret)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


# ---------------------------------------------------------------------------
# Paged decode attention (ISSUE 7): the Pallas twin of
# ops/attention.paged_attention. One decode tick's q ([slots, heads, d])
# attends each slot's block-table-mapped KV blocks streamed STRAIGHT from
# the shared pool — the [slots, blocks*block_size, ...] gathered copy the
# reference path materializes in HBM never exists here. The block table
# and per-slot lengths ride as scalar-prefetch operands so the KV
# BlockSpec index maps can chase the table (pool block `tables[slot, j]`
# is DMA'd as grid step j), the canonical PagedAttention dataflow.


def _paged_kernel(tables_ref, lengths_ref, q_ref, k_ref, v_ref, *rest,
                  block_size: int, num_blocks: int, kv_heads: int,
                  scale: float, quantized: bool, sink: int, window: int):
    """Online-softmax over one slot's table blocks; grid
    (slots·kv_heads, blocks_per_slot), rows = the kv head's q group.
    ``quantized`` adds two scale refs (int8 pool, fp32 per-row scales,
    dequantized in VMEM right before the dots); ``window`` > 0 applies
    the sink+sliding-window mask and skips fully-dead middle blocks —
    the blocks the serving engine retires to the allocator."""
    if quantized:
        ks_ref, vs_ref, o_ref, acc_s, m_s, l_s = rest
    else:
        ks_ref = vs_ref = None
        o_ref, acc_s, m_s, l_s = rest
    b, ji = pl.program_id(0), pl.program_id(1)

    @pl.when(ji == 0)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)
        m_s[...] = jnp.full_like(m_s, _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)

    length = lengths_ref[b // kv_heads]
    # skip blocks wholly past the slot's live window (the current token
    # sits at position `length`, so positions <= length are attendable);
    # dead slots (length 0) still run block 0 — masked rows are exact
    # zeros, the same garbage-tolerance contract as the reference path
    run = ji * block_size <= length
    if window:
        # sliding window: a middle block whose last position already fell
        # out of every live query's window (and past the sinks) is fully
        # masked — and its table entry points at trash once the engine
        # retires it — so skip its DMA outright
        dead = ((ji * block_size >= sink)
                & ((ji + 1) * block_size <= length - window + 1))
        run = run & ~dead

    @pl.when(run)
    def _compute():
        q = q_ref[0]                                       # [group, d]
        k = k_ref[0, :, 0]                                 # [bs, d]
        if quantized:
            # canonical dequant (ops/quant.kv_dequantize spelling):
            # int8 → fp32 × per-row scale → compute dtype
            k = (k.astype(jnp.float32)
                 * ks_ref[0, :, 0][:, None]).astype(q.dtype)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale    # [group, bs]
        pos = ji * block_size + lax.broadcasted_iota(
            jnp.int32, logits.shape, 1)
        valid = pos <= length
        if window:
            valid &= (pos < sink) | (pos > length - window)
        logits = jnp.where(valid, logits, _NEG_INF)
        m_prev = m_s[...]
        m_new = jnp.maximum(m_prev, jnp.max(logits, -1, keepdims=True))
        corr = jnp.exp(m_prev - m_new)
        p = jnp.where(valid, jnp.exp(logits - m_new), 0.0)
        l_s[...] = l_s[...] * corr + jnp.sum(p, -1, keepdims=True)
        m_s[...] = m_new
        v = v_ref[0, :, 0]                                 # [bs, d]
        if quantized:
            v = (v.astype(jnp.float32)
                 * vs_ref[0, :, 0][:, None]).astype(q_ref.dtype)
        acc_s[...] = acc_s[...] * corr + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ji == num_blocks - 1)
    def _finalize():
        o_ref[0] = (acc_s[...]
                    / jnp.maximum(l_s[...], 1e-30)).astype(o_ref.dtype)


def paged_flash_attention(q, k_pool, v_pool, block_tables, lengths, *,
                          k_scale=None, v_scale=None,
                          sink_tokens: int = 0, window_tokens: int = 0,
                          scale: float | None = None,
                          interpret: bool | None = None):
    """One decode tick of paged attention, pool-native — the serving
    engine's default decode hot path (ISSUE 13; gather fallback via
    ``ServingEngine(paged_attn=...)`` / ``PTD_PAGED_ATTN``).

    Args:
      q: ``[slots, heads, head_dim]`` — each slot's single current-token
        query (its K/V already written into the pool, the decode
        contract).
      k_pool / v_pool: ``[num_blocks, block_size, kv_heads, head_dim]``,
        the model dtype or int8 (compressed pool).
      block_tables: ``[slots, blocks_per_slot]`` int32 physical block ids
        (entries past a slot's live length — and retired window blocks —
        point at the trash block 0).
      lengths: ``[slots]`` int32 — the query attends positions <= length.
      k_scale / v_scale: ``[num_blocks, block_size, kv_heads]`` fp32
        per-(token, head) dequant scales; required iff the pool is int8.
      sink_tokens / window_tokens: static sink+sliding-window mask
        (window_tokens 0 = full attention): position j is attendable iff
        ``j < sink_tokens or j > length - window_tokens``; fully-dead
        middle blocks are skipped (no DMA) — they are the blocks the
        engine retires back to the allocator mid-stream.

    Returns ``[slots, heads, head_dim]``. Matches
    ops.attention.paged_attention to fp32 online-softmax tolerance (the
    reassociated flash recurrence is not bitwise — the bitwise-parity
    contract vs generate() holds on the reference gather path; this
    kernel never materializes the [slots, blocks*block_size, ...]
    gathered copy, the HBM-traffic-optimal hot path). Grouped-query
    native: each (slot, kv_head) program streams its group's shared KV
    block once. On TPU the group width (heads/kv_heads) rides the
    sublane dim — pad q to a multiple of 8 rows for compiled-mode
    tiling; interpret mode (the CPU sim) has no such constraint."""
    slots, h, d = q.shape
    nb, bs, hk, _ = k_pool.shape
    if h % hk:
        raise ValueError(f"q heads {h} not divisible by kv heads {hk}")
    quantized = k_pool.dtype == jnp.int8
    if quantized != (k_scale is not None and v_scale is not None):
        raise ValueError(
            "k_scale/v_scale must be provided iff the pool is int8 "
            f"(pool {k_pool.dtype}, k_scale "
            f"{'set' if k_scale is not None else 'None'})")
    if quantized and (k_scale.shape != (nb, bs, hk)
                      or v_scale.shape != (nb, bs, hk)):
        raise ValueError(
            f"scale planes must be [num_blocks, block_size, kv_heads] = "
            f"{(nb, bs, hk)}; got {k_scale.shape} / {v_scale.shape}")
    if window_tokens < 0 or sink_tokens < 0 or (
            window_tokens and (window_tokens % bs or sink_tokens % bs)):
        raise ValueError(
            f"sink_tokens {sink_tokens} / window_tokens {window_tokens} "
            f"must be non-negative multiples of block_size {bs}")
    group = h // hk
    mb = block_tables.shape[1]
    scale = (d**-0.5) if scale is None else scale
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    from jax.experimental.pallas import tpu as pltpu

    qf = q.reshape(slots * hk, group, d)  # kv head g owns q rows g·group+
    kv_spec = pl.BlockSpec((1, bs, 1, d),
                           lambda b, j, tbl, ln: (tbl[b // hk, j], 0,
                                                  b % hk, 0))
    in_specs = [
        pl.BlockSpec((1, group, d), lambda b, j, tbl, ln: (b, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    operands = [qf, k_pool, v_pool]
    if quantized:
        scale_spec = pl.BlockSpec(
            (1, bs, 1), lambda b, j, tbl, ln: (tbl[b // hk, j], 0, b % hk))
        in_specs += [scale_spec, scale_spec]
        operands += [k_scale.astype(jnp.float32),
                     v_scale.astype(jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(slots * hk, mb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, group, d),
                               lambda b, j, tbl, ln: (b, 0, 0)),
        scratch_shapes=[
            _vmem_scratch((group, d)),
            _vmem_scratch((group, 1)),
            _vmem_scratch((group, 1)),
        ],
    )
    kernel = functools.partial(
        _paged_kernel, block_size=bs, num_blocks=mb, kv_heads=hk,
        scale=scale, quantized=quantized, sink=int(sink_tokens),
        window=int(window_tokens))
    out_dtype = q.dtype
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((slots * hk, group, d), out_dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), lengths.astype(jnp.int32),
      *operands)
    return out.reshape(slots, h, d)
