"""Flash attention as a Pallas TPU kernel (SURVEY.md §5: "blockwise /
Flash-style Pallas attention kernel").

Forward: one fused kernel, grid (batch·heads, q_blocks, k_blocks). The
online-softmax accumulator (m, l, acc) lives in VMEM scratch and is carried
across the sequentially-executed k_blocks grid dimension; HBM traffic is one
read of each Q/K/V block and one write of each O block — the flash
recurrence. Causal blocks strictly above the diagonal are masked (their
contribution is exactly zero).

Backward: `jax.custom_vjp` whose bwd recomputes attention blockwise in plain
JAX (a `lax.scan` flash recurrence XLA fuses well) and differentiates that —
activation-recompute semantics (no S×S residuals stored), numerically
identical gradients.

On non-TPU backends (the CPU test sim) the kernel runs in Pallas interpret
mode automatically.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *,
                block_q: int, block_k: int, causal: bool, scale: float,
                num_k_blocks: int, seq_len: int):
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    qi = pl.program_id(1)
    q_start = qi * block_q
    k_start = ki * block_k

    run = True
    if causal:
        # skip blocks strictly above the diagonal
        run = q_start + block_q - 1 >= k_start

    @pl.when(run)
    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale          # [bq, d]
        k = k_ref[0].astype(jnp.float32)                  # [bk, d]
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # [bq, bk]
        # mask the padded K tail (seq_len not divisible by block_k) and,
        # for causal, positions above the diagonal
        k_pos = k_start + lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        valid = k_pos < seq_len
        if causal:
            q_pos = q_start + lax.broadcasted_iota(jnp.int32, logits.shape, 0)
            valid = valid & (q_pos >= k_pos)
        logits = jnp.where(valid, logits, _NEG_INF)
        m_prev = m_ref[...]
        blk_max = jnp.max(logits, axis=-1, keepdims=True)  # [bq, 1]
        m_new = jnp.maximum(m_prev, blk_max)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.where(valid, jnp.exp(logits - m_new), 0.0)  # [bq, bk]
        l_ref[...] = l_ref[...] * corr + jnp.sum(p, -1, keepdims=True)
        m_ref[...] = m_new
        v = v_ref[0].astype(jnp.float32)                   # [bk, d]
        # zero the padded V tail: p is 0 there, but 0·garbage(NaN) = NaN
        v_pos = k_start + lax.broadcasted_iota(jnp.int32, v.shape, 0)
        v = jnp.where(v_pos < seq_len, v, 0.0)
        acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] /
                    jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, *, causal: bool, scale: float, block_q: int,
               block_k: int, interpret: bool):
    bh, s, d = q.shape
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    nq, nk = pl.cdiv(s, block_q), pl.cdiv(s, block_k)

    kernel = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, causal=causal,
        scale=scale, num_k_blocks=nk, seq_len=s)

    return pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        scratch_shapes=[
            _vmem_scratch((block_q, d)),
            _vmem_scratch((block_q, 1)),
            _vmem_scratch((block_q, 1)),
        ],
        interpret=interpret,
    )(q, k, v)


def _vmem_scratch(shape):
    from jax.experimental.pallas import tpu as pltpu
    return pltpu.VMEM(shape, jnp.float32)


def _blockwise_reference(q, k, v, *, causal: bool, scale: float,
                         block_k: int = 512):
    """Flash recurrence in plain JAX ([bh, s, d] layout) — the recompute
    target the custom bwd differentiates; O(s·block_k) memory via lax.scan."""
    bh, s, d = q.shape
    block_k = min(block_k, s)
    nk = s // block_k if s % block_k == 0 else -(-s // block_k)
    pad = nk * block_k - s
    kp = jnp.pad(k, ((0, 0), (0, pad), (0, 0)))
    vp = jnp.pad(v, ((0, 0), (0, pad), (0, 0)))
    q32 = q.astype(jnp.float32) * scale
    q_pos = jnp.arange(s)

    def step(carry, i):
        o, m, l = carry
        k_blk = lax.dynamic_slice_in_dim(kp, i * block_k, block_k, 1)
        v_blk = lax.dynamic_slice_in_dim(vp, i * block_k, block_k, 1)
        logits = jnp.einsum("bqd,bkd->bqk", q32, k_blk.astype(jnp.float32))
        k_pos = i * block_k + jnp.arange(block_k)
        valid = k_pos < s
        if causal:
            valid = valid[None, :] & (q_pos[:, None] >= k_pos[None, :])
        else:
            valid = jnp.broadcast_to(valid[None, :], (s, block_k))
        logits = jnp.where(valid[None], logits, _NEG_INF)
        blk_max = jnp.max(logits, -1)
        m_new = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - m_new)
        p = jnp.where(valid[None], jnp.exp(logits - m_new[..., None]), 0.0)
        l_new = l * corr + p.sum(-1)
        o_new = o * corr[..., None] + jnp.einsum(
            "bqk,bkd->bqd", p, v_blk.astype(jnp.float32))
        return (o_new, m_new, l_new), None

    o0 = jnp.zeros((bh, s, d), jnp.float32)
    m0 = jnp.full((bh, s), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bh, s), jnp.float32)
    (o, m, l), _ = lax.scan(step, (o0, m0, l0), jnp.arange(nk))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, causal, scale, block_q, block_k, interpret):
    return _flash_fwd(q, k, v, causal=causal, scale=scale, block_q=block_q,
                      block_k=block_k, interpret=interpret)


def _flash_vjp_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    out = _flash(q, k, v, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q, k, v: _blockwise_reference(q, k, v, causal=causal,
                                             scale=scale, block_k=block_k),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    scale: float | None = None, block_q: int = 512,
                    block_k: int = 512, interpret: bool | None = None):
    """[B, S, H, D] fused flash attention; drop-in for dense_attention."""
    b, s, h, d = q.shape
    scale = (d**-0.5) if scale is None else scale
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    def fold(t):  # [B,S,H,D] -> [B*H, S, D]
        return t.transpose(0, 2, 1, 3).reshape(b * h, s, d)

    out = _flash(fold(q), fold(k), fold(v), causal, scale, block_q, block_k,
                 interpret)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)
