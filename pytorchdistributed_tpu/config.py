"""Config / flag system (SURVEY.md §5: "dataclass configs + CLI overrides;
a --backend/mesh flag selecting {cpu-sim, single-TPU, pod}" — the north
star's "entrypoints select the TPU backend via a flag").

The reference's whole config surface is two argparse flags
(--max_epochs/--batch_size, ddp_gpus.py:88-92) with topology implied by
`torch.cuda.device_count()`. Here one dataclass covers model choice,
parallelism axes, precision and training hyperparameters; any field is
overridable from the CLI (`--field value`), and `PRESETS` carries the five
BASELINE.json benchmark configs.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from typing import Any


@dataclasses.dataclass
class ExperimentConfig:
    # model
    model: str = "gpt2"            # gpt2 | llama | bert | vit | resnet18 | resnet50 | mlp
    model_size: str = "test"       # per-family size preset
    attention: str = "dense"       # dense | pallas | ring | ulysses
    remat: bool = False
    fused_norms: bool = False      # custom_vjp norm backward (opt-in until
    #                                the chip A/B lands — BASELINE.md r4)
    # parallelism (mesh axis sizes; -1 = absorb remaining devices)
    strategy: str = "dp"           # dp | fsdp | tp | tp_fsdp | auto
    device_memory_gb: float = 0.0  # per-chip HBM for --strategy auto
                                   # (0 = query the device, v5e fallback)
    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    pipe: int = 1
    seq: int = 1
    num_slices: int = 1
    pipeline_microbatches: int = 1
    # Gradient accumulation: split each global batch into this many
    # micro-batches inside the jitted step (fp32 grad sum, one optimizer
    # update) — the large-batch recipe when activations exceed HBM.
    accum_steps: int = 1
    mlm_mask_rate: float = 0.15    # BERT dynamic-masking rate
    dropout_rate: float = 0.0      # transformer-family dropout (training
    #                                only; losses wire the rng stream)
    pp_schedule: str = "gpipe"     # gpipe | 1f1b (transformer models)
    expert: int = 1                # mesh axis for expert parallelism
    moe_experts: int = 0           # >0: Switch-MoE MLPs (transformer models)
    moe_capacity_factor: float = 1.25  # expert slot headroom over the
    #                                    uniform-routing load (GShard's cf)
    moe_top_k: int = 1             # routed experts per token (1 = Switch,
    #                                2 = GShard top-2 with gate renorm)
    moe_every: int = 1             # MoE block cadence: every Nth block is
    #                                MoE, others dense (needs unrolled
    #                                layers when > 1)
    moe_chunks: int = 1            # capacity chunks for dispatch/combine
    #                                a2a <-> expert-matmul overlap (>1
    #                                pipelines the exchange)
    # precision
    bf16: bool = True
    # Int8 quantized-training matmuls (ops/quant.py, the amp→bf16→int8
    # axis): "int8_fwd" quantizes forward weight matmuls (bf16 backward,
    # the safe default for the MXU's ~2x int8 rate), "int8" also
    # quantizes the backward with stochastic rounding on the gradient.
    # Applies to the transformer families' QKV/out/MLP/LM-head (and
    # fused-CE) contractions plus the MLP toy; implies bf16 compute.
    quant: str = "none"            # none | int8_fwd | int8
    # Collective-latency hiding (ops/overlap.py + trainer scheduler
    # flags): "xla" = monolithic collectives + XLA latency-hiding
    # scheduler (default), "ring" = decomposed collective-matmul rings on
    # the TP projections too, "off" = neither (the measured baseline).
    overlap: str = "xla"           # ring | xla | off
    # training
    max_epochs: int = 1
    batch_size: int = 32           # per-process
    learning_rate: float = 1e-3
    optimizer: str = "adamw"       # adamw | sgd | adafactor
    weight_decay: float = 0.01     # adamw decay, masked to ndim>=2 params
    # LR schedule: peak = learning_rate, linear warmup over warmup_steps,
    # then constant / cosine / linear decay to lr_end over decay_steps.
    lr_schedule: str = "constant"  # constant | cosine | linear
    warmup_steps: int = 0
    decay_steps: int = 10_000      # decay horizon (cosine/linear)
    lr_end: float = 0.0
    grad_clip_norm: float = 0.0    # clip_by_global_norm; 0 = off
    seed: int = 0
    # data: real on-disk datasets when data_dir is set and populated
    # (CIFAR-10 pickle batches or {split}_images/labels.npy pairs —
    # data/files.py); synthetic fallback otherwise
    data_dir: str = ""
    dataset_size: int = 2048       # synthetic dataset size
    seq_len: int = 128
    image_size: int = 32
    num_classes: int = 10
    # infra
    backend: str = "auto"          # auto | tpu | cpu-sim<N>
    checkpoint_dir: str = ""
    checkpoint_every_steps: int = 0
    resume: bool = False
    log_every: int = 10
    profile_dir: str = ""          # capture a jax.profiler trace here
    metrics_file: str = ""         # rank-0 JSONL per-step metrics sink
    watchdog: bool = True          # NaN/Inf watchdog at log cadence
    # In-graph training diagnostics (telemetry/diagnostics.py):
    # "off" | "scalars" | "full[:N]" — per-layer activation/grad health,
    # NaN provenance, int8 saturation, all as extra jitted outputs of
    # the same compiled step. Empty = unset, so the PTD_DIAGNOSTICS env
    # contract (run.py workers) still applies; any explicit value wins.
    diagnostics: str = ""
    # Speculative decoding for the serving path (serving/engine.py,
    # ISSUE 8): spec_k > 0 makes every decode tick draft-and-verify that
    # many tokens per target forward (lossless rejection sampling —
    # greedy output bitwise-equal, sampled distribution-equal).
    # draft_layers > 0 builds the draft by truncating the served model
    # to its first N layers (inference.truncated_draft); 0 self-drafts
    # with the full model. Serving-only knobs: training ignores them
    # (examples/serve.py --spec-k/--draft-layers and bench.py
    # PTD_SERVE_SPEC/PTD_SPEC_K consume the same pair).
    spec_k: int = 0
    draft_layers: int = 0


# The five BASELINE.json benchmark configs, smallest to largest.
PRESETS: dict[str, dict[str, Any]] = {
    # configs[0]: ResNet-18 / CIFAR-10 CPU smoke (the "gloo smoke" analog)
    "resnet18_cifar_smoke": dict(
        model="resnet18", backend="cpu-sim8", image_size=32, num_classes=10,
        strategy="dp", batch_size=32, bf16=False),
    # configs[1]: ResNet-50 / ImageNet multi-process DP
    "resnet50_imagenet_dp": dict(
        model="resnet50", image_size=224, num_classes=1000, strategy="dp",
        batch_size=64),
    # configs[2]: BERT-base MLM, bf16 (warmup+linear decay, the BERT recipe)
    "bert_base_mlm": dict(
        model="bert", model_size="base", seq_len=512, strategy="dp",
        batch_size=16, bf16=True, learning_rate=1e-4, lr_schedule="linear",
        warmup_steps=1000, decay_steps=100_000, grad_clip_norm=1.0),
    # configs[3]: GPT-2-medium FSDP + activation checkpointing
    # (warmup-cosine + clipping, the GPT recipe)
    "gpt2_medium_fsdp": dict(
        model="gpt2", model_size="medium", seq_len=1024, strategy="fsdp",
        data=1, fsdp=-1, remat=True, batch_size=8, learning_rate=3e-4,
        lr_schedule="cosine", warmup_steps=500, decay_steps=50_000,
        grad_clip_norm=1.0),
    # configs[4]: ViT-L/16 multi-host DP across pod slices
    "vit_l16_multihost": dict(
        model="vit", model_size="large", image_size=224, num_classes=1000,
        strategy="dp", num_slices=2, batch_size=32),
}


def select_backend(backend: str) -> None:
    """Apply the --backend flag. MUST run before the first JAX backend
    initialization (any jax.devices() call)."""
    import re

    if backend == "auto":
        return
    if backend == "tpu":
        os.environ.pop("JAX_PLATFORMS", None)
        try:
            import jax
            # jax may already be imported with a platform baked into its
            # config (the package __init__ re-asserts env) — reset to
            # autodetect, which picks the TPU plugin when present
            jax.config.update("jax_platforms", None)
        except ImportError:
            pass
        return
    if backend.startswith("cpu-sim"):
        n = int(backend[len("cpu-sim"):] or "8")
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        # replace (not keep) any pre-existing count: the explicit backend
        # request wins over inherited env
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       flags)
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}".strip())
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except ImportError:
            pass
        return
    raise ValueError(f"unknown backend {backend!r} "
                     "(use auto | tpu | cpu-sim<N>)")


def parse_cli(argv=None) -> ExperimentConfig:
    """Every dataclass field becomes a --flag; --preset applies a BASELINE
    config first, explicit flags override it."""
    parser = argparse.ArgumentParser(description="tpu-distributed training")
    parser.add_argument("--preset", choices=sorted(PRESETS), default=None)
    for f in dataclasses.fields(ExperimentConfig):
        if f.type == "bool":
            parser.add_argument(f"--{f.name}", type=lambda s: s.lower() in
                                ("1", "true", "yes"), default=None,
                                metavar="BOOL")
        else:
            parser.add_argument(f"--{f.name}",
                                type=type(f.default), default=None)
    ns = parser.parse_args(argv)
    values: dict[str, Any] = {}
    if ns.preset:
        values.update(PRESETS[ns.preset])
    for f in dataclasses.fields(ExperimentConfig):
        v = getattr(ns, f.name)
        if v is not None:
            values[f.name] = v
    return ExperimentConfig(**values)


def _build_model(cfg: ExperimentConfig):
    """(model, loss_fn, dataset) for a config — separated from `build` so
    the auto-placement path can re-instantiate the model after the planner
    picks a pipeline split."""
    import jax.numpy as jnp

    from pytorchdistributed_tpu import models
    from pytorchdistributed_tpu.data import (
        SyntheticImageDataset,
        SyntheticRegressionDataset,
        SyntheticTokenDataset,
    )
    from pytorchdistributed_tpu.training import (
        cross_entropy_loss,
        moe_token_cross_entropy_loss,
        mse_loss,
        token_cross_entropy_loss,
    )

    if cfg.moe_experts > 0:
        token_cross_entropy_loss = moe_token_cross_entropy_loss

    if cfg.quant not in ("none", "int8_fwd", "int8"):
        raise ValueError(f"unknown --quant {cfg.quant!r} "
                         "(none | int8_fwd | int8)")
    # quantized matmuls ride the bf16 compute dtype (the int8 path
    # rescales through fp32 either way; fp32 "compute" would only slow
    # the non-matmul remainder)
    dtype = jnp.bfloat16 if (cfg.bf16 or cfg.quant != "none") else jnp.float32
    from pytorchdistributed_tpu.parallel.overlap import validate_overlap

    validate_overlap(cfg.overlap)
    tkw = dict(attention=cfg.attention, remat=cfg.remat, dtype=dtype,
               quant=cfg.quant, overlap=cfg.overlap,
               fused_norms=cfg.fused_norms,
               pipeline_stages=cfg.pipe if cfg.pipe > 1 else 1,
               pipeline_microbatches=cfg.pipeline_microbatches,
               pp_schedule=cfg.pp_schedule, moe_experts=cfg.moe_experts,
               dropout_rate=cfg.dropout_rate)
    if cfg.moe_experts > 0:
        tkw.update(moe_capacity_factor=cfg.moe_capacity_factor,
                   moe_top_k=cfg.moe_top_k, moe_every=cfg.moe_every,
                   moe_chunks=cfg.moe_chunks,
                   # interleaving picks blocks by index — needs the
                   # unrolled stack (transformer.py __post_init__ errors
                   # on scan_layers + moe_every > 1)
                   **(dict(scan_layers=False) if cfg.moe_every > 1
                      else {}))

    lm_families = {
        "gpt2": (models.GPT2, models.gpt2_config),
        "llama": (models.Llama, models.llama_config),
        "bert": (models.BertMLM, models.bert_config),
    }
    if cfg.model in lm_families:
        cls, make_cfg = lm_families[cfg.model]
        model = cls(make_cfg(cfg.model_size, max_seq_len=cfg.seq_len, **tkw))
        loss = token_cross_entropy_loss
        data_vocab = model.cfg.vocab_size - (cfg.model == "bert")
        ds = _token_dataset(cfg, data_vocab)
        if cfg.model == "bert":
            # BERT trains the masked-LM objective, not next-token: wrap the
            # corpus in dynamic 80/10/10 masking (data/datasets.MLMDataset).
            # The top vocab id is RESERVED as [MASK]: the corpus (synthetic
            # or --data_dir) is held to ids < vocab-1 so mask positions are
            # unambiguous.
            from pytorchdistributed_tpu.data import MLMDataset

            ds = MLMDataset(ds, model.cfg.vocab_size,
                            mask_rate=cfg.mlm_mask_rate, seed=cfg.seed)
    elif cfg.model == "vit":
        model = models.ViT(models.vit_config(
            cfg.model_size, image_size=cfg.image_size,
            num_classes=cfg.num_classes, **tkw))
        loss = cross_entropy_loss
        ds = _image_dataset(cfg)
    elif cfg.model in ("resnet18", "resnet50"):
        maker = models.resnet18 if cfg.model == "resnet18" else models.resnet50
        model = maker(num_classes=cfg.num_classes, dtype=dtype,
                      **(dict(cifar_stem=True) if cfg.model == "resnet18"
                         and cfg.image_size <= 64 else {}))
        loss = cross_entropy_loss
        ds = _image_dataset(cfg)
    elif cfg.model == "mlp":
        from pytorchdistributed_tpu.ops.quant import dot_general_for

        model = models.MLP(dot_general=dot_general_for(cfg.quant))
        loss = mse_loss
        ds = SyntheticRegressionDataset(cfg.dataset_size, seed=cfg.seed)
    else:
        raise ValueError(f"unknown model {cfg.model!r}")
    return model, loss, ds


def _image_dataset(cfg: ExperimentConfig):
    """Real on-disk data when --data_dir points at a populated directory
    (CIFAR-10 pickle batches, or the {split}_images/labels.npy convention
    for ImageNet-class sets), synthetic fallback otherwise — the BASELINE
    img/s configs measure the real input pipeline when data is present."""
    from pytorchdistributed_tpu.data import SyntheticImageDataset
    from pytorchdistributed_tpu.data.files import load_cifar10, load_image_dir

    if cfg.data_dir:
        ds = (load_cifar10(cfg.data_dir) if cfg.image_size <= 32
              else load_image_dir(cfg.data_dir))
        if ds is None:
            ds = load_image_dir(cfg.data_dir) or load_cifar10(cfg.data_dir)
        if ds is not None:
            if ds.num_classes != cfg.num_classes:
                raise ValueError(
                    f"--data_dir dataset has {ds.num_classes} classes but "
                    f"the config expects {cfg.num_classes}")
            return ds
        print(f"[config] no dataset found under {cfg.data_dir!r}; "
              f"falling back to synthetic data", flush=True)
    return SyntheticImageDataset(cfg.dataset_size, cfg.image_size,
                                 num_classes=cfg.num_classes, seed=cfg.seed)


def _token_dataset(cfg: ExperimentConfig, vocab_size: int):
    """Real pre-tokenized corpus when --data_dir holds a
    ``{split}_tokens.npy`` (1-D stream or [n, seq+1] windows, memory-mapped
    through the native gather), synthetic fallback otherwise — the LM
    analog of _image_dataset."""
    from pytorchdistributed_tpu.data import SyntheticTokenDataset
    from pytorchdistributed_tpu.data.files import load_tokens

    if cfg.data_dir:
        ds = load_tokens(cfg.data_dir, cfg.seq_len)
        if ds is not None:
            if ds.vocab_size > vocab_size:
                hint = (" (the top id is reserved as [MASK] for BERT's "
                        "dynamic masking — remap it in the corpus)"
                        if cfg.model == "bert" else "")
                raise ValueError(
                    f"--data_dir corpus has token ids up to "
                    f"{ds.vocab_size - 1} but this config accepts data ids "
                    f"< {vocab_size}{hint}")
            return ds
        print(f"[config] no {{split}}_tokens.npy under {cfg.data_dir!r}; "
              f"falling back to synthetic data", flush=True)
    return SyntheticTokenDataset(cfg.dataset_size, cfg.seq_len,
                                 vocab_size, cfg.seed)


def build(cfg: ExperimentConfig):
    """(model, optimizer, loss_fn, mesh, dataset) from a config. Imports jax
    lazily so select_backend can act first. ``strategy="auto"`` runs the
    memory planner (parallel/auto.py — the device_map="auto" analog) and
    rewrites strategy + mesh axes from its plan."""
    from pytorchdistributed_tpu.runtime.mesh import MeshConfig, create_mesh

    if cfg.strategy == "auto":
        cfg = _auto_place(cfg)
    model, loss, ds = _build_model(cfg)
    mesh = create_mesh(MeshConfig(
        data=cfg.data, fsdp=cfg.fsdp, expert=cfg.expert, tensor=cfg.tensor,
        pipe=cfg.pipe, seq=cfg.seq, num_slices=cfg.num_slices))
    return model, make_optimizer(cfg), loss, mesh, ds, cfg


def _auto_place(cfg: ExperimentConfig) -> ExperimentConfig:
    """Run the auto-shard planner against the model's real abstract params
    (a scratch instantiation — nothing is allocated) and fold its
    (strategy, mesh axes) back into the config."""
    import dataclasses as _dc

    import jax
    import numpy as np

    from pytorchdistributed_tpu.parallel.auto import auto_shard

    model, _, ds = _build_model(cfg)
    sample = ds[np.arange(min(2, len(ds)))]
    inputs = next(sample[k] for k in ("x", "image", "tokens") if k in sample)
    mem = (cfg.device_memory_gb * 2**30) if cfg.device_memory_gb else None
    plan = auto_shard(model, (inputs,), n_devices=len(jax.devices()),
                      device_memory_bytes=mem, optimizer=cfg.optimizer)
    cfg = _dc.replace(
        cfg, strategy=plan.strategy, data=plan.mesh.data,
        fsdp=plan.mesh.fsdp, tensor=plan.mesh.tensor, pipe=plan.mesh.pipe)
    if plan.mesh.pipe > 1:
        cfg = _dc.replace(cfg, pipeline_microbatches=max(
            cfg.pipeline_microbatches, 2 * plan.mesh.pipe))
    return cfg


def make_lr_schedule(cfg: ExperimentConfig):
    """Scalar or optax schedule: linear warmup to the peak learning_rate
    over warmup_steps, then the configured decay (every BASELINE config past
    the smoke test trains with warmup+decay in practice)."""
    import optax

    lr, w = cfg.learning_rate, cfg.warmup_steps
    if cfg.lr_schedule == "constant":
        if w == 0:
            return lr
        return optax.schedules.warmup_constant_schedule(0.0, lr, w)
    if cfg.lr_schedule == "cosine":
        return optax.schedules.warmup_cosine_decay_schedule(
            0.0, lr, w, decay_steps=cfg.decay_steps, end_value=cfg.lr_end)
    if cfg.lr_schedule == "linear":
        warm = optax.schedules.linear_schedule(0.0, lr, max(w, 1))
        decay = optax.schedules.linear_schedule(
            lr, cfg.lr_end, max(cfg.decay_steps - w, 1))
        return optax.schedules.join_schedules([warm, decay], [w])
    raise ValueError(f"unknown lr_schedule {cfg.lr_schedule!r} "
                     "(constant | cosine | linear)")


def decay_mask(params):
    """Standard transformer weight-decay mask: decay matrices (kernels and
    embedding tables, ndim >= 2), never biases or norm scales (ndim <= 1) —
    decaying norm scales toward zero actively hurts. Shape-based so it
    works for every model family without name lists."""
    import jax

    return jax.tree.map(lambda p: getattr(p, "ndim", 0) >= 2, params)


def make_optimizer(cfg: ExperimentConfig):
    """Optimizer chain: [global-norm clip →] adamw/sgd/adafactor with the
    schedule; adamw's weight decay is masked to matrices only."""
    import optax

    lr = make_lr_schedule(cfg)
    if cfg.optimizer == "adamw":
        opt = optax.adamw(lr, weight_decay=cfg.weight_decay,
                          mask=decay_mask)
    elif cfg.optimizer == "sgd":
        opt = optax.sgd(lr, momentum=0.9)
    elif cfg.optimizer == "adafactor":
        # the memory-factored choice: second moment stored as row/col
        # factors — what lets 1B+ models train on one 16G chip (bench.py
        # llama1b)
        opt = optax.adafactor(lr)
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    if cfg.grad_clip_norm > 0:
        opt = optax.chain(optax.clip_by_global_norm(cfg.grad_clip_norm), opt)
    return opt


def make_trainer(cfg: ExperimentConfig):
    """Fully-wired Trainer + DataLoader for a config."""
    from pytorchdistributed_tpu.data import DataLoader
    from pytorchdistributed_tpu.parallel.precision import Policy
    from pytorchdistributed_tpu.training import Trainer

    model, opt, loss, mesh, ds, cfg = build(cfg)
    loader = DataLoader(ds, batch_size=cfg.batch_size, seed=cfg.seed)
    if cfg.quant == "int8":
        precision = Policy.int8()
    elif cfg.quant == "int8_fwd":
        precision = Policy.int8_fwd()
    else:
        precision = Policy.bf16() if cfg.bf16 else Policy.full()
    trainer = Trainer(
        model, opt, loss, mesh=mesh, strategy=cfg.strategy,
        precision=precision,
        log_every=cfg.log_every,
        checkpoint_dir=cfg.checkpoint_dir or None,
        checkpoint_every_steps=cfg.checkpoint_every_steps,
        watchdog=cfg.watchdog,
        profile_dir=cfg.profile_dir or None,
        metrics_file=cfg.metrics_file or None,
        accum_steps=cfg.accum_steps,
        overlap=cfg.overlap,
        diagnostics=cfg.diagnostics or None,
    )
    return trainer, loader
