"""Config / flag system (SURVEY.md §5: "dataclass configs + CLI overrides;
a --backend/mesh flag selecting {cpu-sim, single-TPU, pod}" — the north
star's "entrypoints select the TPU backend via a flag").

The reference's whole config surface is two argparse flags
(--max_epochs/--batch_size, ddp_gpus.py:88-92) with topology implied by
`torch.cuda.device_count()`. Here one dataclass covers model choice,
parallelism axes, precision and training hyperparameters; any field is
overridable from the CLI (`--field value`), and `PRESETS` carries the five
BASELINE.json benchmark configs.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
from typing import Any


@dataclasses.dataclass
class ExperimentConfig:
    # model
    model: str = "gpt2"            # gpt2 | bert | vit | resnet18 | resnet50 | mlp
    model_size: str = "test"       # per-family size preset
    attention: str = "dense"       # dense | pallas | ring | ulysses
    remat: bool = False
    # parallelism (mesh axis sizes; -1 = absorb remaining devices)
    strategy: str = "dp"           # dp | fsdp | tp | tp_fsdp
    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    pipe: int = 1
    seq: int = 1
    num_slices: int = 1
    pipeline_microbatches: int = 1
    pp_schedule: str = "gpipe"     # gpipe | 1f1b (transformer models)
    expert: int = 1                # mesh axis for expert parallelism
    moe_experts: int = 0           # >0: Switch-MoE MLPs (transformer models)
    # precision
    bf16: bool = True
    # training
    max_epochs: int = 1
    batch_size: int = 32           # per-process
    learning_rate: float = 1e-3
    optimizer: str = "adamw"       # adamw | sgd
    # LR schedule: peak = learning_rate, linear warmup over warmup_steps,
    # then constant / cosine / linear decay to lr_end over decay_steps.
    lr_schedule: str = "constant"  # constant | cosine | linear
    warmup_steps: int = 0
    decay_steps: int = 10_000      # decay horizon (cosine/linear)
    lr_end: float = 0.0
    grad_clip_norm: float = 0.0    # clip_by_global_norm; 0 = off
    seed: int = 0
    # data shapes (synthetic datasets)
    dataset_size: int = 2048
    seq_len: int = 128
    image_size: int = 32
    num_classes: int = 10
    # infra
    backend: str = "auto"          # auto | tpu | cpu-sim<N>
    checkpoint_dir: str = ""
    checkpoint_every_steps: int = 0
    resume: bool = False
    log_every: int = 10
    profile_dir: str = ""          # capture a jax.profiler trace here
    watchdog: bool = True          # NaN/Inf watchdog at log cadence


# The five BASELINE.json benchmark configs, smallest to largest.
PRESETS: dict[str, dict[str, Any]] = {
    # configs[0]: ResNet-18 / CIFAR-10 CPU smoke (the "gloo smoke" analog)
    "resnet18_cifar_smoke": dict(
        model="resnet18", backend="cpu-sim8", image_size=32, num_classes=10,
        strategy="dp", batch_size=32, bf16=False),
    # configs[1]: ResNet-50 / ImageNet multi-process DP
    "resnet50_imagenet_dp": dict(
        model="resnet50", image_size=224, num_classes=1000, strategy="dp",
        batch_size=64),
    # configs[2]: BERT-base MLM, bf16
    "bert_base_mlm": dict(
        model="bert", model_size="base", seq_len=512, strategy="dp",
        batch_size=16, bf16=True),
    # configs[3]: GPT-2-medium FSDP + activation checkpointing
    "gpt2_medium_fsdp": dict(
        model="gpt2", model_size="medium", seq_len=1024, strategy="fsdp",
        data=1, fsdp=-1, remat=True, batch_size=8),
    # configs[4]: ViT-L/16 multi-host DP across pod slices
    "vit_l16_multihost": dict(
        model="vit", model_size="large", image_size=224, num_classes=1000,
        strategy="dp", num_slices=2, batch_size=32),
}


def select_backend(backend: str) -> None:
    """Apply the --backend flag. MUST run before the first JAX backend
    initialization (any jax.devices() call)."""
    import re

    if backend == "auto":
        return
    if backend == "tpu":
        os.environ.pop("JAX_PLATFORMS", None)
        try:
            import jax
            # jax may already be imported with a platform baked into its
            # config (the package __init__ re-asserts env) — reset to
            # autodetect, which picks the TPU plugin when present
            jax.config.update("jax_platforms", None)
        except ImportError:
            pass
        return
    if backend.startswith("cpu-sim"):
        n = int(backend[len("cpu-sim"):] or "8")
        os.environ["JAX_PLATFORMS"] = "cpu"
        flags = os.environ.get("XLA_FLAGS", "")
        # replace (not keep) any pre-existing count: the explicit backend
        # request wins over inherited env
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       flags)
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={n}".strip())
        try:
            import jax
            jax.config.update("jax_platforms", "cpu")
        except ImportError:
            pass
        return
    raise ValueError(f"unknown backend {backend!r} "
                     "(use auto | tpu | cpu-sim<N>)")


def parse_cli(argv=None) -> ExperimentConfig:
    """Every dataclass field becomes a --flag; --preset applies a BASELINE
    config first, explicit flags override it."""
    parser = argparse.ArgumentParser(description="tpu-distributed training")
    parser.add_argument("--preset", choices=sorted(PRESETS), default=None)
    for f in dataclasses.fields(ExperimentConfig):
        if f.type == "bool":
            parser.add_argument(f"--{f.name}", type=lambda s: s.lower() in
                                ("1", "true", "yes"), default=None,
                                metavar="BOOL")
        else:
            parser.add_argument(f"--{f.name}",
                                type=type(f.default), default=None)
    ns = parser.parse_args(argv)
    values: dict[str, Any] = {}
    if ns.preset:
        values.update(PRESETS[ns.preset])
    for f in dataclasses.fields(ExperimentConfig):
        v = getattr(ns, f.name)
        if v is not None:
            values[f.name] = v
    return ExperimentConfig(**values)


def build(cfg: ExperimentConfig):
    """(model, optimizer, loss_fn, mesh, dataset) from a config. Imports jax
    lazily so select_backend can act first."""
    import jax.numpy as jnp
    import optax

    from pytorchdistributed_tpu import models
    from pytorchdistributed_tpu.data import (
        SyntheticImageDataset,
        SyntheticRegressionDataset,
        SyntheticTokenDataset,
    )
    from pytorchdistributed_tpu.runtime.mesh import MeshConfig, create_mesh
    from pytorchdistributed_tpu.training import (
        cross_entropy_loss,
        moe_token_cross_entropy_loss,
        mse_loss,
        token_cross_entropy_loss,
    )

    if cfg.moe_experts > 0:
        token_cross_entropy_loss = moe_token_cross_entropy_loss

    dtype = jnp.bfloat16 if cfg.bf16 else jnp.float32
    tkw = dict(attention=cfg.attention, remat=cfg.remat, dtype=dtype,
               pipeline_stages=cfg.pipe if cfg.pipe > 1 else 1,
               pipeline_microbatches=cfg.pipeline_microbatches,
               pp_schedule=cfg.pp_schedule, moe_experts=cfg.moe_experts)

    if cfg.model == "gpt2":
        model = models.GPT2(models.gpt2_config(
            cfg.model_size, max_seq_len=cfg.seq_len, **tkw))
        loss = token_cross_entropy_loss
        ds = SyntheticTokenDataset(cfg.dataset_size, cfg.seq_len,
                                   model.cfg.vocab_size, cfg.seed)
    elif cfg.model == "bert":
        model = models.BertMLM(models.bert_config(
            cfg.model_size, max_seq_len=cfg.seq_len, **tkw))
        loss = token_cross_entropy_loss
        ds = SyntheticTokenDataset(cfg.dataset_size, cfg.seq_len,
                                   model.cfg.vocab_size, cfg.seed)
    elif cfg.model == "vit":
        model = models.ViT(models.vit_config(
            cfg.model_size, image_size=cfg.image_size,
            num_classes=cfg.num_classes, **tkw))
        loss = cross_entropy_loss
        ds = SyntheticImageDataset(cfg.dataset_size, cfg.image_size,
                                   num_classes=cfg.num_classes, seed=cfg.seed)
    elif cfg.model in ("resnet18", "resnet50"):
        maker = models.resnet18 if cfg.model == "resnet18" else models.resnet50
        model = maker(num_classes=cfg.num_classes, dtype=dtype,
                      **(dict(cifar_stem=True) if cfg.model == "resnet18"
                         and cfg.image_size <= 64 else {}))
        loss = cross_entropy_loss
        ds = SyntheticImageDataset(cfg.dataset_size, cfg.image_size,
                                   num_classes=cfg.num_classes, seed=cfg.seed)
    elif cfg.model == "mlp":
        model = models.MLP()
        loss = mse_loss
        ds = SyntheticRegressionDataset(cfg.dataset_size, seed=cfg.seed)
    else:
        raise ValueError(f"unknown model {cfg.model!r}")

    mesh = create_mesh(MeshConfig(
        data=cfg.data, fsdp=cfg.fsdp, expert=cfg.expert, tensor=cfg.tensor,
        pipe=cfg.pipe, seq=cfg.seq, num_slices=cfg.num_slices))
    if cfg.optimizer == "adamw":
        opt = optax.adamw(cfg.learning_rate)
    elif cfg.optimizer == "sgd":
        opt = optax.sgd(cfg.learning_rate, momentum=0.9)
    else:
        raise ValueError(f"unknown optimizer {cfg.optimizer!r}")
    return model, opt, loss, mesh, ds


def make_trainer(cfg: ExperimentConfig):
    """Fully-wired Trainer + DataLoader for a config."""
    from pytorchdistributed_tpu.data import DataLoader
    from pytorchdistributed_tpu.parallel.precision import Policy
    from pytorchdistributed_tpu.training import Trainer

    model, opt, loss, mesh, ds = build(cfg)
    loader = DataLoader(ds, batch_size=cfg.batch_size, seed=cfg.seed)
    trainer = Trainer(
        model, opt, loss, mesh=mesh, strategy=cfg.strategy,
        precision=Policy.bf16() if cfg.bf16 else Policy.full(),
        log_every=cfg.log_every,
        checkpoint_dir=cfg.checkpoint_dir or None,
        checkpoint_every_steps=cfg.checkpoint_every_steps,
        watchdog=cfg.watchdog,
        profile_dir=cfg.profile_dir or None,
    )
    return trainer, loader
